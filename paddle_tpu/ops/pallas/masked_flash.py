"""Flashmask + varlen flash attention for TPU, in Pallas.

Reference analogs:
- flashmask: python/paddle/nn/functional/flash_attention.py:1299
  (flashmask_attention) backed by the flashmask params of the dynloaded
  flash-attention kernel (paddle/phi/kernels/gpu/flash_attn_kernel.cu:832).
- varlen: flash_attn_unpadded (flash_attention.py) / flash_attn varlen
  kernels — ragged packed batches.

TPU-native design (not a translation):
- Same online-softmax running state in VMEM scratch as the dense kernel
  (flash_attention.py in this package), kv innermost on the sequential grid.
- flashmask's per-column row ranges ride in as a [B, Hm, n, Sk] operand
  sliced per kv block; the keep-mask is computed on the VPU from the loaded
  index columns, and a whole (q-block, kv-block) tile is SKIPPED (no MXU
  work) when its keep-mask is empty — the block-sparsity win the reference
  gets from its flashmask CUDA kernel.
- varlen uses segment ids + in-segment positions (the TPU-idiomatic ragged
  encoding: static shapes, no dynamic slicing); blocks whose q/k segment
  ranges cannot intersect are skipped.
- backward recomputes logits from the saved LSE (flash backward), with the
  same skip conditions; wired as jax.custom_vjp.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import interpret_mode
from .flash_attention import NEG_INF, _block_sizes, _pad_seq

__all__ = ["flashmask_attention_fwd", "varlen_flash_attention_fwd"]


# --------------------------------------------------------------------------- #
# flashmask keep-mask from startend_row_indices columns
# --------------------------------------------------------------------------- #


def _flashmask_keep(idx_blk, row, col, sq, skv, causal, n):
    """keep[bq, bk] from idx columns [n, bk]; row/col are absolute indices.

    Encoding (reference flashmask_attention docstring):
      causal n=1: rows >= start masked;  causal n=2: [start, end) masked
      non-causal n=2: (LTS, UTE) -> rows >= LTS or < UTE masked
      non-causal n=4: [LTS, LTE) and [UTS, UTE) masked
    """
    keep = (col < skv) & (row < sq)
    if causal:
        keep = keep & (col <= row)  # flashmask is top-left causal (sq == skv)
        start = idx_blk[0][None, :]
        if n == 1:
            masked = row >= start
        else:
            end = idx_blk[1][None, :]
            masked = (row >= start) & (row < end)
    else:
        if n == 2:
            lts = idx_blk[0][None, :]
            ute = idx_blk[1][None, :]
            masked = (row >= lts) | (row < ute)
        else:
            lts = idx_blk[0][None, :]
            lte = idx_blk[1][None, :]
            uts = idx_blk[2][None, :]
            ute = idx_blk[3][None, :]
            masked = ((row >= lts) & (row < lte)) | ((row >= uts) & (row < ute))
    return keep & ~masked


def _fm_fwd_kernel(q_ref, kt_ref, v_ref, idx_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr,
                   *, scale, causal, n, sq, skv, bq, bk, nk):
    i = pl.program_id(2)
    j = pl.program_id(3)
    q_start = i * bq
    k_start = j * bk

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # static causal skip (strictly above the diagonal)
    needed = k_start <= q_start + bq - 1 if causal else True

    row = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    col = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    idx_blk = idx_ref[0, 0].astype(jnp.int32)  # [n, bk]
    keep = _flashmask_keep(idx_blk, row, col, sq, skv, causal, n)

    @pl.when(needed & jnp.any(keep))
    def _compute():
        # feed the MXU native dtypes (bf16 under AMP — an f32 upcast would
        # cost ~4x MXU passes); accumulation is f32 via preferred_element_type
        q = q_ref[0, 0]
        kt = kt_ref[0, 0]  # [D, bk]: MXU-native QK^T (see flash_attention.py)
        s = jax.lax.dot_general(
            q, kt, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        s = jnp.where(keep, s, NEG_INF)

        m_prev = m_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(keep, p, 0.0)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)

        v = v_ref[0, 0]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # [bq, 1] layout (trailing singleton keeps Mosaic tiling legal,
        # see flash_attention.py _fwd_kernel)
        lse_ref[0, 0] = jnp.where(l == 0.0, NEG_INF,
                                  m_scr[:, :1] + jnp.log(l_safe))


def _fm_bwd_dq_kernel(q_ref, kt_ref, vt_ref, k_ref, idx_ref, do_ref, lse_ref,
                      delta_ref, dq_ref, dq_scr,
                      *, scale, causal, n, sq, skv, bq, bk, nk):
    i = pl.program_id(2)
    j = pl.program_id(3)
    q_start = i * bq
    k_start = j * bk

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    needed = k_start <= q_start + bq - 1 if causal else True
    row = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    col = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    idx_blk = idx_ref[0, 0].astype(jnp.int32)
    keep = _flashmask_keep(idx_blk, row, col, sq, skv, causal, n)

    @pl.when(needed & jnp.any(keep))
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # [bq, 1]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, kt_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        ) * scale
        p = jnp.where(keep, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, vt_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _fm_bwd_dkv_kernel(q_ref, kt_ref, vt_ref, idx_ref, do_ref, lse_ref,
                       delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                       *, scale, causal, n, sq, skv, bq, bk, nq):
    j = pl.program_id(2)  # kv block
    i = pl.program_id(3)  # q block
    q_start = i * bq
    k_start = j * bk

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    needed = q_start + bq - 1 >= k_start if causal else True
    row = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    col = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    idx_blk = idx_ref[0, 0].astype(jnp.int32)
    keep = _flashmask_keep(idx_blk, row, col, sq, skv, causal, n)

    @pl.when(needed & jnp.any(keep))
    def _compute():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # [bq, 1]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, kt_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        ) * scale
        p = jnp.where(keep, jnp.exp(s - lse), 0.0)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, vt_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _fm_specs(B, H, Hm, Hkv, n, bq, bk, D):
    """[q, kT, v, idx] input specs (K rides TRANSPOSED [B,Hkv,D,S] so the
    QK^T contraction is MXU-native — see flash_attention.py)."""
    group = H // Hkv
    gm = H // Hm
    return [
        pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, D, bk), lambda b, h, i, j, g=group: (b, h // g, 0, j)),
        pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j, g=group: (b, h // g, j, 0)),
        pl.BlockSpec((1, 1, n, bk), lambda b, h, i, j, g=gm: (b, h // g, 0, j)),
    ]


def _fm_fwd(q, k, v, idx, scale, causal, sq, skv, bq, bk):
    B, H, Sqp, D = q.shape
    _, Hkv, Skvp, _ = k.shape
    Hm, n = idx.shape[1], idx.shape[2]
    nq, nk = Sqp // bq, Skvp // bk

    kernel = functools.partial(
        _fm_fwd_kernel, scale=scale, causal=causal, n=n, sq=sq, skv=skv,
        bq=bq, bk=bk, nk=nk)
    return pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=_fm_specs(B, H, Hm, Hkv, n, bq, bk, D),
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sqp, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sqp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(q, jnp.swapaxes(k, 2, 3), v, idx)


def _fm_bwd(scale, causal, sq, skv, residuals, dout, bq, bk):
    # (bq, bk) are the FORWARD's block sizes threaded through the custom-VJP
    # statics — recomputing here could diverge (env override changing
    # mid-run) and leave bwd grid rows unwritten
    q, k, v, idx, out, lse = residuals
    B, H, Sqp, D = q.shape
    _, Hkv, Skvp, _ = k.shape
    Hm, n = idx.shape[1], idx.shape[2]
    nq, nk = Sqp // bq, Skvp // bk
    group = H // Hkv

    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B, H, Sqp, 1] like lse
    kt = jnp.swapaxes(k, 2, 3)  # [B, Hkv, D, Skv]: MXU-native recomputes
    vt = jnp.swapaxes(v, 2, 3)
    gm = H // Hm

    dq = pl.pallas_call(
        functools.partial(_fm_bwd_dq_kernel, scale=scale, causal=causal, n=n,
                          sq=sq, skv=skv, bq=bq, bk=bk, nk=nk),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, D, bk), lambda b, h, i, j, g=group: (b, h // g, 0, j)),
            pl.BlockSpec((1, 1, D, bk), lambda b, h, i, j, g=group: (b, h // g, 0, j)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j, g=group: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, n, bk), lambda b, h, i, j, g=gm: (b, h // g, 0, j)),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Sqp, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret_mode(),
    )(q, kt, vt, k, idx, dout, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_fm_bwd_dkv_kernel, scale=scale, causal=causal, n=n,
                          sq=sq, skv=skv, bq=bq, bk=bk, nq=nq),
        grid=(B, H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, D, bk), lambda b, h, j, i, g=group: (b, h // g, 0, j)),
            pl.BlockSpec((1, 1, D, bk), lambda b, h, j, i, g=group: (b, h // g, 0, j)),
            pl.BlockSpec((1, 1, n, bk), lambda b, h, j, i, g=gm: (b, h // g, 0, j)),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Skvp, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, Skvp, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(q, kt, vt, idx, dout, lse, delta)

    if group > 1:
        dk = dk.reshape(B, Hkv, group, Skvp, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, group, Skvp, D).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flashmask(q, k, v, idx, causal, scale, bq, bk):
    out, _ = _flashmask_fwd_res(q, k, v, idx, causal, scale, bq, bk)
    return out


def _flashmask_fwd_res(q, k, v, idx, causal, scale, bq, bk):
    sq, skv = q.shape[2], k.shape[2]
    qp = _pad_seq(q, bq)
    kp = _pad_seq(k, bk)
    vp = _pad_seq(v, bk)
    pad_k = kp.shape[2] - skv
    # padded key columns are dropped by the (col < skv) term in the keep mask,
    # so the pad value for idx does not matter
    idxp = jnp.pad(idx, ((0, 0), (0, 0), (0, 0), (0, pad_k)))
    out, lse = _fm_fwd(qp, kp, vp, idxp, scale, causal, sq, skv, bq, bk)
    return out[:, :, :sq], (qp, kp, vp, idxp, out, lse)


def _flashmask_vjp_fwd(q, k, v, idx, causal, scale, bq, bk):
    out, res = _flashmask_fwd_res(q, k, v, idx, causal, scale, bq, bk)
    return out, (res, q.shape[2], k.shape[2])


def _flashmask_vjp_bwd(causal, scale, bq, bk, saved, dout):
    res, sq, skv = saved
    qp = res[0]
    dop = jnp.pad(dout, ((0, 0), (0, 0), (0, qp.shape[2] - sq), (0, 0)))
    dq, dk, dv = _fm_bwd(scale, causal, sq, skv, res, dop, bq, bk)
    return dq[:, :, :sq], dk[:, :, :skv], dv[:, :, :skv], None


_flashmask.defvjp(_flashmask_vjp_fwd, _flashmask_vjp_bwd)


def _tuned_blocks_fm(q, k, v, idx, causal, scale):
    """Forward block sizes for the flashmask kernel ([B,H,S,D] layout),
    autotuned per signature when PADDLE_TPU_AUTOTUNE=1 — previously this
    kernel ran fixed _block_sizes defaults and the tuning env var silently
    did nothing for it. Under a jit trace only the cache is consulted
    (allow_measure=False); misses are counted as fallbacks and warned."""
    from .autotune import pick_block_sizes

    sq, skv = q.shape[2], k.shape[2]
    default = _block_sizes(sq, skv, d=q.shape[-1])

    def run_with(bq, bk):
        qp = _pad_seq(q, bq)
        kp = _pad_seq(k, bk)
        vp = _pad_seq(v, bk)
        idxp = jnp.pad(idx, ((0, 0), (0, 0), (0, 0),
                             (0, kp.shape[2] - skv)))
        out, _ = _fm_fwd(qp, kp, vp, idxp, scale, causal, sq, skv, bq, bk)
        jax.device_get(out.ravel()[0:1])  # real fetch, see flash tuner

    concrete = not any(isinstance(x, jax.core.Tracer)
                       for x in (q, k, v, idx))
    return pick_block_sizes(
        "flashmask_fwd", sq, skv, default, run_with, allow_measure=concrete,
        signature=(q.shape[0], q.shape[1], k.shape[1], idx.shape[1],
                   idx.shape[2], q.shape[-1], str(q.dtype), bool(causal)))


def flashmask_attention_fwd(q, k, v, startend_row_indices, causal=True,
                            scale=None):
    """Paddle-layout entry: q [B,Sq,H,D], k/v [B,Skv,Hkv,D],
    startend_row_indices [B,Hm,Skv,n] -> [B,Sq,H,D]. Differentiable."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    idx = jnp.moveaxis(startend_row_indices.astype(jnp.int32), 2, 3)  # [B,Hm,n,Sk]
    bq, bk = _tuned_blocks_fm(qt, kt, vt, idx, causal, scale)
    out = _flashmask(qt, kt, vt, idx, causal, scale, bq, bk)
    return jnp.swapaxes(out, 1, 2)


# --------------------------------------------------------------------------- #
# varlen (packed ragged batches, segment-id encoding)
# --------------------------------------------------------------------------- #


def _vl_keep(sq_blk, sk_blk, pq_blk, pk_blk, causal, tq, tk, q_start, k_start,
             bq, bk):
    """sq/pq ride as [bq, 1] columns, sk/pk as [1, bk] rows (2-D layouts —
    1-D s32 operands trip the XLA-vs-Mosaic tiling mismatch on real TPUs);
    plain broadcasting then forms the [bq, bk] mask."""
    row = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    col = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    keep = (row < tq) & (col < tk)
    keep = keep & (sq_blk == sk_blk)
    if causal:
        keep = keep & (pq_blk >= pk_blk)
    return keep


def _vl_fwd_kernel(q_ref, k_ref, v_ref, sq_ref, sk_ref, pq_ref, pk_ref,
                   o_ref, lse_ref, m_scr, l_scr, acc_scr,
                   *, scale, causal, tq, tk, bq, bk, nk):
    i = pl.program_id(1)
    j = pl.program_id(2)
    q_start = i * bq
    k_start = j * bk

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    keep = _vl_keep(sq_ref[...].astype(jnp.int32), sk_ref[...].astype(jnp.int32),
                    pq_ref[...].astype(jnp.int32), pk_ref[...].astype(jnp.int32),
                    causal, tq, tk, q_start, k_start, bq, bk)

    @pl.when(jnp.any(keep))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        s = jnp.where(keep, s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(keep, jnp.exp(s - m_new), 0.0)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(l == 0.0, NEG_INF,
                               m_scr[:, :1] + jnp.log(l_safe))


def _vl_bwd_dq_kernel(q_ref, k_ref, v_ref, sq_ref, sk_ref, pq_ref, pk_ref,
                      do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
                      *, scale, causal, tq, tk, bq, bk, nk):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    keep = _vl_keep(sq_ref[...].astype(jnp.int32), sk_ref[...].astype(jnp.int32),
                    pq_ref[...].astype(jnp.int32), pk_ref[...].astype(jnp.int32),
                    causal, tq, tk, i * bq, j * bk, bq, bk)

    @pl.when(jnp.any(keep))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]  # [bq, 1]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        p = jnp.where(keep, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _vl_bwd_dkv_kernel(q_ref, k_ref, v_ref, sq_ref, sk_ref, pq_ref, pk_ref,
                       do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                       dk_scr, dv_scr,
                       *, scale, causal, tq, tk, bq, bk, nq):
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    keep = _vl_keep(sq_ref[...].astype(jnp.int32), sk_ref[...].astype(jnp.int32),
                    pq_ref[...].astype(jnp.int32), pk_ref[...].astype(jnp.int32),
                    causal, tq, tk, i * bq, j * bk, bq, bk)

    @pl.when(jnp.any(keep))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]  # [bq, 1]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        p = jnp.where(keep, jnp.exp(s - lse), 0.0)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _pad_tokens(x, block):
    pad = (-x.shape[1]) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _pad_vec(x, block, fill):
    pad = (-x.shape[0]) % block
    if pad:
        x = jnp.pad(x, (0, pad), constant_values=fill)
    return x


def _vl_specs(bq, bk, D, group, transpose_grid=False):
    if transpose_grid:  # grid (H, nk, nq)
        qm = lambda h, j, i: (h, i, 0)
        km = lambda h, j, i, g=group: (h // g, j, 0)
        sqm = lambda h, j, i: (i, 0)
        skm = lambda h, j, i: (0, j)
    else:  # grid (H, nq, nk)
        qm = lambda h, i, j: (h, i, 0)
        km = lambda h, i, j, g=group: (h // g, j, 0)
        sqm = lambda h, i, j: (i, 0)
        skm = lambda h, i, j: (0, j)
    return [
        pl.BlockSpec((1, bq, D), qm),
        pl.BlockSpec((1, bk, D), km),
        pl.BlockSpec((1, bk, D), km),
        pl.BlockSpec((bq, 1), sqm),
        pl.BlockSpec((1, bk), skm),
        pl.BlockSpec((bq, 1), sqm),
        pl.BlockSpec((1, bk), skm),
    ]


def _vl_fwd(q, k, v, seg_q, seg_k, pos_q, pos_k, scale, causal, tq, tk,
            bq, bk):
    H, Tqp, D = q.shape
    Hkv, Tkp, _ = k.shape
    nq, nk = Tqp // bq, Tkp // bk
    group = H // Hkv
    return pl.pallas_call(
        functools.partial(_vl_fwd_kernel, scale=scale, causal=causal,
                          tq=tq, tk=tk, bq=bq, bk=bk, nk=nk),
        grid=(H, nq, nk),
        in_specs=_vl_specs(bq, bk, D, group),
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, i, j: (h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((H, Tqp, D), q.dtype),
            jax.ShapeDtypeStruct((H, Tqp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(q, k, v, seg_q, seg_k, pos_q, pos_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _varlen(q, k, v, seg_q, seg_k, pos_q, pos_k, causal, scale, bq, bk):
    out, _ = _varlen_fwd_res(q, k, v, seg_q, seg_k, pos_q, pos_k, causal,
                             scale, bq, bk)
    return out


def _varlen_fwd_res(q, k, v, seg_q, seg_k, pos_q, pos_k, causal, scale,
                    bq, bk):
    tq, tk = q.shape[1], k.shape[1]
    qp = _pad_tokens(q, bq)
    kp = _pad_tokens(k, bk)
    vp = _pad_tokens(v, bk)
    # pad segments with distinct sentinels so padding never matches;
    # q-side metadata rides as [Tq, 1] columns, k-side as [1, Tk] rows
    sqp = _pad_vec(seg_q.astype(jnp.int32), bq, -1)[:, None]
    skp = _pad_vec(seg_k.astype(jnp.int32), bk, -2)[None, :]
    pqp = _pad_vec(pos_q.astype(jnp.int32), bq, 0)[:, None]
    pkp = _pad_vec(pos_k.astype(jnp.int32), bk, 0)[None, :]
    out, lse = _vl_fwd(qp, kp, vp, sqp, skp, pqp, pkp, scale, causal, tq,
                       tk, bq, bk)
    return out[:, :tq], (qp, kp, vp, sqp, skp, pqp, pkp, out, lse)


def _varlen_vjp_fwd(q, k, v, seg_q, seg_k, pos_q, pos_k, causal, scale,
                    bq, bk):
    out, res = _varlen_fwd_res(q, k, v, seg_q, seg_k, pos_q, pos_k, causal,
                               scale, bq, bk)
    return out, (res, q.shape[1], k.shape[1])


def _varlen_vjp_bwd(causal, scale, bq, bk, saved, dout):
    # forward's block sizes arrive as custom-VJP statics — never recomputed
    (qp, kp, vp, sqp, skp, pqp, pkp, outp, lse), tq, tk = saved
    H, Tqp, D = qp.shape
    Hkv, Tkp, _ = kp.shape
    nq, nk = Tqp // bq, Tkp // bk
    group = H // Hkv
    dop = jnp.pad(dout, ((0, 0), (0, Tqp - tq), (0, 0)))
    delta = jnp.sum(dop.astype(jnp.float32) * outp.astype(jnp.float32),
                    axis=-1, keepdims=True)

    dq = pl.pallas_call(
        functools.partial(_vl_bwd_dq_kernel, scale=scale, causal=causal,
                          tq=tq, tk=tk, bq=bq, bk=bk, nk=nk),
        grid=(H, nq, nk),
        in_specs=_vl_specs(bq, bk, D, group) + [
            pl.BlockSpec((1, bq, D), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, i, j: (h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda h, i, j: (h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((H, Tqp, D), qp.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret_mode(),
    )(qp, kp, vp, sqp, skp, pqp, pkp, dop, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_vl_bwd_dkv_kernel, scale=scale, causal=causal,
                          tq=tq, tk=tk, bq=bq, bk=bk, nq=nq),
        grid=(H, nk, nq),
        in_specs=_vl_specs(bq, bk, D, group, transpose_grid=True) + [
            pl.BlockSpec((1, bq, D), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, j, i: (h, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((1, bk, D), lambda h, j, i: (h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((H, Tkp, D), jnp.float32),
            jax.ShapeDtypeStruct((H, Tkp, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(qp, kp, vp, sqp, skp, pqp, pkp, dop, lse, delta)

    if group > 1:
        dk = dk.reshape(Hkv, group, Tkp, D).sum(axis=1)
        dv = dv.reshape(Hkv, group, Tkp, D).sum(axis=1)
    return (dq[:, :tq], dk[:, :tk].astype(kp.dtype), dv[:, :tk].astype(vp.dtype),
            None, None, None, None)


_varlen.defvjp(_varlen_vjp_fwd, _varlen_vjp_bwd)


def _tuned_blocks_vl(q, k, v, seg_q, seg_k, pos_q, pos_k, causal, scale):
    """Forward block sizes for the varlen kernel ([H,T,D] packed layout),
    autotuned per signature when PADDLE_TPU_AUTOTUNE=1 (cache-only under
    trace, fallback-counted on miss — see autotune.pick_block_sizes)."""
    from .autotune import pick_block_sizes

    tq, tk = q.shape[1], k.shape[1]
    default = _block_sizes(tq, tk, d=q.shape[-1])

    def run_with(bq, bk):
        qp = _pad_tokens(q, bq)
        kp = _pad_tokens(k, bk)
        vp = _pad_tokens(v, bk)
        sqp = _pad_vec(seg_q, bq, -1)[:, None]
        skp = _pad_vec(seg_k, bk, -2)[None, :]
        pqp = _pad_vec(pos_q, bq, 0)[:, None]
        pkp = _pad_vec(pos_k, bk, 0)[None, :]
        out, _ = _vl_fwd(qp, kp, vp, sqp, skp, pqp, pkp, scale, causal, tq,
                         tk, bq, bk)
        jax.device_get(out.ravel()[0:1])  # real fetch, see flash tuner

    concrete = not any(isinstance(x, jax.core.Tracer)
                       for x in (q, k, v, seg_q, seg_k))
    return pick_block_sizes(
        "varlen_fwd", tq, tk, default, run_with, allow_measure=concrete,
        signature=(q.shape[0], k.shape[0], q.shape[-1], str(q.dtype),
                   bool(causal)))


def varlen_flash_attention_fwd(q, k, v, cu_seqlens_q, cu_seqlens_k, scale,
                               causal=False):
    """Packed varlen entry: q [Tq,H,D], k/v [Tk,Hkv,D], cu_seqlens [B+1].
    Differentiable w.r.t. q/k/v. Reference: flash_attn_unpadded."""
    Tq, Tk = q.shape[0], k.shape[0]
    cq = cu_seqlens_q.astype(jnp.int32)
    ck = cu_seqlens_k.astype(jnp.int32)
    seg_q = jnp.cumsum(jnp.zeros(Tq, jnp.int32).at[cq[1:-1]].add(1))
    seg_k = jnp.cumsum(jnp.zeros(Tk, jnp.int32).at[ck[1:-1]].add(1))
    pos_q = jnp.arange(Tq, dtype=jnp.int32) - jnp.take(cq, seg_q)
    pos_k = jnp.arange(Tk, dtype=jnp.int32) - jnp.take(ck, seg_k)
    qt = jnp.swapaxes(q, 0, 1)  # [H, T, D]
    kt = jnp.swapaxes(k, 0, 1)
    vt = jnp.swapaxes(v, 0, 1)
    bq, bk = _tuned_blocks_vl(qt, kt, vt, seg_q, seg_k, pos_q, pos_k,
                              causal, scale)
    out = _varlen(qt, kt, vt, seg_q, seg_k, pos_q, pos_k, causal, scale,
                  bq, bk)
    return jnp.swapaxes(out, 0, 1)
