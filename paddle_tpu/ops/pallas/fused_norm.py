"""Fused RMSNorm / LayerNorm for TPU, in Pallas.

Reference analogs: paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu
and gpu/rms_norm_kernel.cu (the fused_rms_norm / fused_layer_norm python
APIs) — re-designed for the TPU memory hierarchy rather than translated:

- The norm is memory-bound: the entire job is streaming each [rows, N]
  activation tile through VMEM exactly once per pass. The forward runs ONE
  fused stream per tile — f32 upcast + square/sum (or sum + square-sum for
  LayerNorm) + rsqrt + scale (+ shift) + downcast — instead of the separate
  reduce/normalize/affine passes the unfused lax path can decompose into
  between flash-attention calls (the non-attention residency the gpt3/llama
  bench rungs sit in).
- Stats are computed in f32 regardless of input dtype, like the reference
  kernels; LayerNorm variance is the two-pass (x - mean)^2 form (the
  one-pass E[x^2]-E[x]^2 cancels catastrophically in f32 for
  mean-dominated inputs) with padded lanes masked out of the centered sum.
- The rows axis is tiled by an AUTOTUNED block (autotune.pick_block_sizes,
  kernels "fused_rms_norm"/"fused_layer_norm"); the feature axis is never
  split — the row statistics need the whole row, and N*4B rows fit VMEM for
  every hidden size this repo benches (N <= ~24k at the default block).
- backward: dx is a second fused Pallas stream recomputing x_hat from the
  saved rstd (and mean) — the recompute-not-store trade, same as the flash
  backward. dweight/dbias are plain jnp row reductions (a single XLA
  reduce over an operand the backward already touches; a Pallas kernel
  would add nothing). Wired as jax.custom_vjp; the block size and
  weight/bias arity ride the nondiff statics so forward and backward can
  never disagree on tiling.

All entry points pad rows to block multiples and lanes to 128 multiples and
mask/slice the padding, so any shape works with static shapes. The
PADDLE_TPU_FUSED_NORM toggle (read by the functional dispatch, captured at
trace time) selects between these kernels and the lax composite for A/B.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import interpret_mode

__all__ = ["fused_norm_on", "rms_norm_fwd", "layer_norm_fwd"]


def fused_norm_on() -> bool:
    """PADDLE_TPU_FUSED_NORM toggle, default ON. Read once per forward
    trace by the functional dispatch (nn/functional/norm.py, incubate) and
    captured into the traced closure — like the PR-7 safe-softmax capture,
    an env flip between forward and backward tracing cannot mix paths,
    because the backward is this module's custom VJP, not a re-dispatch."""
    return os.environ.get("PADDLE_TPU_FUSED_NORM", "1") != "0"


def _pad_lanes(n):
    return max(128, -(-n // 128) * 128)


def _pad2(x, br, nl):
    r, n = x.shape
    pr, pn = (-r) % br, nl - n
    if pr or pn:
        x = jnp.pad(x, ((0, pr), (0, pn)))
    return x


def _vec_spec_and_arg(v, nl, grid_rank=1):
    """BlockSpec + operand for a per-feature vector (weight/bias): [8, Nl]
    with 8 replicated sublanes (Mosaic wants last-two block dims divisible
    by (8, 128)); kernels read row 0 and broadcast."""
    v = v.astype(jnp.float32)
    if nl > v.shape[0]:
        v = jnp.pad(v, (0, nl - v.shape[0]))
    arg = jnp.broadcast_to(v[None, :], (8, nl))
    spec = pl.BlockSpec((8, nl), lambda i: (0, 0))
    return spec, arg


def _row_block(r, nl):
    """Default rows-per-block: the largest power-of-two block whose f32
    working set (x tile + out tile + f32 temps ~ 4 copies) stays near 8MB
    with double buffering, clamped to the padded row count."""
    cap = 1024
    while cap > 8 and cap * nl * 4 * 4 > 8 * 1024 * 1024:
        cap //= 2
    return max(8, min(cap, -(-max(8, r) // 8) * 8))


def _row_candidates(r, nl, default):
    cands = {default}
    for br in (64, 128, 256, 512, 1024):
        if br <= -(-max(8, r) // 8) * 8 and br * nl * 4 * 4 <= 12 * 1024 * 1024:
            cands.add((br, nl))
    return sorted(cands)


# --------------------------------------------------------------------------- #
# forward
# --------------------------------------------------------------------------- #


def _fwd_kernel(x_ref, *rest, kind, eps, n, has_w, has_b):
    i = iter(rest)
    w_ref = next(i) if has_w else None
    b_ref = next(i) if has_b else None
    o_ref = next(i)
    rstd_ref = next(i)
    mean_ref = next(i) if kind == "ln" else None

    x = x_ref[...].astype(jnp.float32)
    inv_n = 1.0 / n
    if kind == "ln":
        # two-pass (x - mean)^2 — the E[x^2]-E[x]^2 one-pass form
        # catastrophically cancels in f32 when |mean| >> std (x ~ 1e4 puts
        # both moments at ~1e8 and their difference below f32 resolution).
        # The whole row is already in VMEM, so the second pass is free;
        # padded lanes (zeros, which would contribute mean^2 each) are
        # masked out of the centered sum — statically elided when N needs
        # no lane padding.
        mean = jnp.sum(x, axis=-1, keepdims=True) * inv_n
        centered = x - mean
        if n != x.shape[-1]:
            lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
            centered = jnp.where(lane < n, centered, 0.0)
        var = jnp.sum(centered * centered, axis=-1, keepdims=True) * inv_n
        rstd = jax.lax.rsqrt(var + eps)
        out = centered * rstd
        mean_ref[...] = mean
    else:
        var = jnp.sum(x * x, axis=-1, keepdims=True) * inv_n
        rstd = jax.lax.rsqrt(var + eps)
        out = x * rstd
    if w_ref is not None:
        out = out * w_ref[0:1, :]
    if b_ref is not None:
        out = out + b_ref[0:1, :]
    o_ref[...] = out.astype(o_ref.dtype)
    rstd_ref[...] = rstd


def _norm_fwd(x2, w, b, kind, eps, br):
    """x2: [R, N] (leading dims pre-flattened). Returns (out [R, N],
    xp [Rp, Nl], rstd [Rp, 1], mean [Rp, 1]|None) — padded residuals for
    the backward kernel."""
    r, n = x2.shape
    nl = _pad_lanes(n)
    xp = _pad2(x2, br, nl)
    rp = xp.shape[0]
    grid = (rp // br,)
    in_specs = [pl.BlockSpec((br, nl), lambda i: (i, 0))]
    args = [xp]
    for v, flag in ((w, w is not None), (b, b is not None)):
        if flag:
            spec, arg = _vec_spec_and_arg(v, nl)
            in_specs.append(spec)
            args.append(arg)
    out_specs = [
        pl.BlockSpec((br, nl), lambda i: (i, 0)),
        pl.BlockSpec((br, 1), lambda i: (i, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((rp, nl), x2.dtype),
        jax.ShapeDtypeStruct((rp, 1), jnp.float32),
    ]
    if kind == "ln":
        out_specs.append(pl.BlockSpec((br, 1), lambda i: (i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((rp, 1), jnp.float32))
    kernel = functools.partial(
        _fwd_kernel, kind=kind, eps=eps, n=n,
        has_w=w is not None, has_b=b is not None)
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret_mode(),
    )(*args)
    if kind == "ln":
        op, rstd, mean = outs
    else:
        (op, rstd), mean = outs, None
    return op[:r, :n], xp, rstd, mean


# --------------------------------------------------------------------------- #
# backward (dx kernel; dw/db are jnp row reductions)
# --------------------------------------------------------------------------- #


def _bwd_kernel(x_ref, *rest, kind, n, has_w):
    i = iter(rest)
    w_ref = next(i) if has_w else None
    dy_ref = next(i)
    rstd_ref = next(i)
    mean_ref = next(i) if kind == "ln" else None
    dx_ref = next(i)

    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    rstd = rstd_ref[...]
    g = dy * w_ref[0:1, :] if w_ref is not None else dy
    inv_n = 1.0 / n
    if kind == "ln":
        xhat = (x - mean_ref[...]) * rstd
        c1 = jnp.sum(g, axis=-1, keepdims=True) * inv_n
        c2 = jnp.sum(g * xhat, axis=-1, keepdims=True) * inv_n
        dx = rstd * (g - c1 - xhat * c2)
    else:
        xhat = x * rstd
        c = jnp.sum(g * xhat, axis=-1, keepdims=True) * inv_n
        dx = rstd * (g - xhat * c)
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _norm_bwd_dx(xp, w, dyp, rstd, mean, kind, n, br):
    rp, nl = xp.shape
    grid = (rp // br,)
    row = pl.BlockSpec((br, nl), lambda i: (i, 0))
    col = pl.BlockSpec((br, 1), lambda i: (i, 0))
    in_specs = [row]
    args = [xp]
    if w is not None:
        spec, arg = _vec_spec_and_arg(w, nl)
        in_specs.append(spec)
        args.append(arg)
    in_specs += [row, col]
    args += [dyp, rstd]
    if kind == "ln":
        in_specs.append(col)
        args.append(mean)
    kernel = functools.partial(_bwd_kernel, kind=kind, n=n,
                               has_w=w is not None)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=row,
        out_shape=jax.ShapeDtypeStruct((rp, nl), xp.dtype),
        interpret=interpret_mode(),
    )(*args)


# --------------------------------------------------------------------------- #
# custom VJP over (x, weight, bias) — absent weight/bias ride as None
# --------------------------------------------------------------------------- #


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _fused_norm(operands, kind, eps, br):
    out, _ = _fused_norm_fwd_res(operands, kind, eps, br)
    return out


def _fused_norm_fwd_res(operands, kind, eps, br):
    x, w, b = operands
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    out2, xp, rstd, mean = _norm_fwd(x2, w, b, kind, eps, br)
    # b rides the residuals only for its arity/dtype (the cotangent pytree
    # must mirror the primal operands)
    return out2.reshape(shape), (xp, w, b, rstd, mean)


def _fused_norm_vjp_fwd(operands, kind, eps, br):
    return _fused_norm_fwd_res(operands, kind, eps, br)


def _fused_norm_vjp_bwd(kind, eps, br, res, dout):
    # (br, kind, weight arity) are the FORWARD's statics — recomputing the
    # block size here could pad the grid differently and leave rows unwritten
    xp, w, b, rstd, mean = res
    shape = dout.shape
    n = shape[-1]
    r = 1
    for d in shape[:-1]:
        r *= d
    dy2 = dout.reshape(r, n)
    dyp = _pad2(dy2, br, xp.shape[1])
    dxp = _norm_bwd_dx(xp, w, dyp, rstd, mean, kind, n, br)
    dx = dxp[:r, :n].reshape(shape)
    dw = db = None
    if w is not None:
        x32 = xp[:r, :n].astype(jnp.float32)
        dy32 = dy2.astype(jnp.float32)
        rs = rstd[:r]
        xhat = (x32 - mean[:r]) * rs if kind == "ln" else x32 * rs
        dw = jnp.sum(dy32 * xhat, axis=0).astype(w.dtype)
    if b is not None:
        db = jnp.sum(dy2.astype(jnp.float32), axis=0).astype(b.dtype)
    return ((dx, dw, db),)


_fused_norm.defvjp(_fused_norm_vjp_fwd, _fused_norm_vjp_bwd)


def _tuned_row_block(kernel_name, x2, w, b, kind, eps):
    """Row-block size for this signature, autotuned when
    PADDLE_TPU_AUTOTUNE=1 (reference: phi/kernels/autotune cache). The
    feature width is pinned (row stats need whole rows), so candidates vary
    only the row block; the recorded tile is (rows, padded_lanes)."""
    from .autotune import pick_block_sizes

    r, n = x2.shape
    nl = _pad_lanes(n)
    default = (_row_block(r, nl), nl)

    def run_with(br, _bk):
        out, _, _, _ = _norm_fwd(x2, w, b, kind, eps, br)
        # REAL device->host fetch (see flash_attention._tuned_blocks: through
        # the axon tunnel block_until_ready returns early)
        jax.device_get(out.ravel()[0:1])

    concrete = not any(
        isinstance(v, jax.core.Tracer) for v in (x2, w, b) if v is not None)
    br, _ = pick_block_sizes(
        kernel_name, r, nl, default, run_with, allow_measure=concrete,
        signature=(n, str(x2.dtype), w is not None, b is not None),
        candidates=_row_candidates(r, nl, default))
    return br


def rms_norm_fwd(x, weight=None, epsilon=1e-6, bias=None):
    """Fused RMSNorm: x [..., N] normalized over the last axis, f32 stats,
    optional weight/bias [N]. Differentiable (custom VJP, fused dx kernel).
    Reference API: python/paddle/incubate/nn/functional/fused_rms_norm.py."""
    x2 = x.reshape(-1, x.shape[-1])
    br = _tuned_row_block("fused_rms_norm", x2, weight, bias, "rms",
                          float(epsilon))
    return _fused_norm((x, weight, bias), "rms", float(epsilon), br)


def layer_norm_fwd(x, weight=None, bias=None, epsilon=1e-5):
    """Fused LayerNorm over the last axis (two-pass masked (x-mean)^2
    variance, f32 stats), optional weight/bias [N]. Differentiable (custom
    VJP, fused dx kernel). Reference: fusion/gpu/fused_layernorm_kernel.cu."""
    x2 = x.reshape(-1, x.shape[-1])
    br = _tuned_row_block("fused_layer_norm", x2, weight, bias, "ln",
                          float(epsilon))
    return _fused_norm((x, weight, bias), "ln", float(epsilon), br)
