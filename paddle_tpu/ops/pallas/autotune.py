"""Kernel block-size autotuning (reference: paddle/phi/kernels/autotune/ —
cache.h AutoTuneCache keyed per kernel+shape, auto_tune_base.h measuring
candidate configs at first use).

TPU formulation: the tunable is the Pallas block shape (bq, bk). Enabled via
PADDLE_TPU_AUTOTUNE=1, the first call of a kernel signature measures each
legal candidate with a compiled micro-run and caches the winner — in-process
and on disk (~/.cache/paddle_tpu_autotune.json, keyed by device kind AND
jaxlib version, so a Mosaic upgrade invalidates stale winners). Disabled
(default) or under the interpreter it returns the caller's default
immediately; measurement failures fall back the same way, so tuning can
never break a run.

Every decision — tuned or default — is recorded for telemetry:

- `chosen_tiles()` returns the last tile picked per kernel plus per-kernel
  hit/miss/fallback counts; the StepTimeline folds it into each step record
  and bench.py into the perf line (`autotuned_tiles=`).
- a `pallas_autotune_{hits,misses,fallbacks}_total{kernel=}` counter family
  lands in the observability registry. A *fallback* is the silent failure
  mode this PR makes visible: tuning enabled, lookup under trace
  (allow_measure=False, measurement impossible inside jit), cache miss —
  the kernel runs defaults even though the user asked for tuning. The first
  fallback per key also emits a RuntimeWarning naming the key so "tuning
  never ran" shows up in logs, not just dashboards.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings

__all__ = ["autotune_enabled", "pick_block_sizes", "cache_path",
           "clear_cache", "chosen_tiles"]

_lock = threading.Lock()
_memory: dict = {}
_disk_loaded = [False]
# telemetry: last tile picked per kernel + decision counts (plain dicts —
# mutated under the GIL only, read by chosen_tiles() snapshots)
_chosen: dict = {}
_stats: dict = {}
_warned: set = set()
_metric_handles = None


def autotune_enabled() -> bool:
    from . import interpret_mode

    return (os.environ.get("PADDLE_TPU_AUTOTUNE", "0") == "1"
            and not interpret_mode())


def cache_path():
    d = os.environ.get("PADDLE_TPU_AUTOTUNE_DIR",
                       os.path.join(os.path.expanduser("~"), ".cache"))
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, "paddle_tpu_autotune.json")


def _device_kind():
    try:
        import jax

        return getattr(jax.devices()[0], "device_kind", "unknown")
    except Exception:
        return "unknown"


def _jaxlib_version():
    """Part of the cache key: a tuned winner reflects one Mosaic compiler's
    code generation — letting it survive a jaxlib upgrade silently pins the
    new compiler to the old compiler's tile choice."""
    try:
        import jaxlib
    except ImportError:
        return "unknown"
    return getattr(jaxlib, "__version__", "unknown")


def _load_disk():
    if _disk_loaded[0]:
        return
    _disk_loaded[0] = True
    try:
        with open(cache_path()) as f:
            _memory.update(json.load(f))
    except Exception:
        pass


def _store_disk():
    """Merge-then-atomic-rename: concurrent tuners must not clobber each
    other's winners, and an interrupt must not truncate the shared file."""
    try:
        path = cache_path()
        merged = {}
        try:
            with open(path) as f:
                merged.update(json.load(f))
        except Exception:
            pass
        merged.update(_memory)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(merged, f)
        os.replace(tmp, path)
        _memory.update(merged)
    except Exception:
        pass


def clear_cache():
    with _lock:
        _memory.clear()
        _disk_loaded[0] = False
        _chosen.clear()
        _stats.clear()
        _warned.clear()
        try:
            os.remove(cache_path())
        except OSError:
            pass


def _candidates(sq, skv, default):
    """Legal (bq, bk) choices: block divides (or covers) the padded seq,
    bounded so the f32 logits tile [bq, bk] stays well under VMEM."""
    cands = {default}
    for bq in (128, 256, 512, 1024):
        for bk in (128, 256, 512, 1024):
            if bq * bk > 1024 * 1024:
                # f32 logits tile caps at 4MB — round-5 on-chip sweeps show
                # the large tiles (512x1024, 1024x1024) winning at long seq
                continue
            if sq >= bq and skv >= bk:
                cands.add((bq, bk))
    return sorted(cands)


def _counters():
    """(hits, misses, fallbacks) registry handles, registry-swap safe."""
    global _metric_handles
    if _metric_handles is None:
        from ...observability.metrics import HandleCache

        _metric_handles = HandleCache(lambda reg: (
            reg.counter("pallas_autotune_hits_total",
                        "autotune cache hits (tuned tile used)",
                        labelnames=("kernel",)),
            reg.counter("pallas_autotune_misses_total",
                        "autotune cache misses that ran a measurement sweep",
                        labelnames=("kernel",)),
            reg.counter("pallas_autotune_fallbacks_total",
                        "autotune enabled but lookup missed under trace — "
                        "kernel ran DEFAULT tiles, tuning never happened",
                        labelnames=("kernel",)),
        ))
    return _metric_handles.get()


def _stat(kernel):
    s = _stats.get(kernel)
    if s is None:
        s = _stats[kernel] = {"hits": 0, "misses": 0, "fallbacks": 0}
    return s


_KINDS = ("hits", "misses", "fallbacks")


def _bump(kind, kernel):
    """Count a tuner decision: module-local (chosen_tiles) + registry
    counter. A kernel launch must never die on telemetry — registry failure
    (e.g. a conflicting foreign declaration of the metric name) degrades to
    the module-local count."""
    _stat(kernel)[kind] += 1
    try:
        _counters()[_KINDS.index(kind)].inc(kernel=kernel)
    except Exception:  # graftlint: disable=GL003 telemetry must not break kernel dispatch; module-local count above still records the event
        pass


def _record(kernel, tile, source):
    _chosen[kernel] = {"bq": int(tile[0]), "bk": int(tile[1]),
                       "source": source}


def chosen_tiles() -> dict:
    """{kernel: {bq, bk, source, hits, misses, fallbacks}} for every Pallas
    kernel that consulted the tuner this process. `source`: "tuned" (cache
    winner), "measured" (swept this call), "fixed" (single legal candidate,
    nothing tunable at launch), "default" (tuning disabled or trace-time
    miss). The StepTimeline attaches this snapshot to each step record;
    bench.py prints it as `autotuned_tiles=`."""
    out = {}
    for kernel, tile in list(_chosen.items()):
        rec = dict(tile)
        rec.update(_stats.get(kernel, {}))
        out[kernel] = rec
    return out


def pick_block_sizes(kernel_name, sq, skv, default, run_with, reps=3,
                     allow_measure=True, signature=(), candidates=None):
    """Return the best (bq, bk) for this signature.

    `run_with(bq, bk)` must execute one full kernel invocation (compiling on
    first use) and block on the result; it is measured `reps` times per
    candidate. Key: (kernel, device kind, jaxlib version, sq, skv,
    *signature) — pass every workload dimension the timing depends on
    (batch, heads, head_dim, dtype, causal) in `signature` so a winner tuned
    for one model is never reused for a different-shaped workload. With
    allow_measure=False (inputs are tracers — measurement impossible inside
    a jit trace) only the cache is consulted; the miss is counted as a
    fallback and warned once per key. `candidates` overrides the built-in
    attention-shaped (bq, bk) grid for kernels with a different tunable
    (e.g. the fused-norm row block, where bk is pinned to the feature
    width)."""
    if not autotune_enabled():
        _record(kernel_name, default, "default")
        return default
    if candidates is not None and len(candidates) == 1:
        # nothing tunable at launch (e.g. the paged-decode tile IS the
        # pool's physical page size): record for telemetry, but never run a
        # foregone one-candidate sweep or count a fallback
        tile = tuple(candidates[0])
        _record(kernel_name, tile, "fixed")
        return tile
    sig = "|".join(str(s) for s in signature)
    key = (f"{kernel_name}|{_device_kind()}|{_jaxlib_version()}|{sq}|{skv}|"
           f"{sig}")
    with _lock:
        _load_disk()
        hit = _memory.get(key)
    if hit is not None:
        _bump("hits", kernel_name)
        _record(kernel_name, tuple(hit), "tuned")
        return tuple(hit)
    if not allow_measure:
        _bump("fallbacks", kernel_name)
        if key not in _warned:
            _warned.add(key)
            warnings.warn(
                f"PADDLE_TPU_AUTOTUNE=1 but no tuned tiles for {key!r} and "
                f"measurement is impossible under trace; running default "
                f"{default}. Prime the cache by calling the kernel's "
                f"ops.pallas entry point (flash_attention_fwd, rms_norm_fwd, "
                f"apply_fused_rope, ...) with CONCRETE arrays of this shape "
                f"first — the model-level functional dispatch always traces, "
                f"so it can only ever read the cache, never fill it "
                f"(ops/pallas/README.md, 'Autotuning').",
                RuntimeWarning, stacklevel=3)
        _record(kernel_name, default, "default")
        return default

    _bump("misses", kernel_name)
    cands = candidates if candidates is not None else _candidates(
        sq, skv, default)
    best, best_t = default, float("inf")
    for bq, bk in cands:
        try:
            run_with(bq, bk)  # compile + warm up
            t0 = time.perf_counter()
            for _ in range(reps):
                run_with(bq, bk)
            dt = (time.perf_counter() - t0) / reps
        except Exception:
            continue  # illegal tiling / OOM candidate: skip
        if dt < best_t:
            best, best_t = (bq, bk), dt
    with _lock:
        _memory[key] = list(best)
        _store_disk()
    _record(kernel_name, best, "measured")
    return best
