"""Kernel block-size autotuning (reference: paddle/phi/kernels/autotune/ —
cache.h AutoTuneCache keyed per kernel+shape, auto_tune_base.h measuring
candidate configs at first use).

TPU formulation: the tunable is the Pallas block shape (bq, bk). Enabled via
PADDLE_TPU_AUTOTUNE=1, the first call of a kernel signature measures each
legal candidate with a compiled micro-run and caches the winner — in-process
and on disk (~/.cache/paddle_tpu_autotune.json, keyed by device kind) so
later processes skip the sweep. Disabled (default) or under the interpreter
it returns the caller's default immediately; measurement failures fall back
the same way, so tuning can never break a run."""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["autotune_enabled", "pick_block_sizes", "cache_path",
           "clear_cache"]

_lock = threading.Lock()
_memory: dict = {}
_disk_loaded = [False]


def autotune_enabled() -> bool:
    from . import interpret_mode

    return (os.environ.get("PADDLE_TPU_AUTOTUNE", "0") == "1"
            and not interpret_mode())


def cache_path():
    d = os.environ.get("PADDLE_TPU_AUTOTUNE_DIR",
                       os.path.join(os.path.expanduser("~"), ".cache"))
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, "paddle_tpu_autotune.json")


def _device_kind():
    try:
        import jax

        return getattr(jax.devices()[0], "device_kind", "unknown")
    except Exception:
        return "unknown"


def _load_disk():
    if _disk_loaded[0]:
        return
    _disk_loaded[0] = True
    try:
        with open(cache_path()) as f:
            _memory.update(json.load(f))
    except Exception:
        pass


def _store_disk():
    """Merge-then-atomic-rename: concurrent tuners must not clobber each
    other's winners, and an interrupt must not truncate the shared file."""
    try:
        path = cache_path()
        merged = {}
        try:
            with open(path) as f:
                merged.update(json.load(f))
        except Exception:
            pass
        merged.update(_memory)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(merged, f)
        os.replace(tmp, path)
        _memory.update(merged)
    except Exception:
        pass


def clear_cache():
    with _lock:
        _memory.clear()
        _disk_loaded[0] = False
        try:
            os.remove(cache_path())
        except OSError:
            pass


def _candidates(sq, skv, default):
    """Legal (bq, bk) choices: block divides (or covers) the padded seq,
    bounded so the f32 logits tile [bq, bk] stays well under VMEM."""
    cands = {default}
    for bq in (128, 256, 512, 1024):
        for bk in (128, 256, 512, 1024):
            if bq * bk > 1024 * 1024:
                # f32 logits tile caps at 4MB — round-5 on-chip sweeps show
                # the large tiles (512x1024, 1024x1024) winning at long seq
                continue
            if sq >= bq and skv >= bk:
                cands.add((bq, bk))
    return sorted(cands)


def pick_block_sizes(kernel_name, sq, skv, default, run_with, reps=3,
                     allow_measure=True, signature=()):
    """Return the best (bq, bk) for this signature.

    `run_with(bq, bk)` must execute one full kernel invocation (compiling on
    first use) and block on the result; it is measured `reps` times per
    candidate. Key: (kernel, device kind, sq, skv, *signature) — pass every
    workload dimension the timing depends on (batch, heads, head_dim, dtype,
    causal) in `signature` so a winner tuned for one model is never reused
    for a different-shaped workload. With allow_measure=False (inputs are
    tracers — measurement impossible inside a jit trace) only the cache is
    consulted."""
    if not autotune_enabled():
        return default
    sig = "|".join(str(s) for s in signature)
    key = f"{kernel_name}|{_device_kind()}|{sq}|{skv}|{sig}"
    with _lock:
        _load_disk()
        hit = _memory.get(key)
    if hit is not None:
        return tuple(hit)
    if not allow_measure:
        return default

    best, best_t = default, float("inf")
    for bq, bk in _candidates(sq, skv, default):
        try:
            run_with(bq, bk)  # compile + warm up
            t0 = time.perf_counter()
            for _ in range(reps):
                run_with(bq, bk)
            dt = (time.perf_counter() - t0) / reps
        except Exception:
            continue  # illegal tiling / OOM candidate: skip
        if dt < best_t:
            best, best_t = (bq, bk), dt
    with _lock:
        _memory[key] = list(best)
        _store_disk()
    return best
