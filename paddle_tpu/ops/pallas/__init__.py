"""Pallas TPU kernels.

Each module provides a jittable, differentiable entry point plus an
`interpret` escape hatch (PADDLE_TPU_PALLAS_INTERPRET=1) so the kernels run —
and are tested — on CPU through the Pallas interpreter, the analog of the
reference testing CUDA kernels against NumPy oracles (test/legacy_test/op_test.py).
"""

import os


def interpret_mode() -> bool:
    return os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1"
