"""Fused rotary position embedding for TPU, in Pallas.

Reference analog: paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu (+
fused_rope_utils.h for the neox-vs-interleaved pairing) — re-designed for
the TPU memory hierarchy rather than translated:

- RoPE is pure elementwise traffic; the entire job is streaming q and k
  through VMEM exactly once, applying cos/sin in the same stream. The
  hazard on TPU is the HALF-ROTATION: both pairings split the head dim on
  the lane axis at non-128-aligned offsets (D/2, or even/odd lanes), which
  Mosaic cannot slice. The kernel never slices — rotate_half is expressed
  as a LANE ROLL (pltpu.roll) with the pairing's signs folded into
  precomputed full-width sin tables:

      out = x * CF + roll(x, s1) * S1 [+ roll(x, s2) * S2]

  neox  (pairs (i, i+D/2)): one roll by D/2 (its own inverse mod D),
        CF = [cos, cos], S1 = [-sin, sin].
  GPT-J (interleaved pairs (2i, 2i+1)): rolls by 1 and D-1 with
        even/odd-masked sin tables (the mask is IN the table — zero
        coefficient kills the cross-pair lanes the circular roll drags in).

- One pallas_call applies the same tables to q, k (and v when the caller
  rotates it) in a single grid sweep — the reference kernel's "one launch
  for the whole qkv group". Tables are [.., S, D] f32, tiny next to the
  activations, and ride per-sequence-block; batch-invariant tables (no
  position_ids) stay [1, S, D] and are index-mapped, not broadcast.
- The sequence axis is tiled by an AUTOTUNED block (kernel "fused_rope");
  heads and head_dim stay whole per block, so the block's last-two dims
  (H, D) are the natural Mosaic tile.
- backward: a rotation is orthogonal and linear, so the VJP is the SAME
  kernel with the sin tables negated (for both pairings the adjoint's
  shifted-table terms reduce to exactly that). No activations are saved —
  only the tables ride the residuals. Wired as jax.custom_vjp; tables get
  zero cotangents (they are position data, not parameters).

The PADDLE_TPU_FUSED_ROPE toggle (read by the functional dispatch at trace
time) selects between this kernel and the lax composite for A/B.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import interpret_mode

__all__ = ["fused_rope_on", "apply_fused_rope"]


def fused_rope_on() -> bool:
    """PADDLE_TPU_FUSED_ROPE toggle, default ON. Read once per forward trace
    by the functional dispatch and captured into the traced closure; the
    backward is this module's custom VJP, so an env flip between forward and
    backward tracing cannot mix kernel and composite gradients."""
    return os.environ.get("PADDLE_TPU_FUSED_ROPE", "1") != "0"


def _roll_lanes(x, shift):
    """Circular shift along the last (lane) axis: out[.., l] = x[.., l-shift].
    pltpu.roll is the Mosaic lane-rotate; the interpreter takes the same
    path (it lowers to jnp.roll semantics)."""
    return pltpu.roll(x, shift=shift, axis=x.ndim - 1)


def _rope_tables_full(c, s, d, interleaved):
    """Expand half-width cos/sin [.., S, D/2] into the kernel's full-width
    coefficient tables (cf, (s1, s2?)) [.., S, D] f32, signs and pair masks
    folded in (see module docstring)."""
    c = c.astype(jnp.float32)
    s = s.astype(jnp.float32)
    if interleaved:
        cf = jnp.repeat(c, 2, axis=-1)
        zero = jnp.zeros_like(s)
        # even lanes pull x[l+1] (roll d-1): coeff -sin; odd lanes 0
        sa = jnp.stack([-s, zero], axis=-1).reshape(*s.shape[:-1], d)
        # odd lanes pull x[l-1] (roll 1): coeff +sin; even lanes 0
        sb = jnp.stack([zero, s], axis=-1).reshape(*s.shape[:-1], d)
        return cf, (sa, sb), (d - 1, 1)
    cf = jnp.concatenate([c, c], axis=-1)
    s1 = jnp.concatenate([-s, s], axis=-1)
    return cf, (s1,), (d // 2,)


def _rope_kernel(*refs, nt, shifts):
    ns = len(shifts)
    cf = refs[nt][0].astype(jnp.float32)                     # [bs, D]
    sins = [refs[nt + 1 + j][0].astype(jnp.float32) for j in range(ns)]
    for t in range(nt):
        x = refs[t][0].astype(jnp.float32)                   # [bs, H, D]
        out = x * cf[:, None, :]
        for shift, sv in zip(shifts, sins):
            out = out + _roll_lanes(x, shift) * sv[:, None, :]
        o_ref = refs[nt + 1 + ns + t]
        o_ref[0] = out.astype(o_ref.dtype)


def _pad_rows(x, bs):
    pad = (-x.shape[1]) % bs
    if pad:
        width = [(0, 0)] * x.ndim
        width[1] = (0, pad)
        x = jnp.pad(x, width)
    return x


def _rope_run(tensors, cf, sins, shifts, bs):
    """tensors: tuple of [B, S, Hi, D]; cf/sins: [Bt, S, D] (Bt in {1, B})."""
    b, s = tensors[0].shape[0], tensors[0].shape[1]
    d = tensors[0].shape[-1]
    tp = [_pad_rows(t, bs) for t in tensors]
    sp = tp[0].shape[1]
    cfp = _pad_rows(cf, bs)
    sinsp = [_pad_rows(sv, bs) for sv in sins]
    bt = cf.shape[0]
    grid = (b, sp // bs)

    def tmap(bi, i, _bt=bt):
        return (bi if _bt > 1 else 0, i, 0)

    in_specs = [
        pl.BlockSpec((1, bs, t.shape[2], d), lambda bi, i: (bi, i, 0, 0))
        for t in tp
    ]
    in_specs.append(pl.BlockSpec((1, bs, d), tmap))
    in_specs += [pl.BlockSpec((1, bs, d), tmap) for _ in sinsp]
    out_specs = [
        pl.BlockSpec((1, bs, t.shape[2], d), lambda bi, i: (bi, i, 0, 0))
        for t in tp
    ]
    out_shape = [jax.ShapeDtypeStruct(t.shape, t.dtype) for t in tp]
    kernel = functools.partial(_rope_kernel, nt=len(tp), shifts=shifts)
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret_mode(),
    )(*tp, cfp, *sinsp)
    if len(tp) == 1:
        outs = (outs,) if not isinstance(outs, (tuple, list)) else tuple(outs)
    return tuple(o[:, :s] for o in outs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rope(tensors, tables, shifts, bs):
    cf, sins = tables
    return _rope_run(tensors, cf, sins, shifts, bs)


def _rope_vjp_fwd(tensors, tables, shifts, bs):
    cf, sins = tables
    return _rope_run(tensors, cf, sins, shifts, bs), tables


def _rope_vjp_bwd(shifts, bs, tables, douts):
    # adjoint of an orthogonal rotation = same kernel, sin tables negated
    # (both pairings: the shift set is closed under lane-negation and the
    # rolled sign tables map onto each other with a sign flip)
    cf, sins = tables
    dtens = _rope_run(tuple(douts), cf, tuple(-sv for sv in sins), shifts,
                      bs)
    zeros = (jnp.zeros_like(cf), tuple(jnp.zeros_like(sv) for sv in sins))
    return (dtens, zeros)


_rope.defvjp(_rope_vjp_fwd, _rope_vjp_bwd)


def _seq_block(s, heads, d):
    """Default sequence block: power of two keeping the per-step working set
    (sum of all tensor tiles, in + out, f32) near 8MB."""
    per_row = max(1, heads) * d * 4 * 2
    cap = 1024
    while cap > 8 and cap * per_row > 8 * 1024 * 1024:
        cap //= 2
    return max(8, min(cap, -(-max(8, s) // 8) * 8))


def _tuned_seq_block(tensors, cf, sins, shifts):
    """Sequence-block size for this signature, autotuned when
    PADDLE_TPU_AUTOTUNE=1. The head/head-dim axes stay whole (they are the
    Mosaic tile), so candidates vary only the sequence block; the recorded
    tile is (seq_rows, head_dim)."""
    from .autotune import pick_block_sizes

    b, s = tensors[0].shape[0], tensors[0].shape[1]
    d = tensors[0].shape[-1]
    heads = sum(t.shape[2] for t in tensors)
    default = (_seq_block(s, heads, d), d)
    per_row = heads * d * 4 * 2
    cands = sorted({default} | {
        (c, d) for c in (64, 128, 256, 512, 1024)
        if c <= -(-max(8, s) // 8) * 8 and c * per_row <= 12 * 1024 * 1024})

    def run_with(bs, _bk):
        outs = _rope_run(tensors, cf, sins, shifts, bs)
        jax.device_get(outs[0].ravel()[0:1])  # real fetch, see flash tuner

    concrete = not any(isinstance(t, jax.core.Tracer)
                       for t in (*tensors, cf, *sins))
    bs, _ = pick_block_sizes(
        "fused_rope", s, d, default, run_with, allow_measure=concrete,
        signature=(b, heads, d, str(tensors[0].dtype), len(shifts)),
        candidates=cands)
    return bs


def apply_fused_rope(tensors, cos_half, sin_half, interleaved=False):
    """Apply rotary embedding to 1..3 tensors [B, S, Hi, D] in ONE kernel
    pass. cos_half/sin_half: [B|1, S, D/2] position tables (data — zero
    cotangent). Differentiable w.r.t. the tensors (custom VJP). Requires
    even D; callers gate on that and fall back to the composite."""
    d = tensors[0].shape[-1]
    cf, sins, shifts = _rope_tables_full(cos_half, sin_half, d, interleaved)
    bs = _tuned_seq_block(tensors, cf, sins, shifts)
    return _rope(tuple(tensors), (cf, tuple(sins)), shifts, bs)
