"""Flash attention for TPU, in Pallas.

Reference analog: the dynloaded flash-attention library the reference wraps
(paddle/phi/kernels/gpu/flash_attn_kernel.cu:832, dynload
paddle/phi/backends/dynload/flashattn.cc) — re-designed for the TPU memory
hierarchy rather than translated:

- grid (batch, heads, q-blocks, kv-blocks), kv innermost: softmax running
  state (l, acc) lives in VMEM scratch that persists across the sequential
  TPU grid steps, so no atomics / split-k reduction pass is needed.
- K is fed TRANSPOSED ([B, H, D, S]) so the QK^T contraction runs in the
  MXU's native layout (lhs lane x rhs sublane). The round-5 on-chip A/B
  measured the nt form (both contractions on lane dims) at 2.4x slower —
  Mosaic inserts a relayout for it.
- the [bq, bk] f32 logits tile cannot live in vector registers, so EVERY
  separate elementwise pass over it is a full VMEM round trip; chained ops
  fuse into one stream and are effectively free (measured: 1 op == 16 ops).
  The kernel therefore runs ONE fused stream per tile: exp(clamp(s)) +
  row-sum + bf16 cast, with NO separate running-max reduce. Softmax
  shift-invariance makes the unshifted form exact while row max < _CLAMP
  (=60: sum bounded by 2048*e^60 ~ 2e29, far inside f32); rows with logits
  >= 60 saturate to equal weights instead of overflowing. Measured on a
  v5e: 1.9x forward speedup over the online-softmax form.
  PADDLE_TPU_FLASH_SAFE_SOFTMAX=1 restores the classic running-max kernel
  (exact for any logit magnitude).
- causal blocks strictly above the diagonal are skipped via pl.when, blocks
  fully below it skip ALL mask work; only diagonal-crossing blocks build a
  mask (1-D iotas broadcast against each other).
- GQA/MQA: kv heads indexed via the BlockSpec index_map (no head repetition
  materialized in the forward).
- backward = two kernels (dq; dk/dv) recomputing logits from the saved
  softmax LSE — the standard recompute-not-store flash backward, wired as
  jax.custom_vjp, with the same transposed K/V layout for the recomputes.

All entry points pad the sequence to block multiples and mask the padding, so
any length works with static shapes.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import interpret_mode

__all__ = ["flash_attention_fwd", "flash_attention"]

NEG_INF = -1e30
# unshifted-softmax saturation bound: exact below, equal-weight above (see
# module docstring); 2048-wide rows sum to <= 2e29 << f32 max.
#
# The matching LOWER bound: f32 exp underflows to 0 for arguments below
# ~-87.3 (ln(2^-126)), so in fast mode any key whose logit sits more than
# ~87 below the row's lse contributes exactly 0 weight — in particular a
# fully-masked row (all logits NEG_INF, l=0 -> lse=0 by the l_safe guard)
# produces an all-zero output row rather than NaN. Between the two bounds
# the unshifted form is exact; outside them it saturates (high side) or
# truncates the tail (low side). PADDLE_TPU_FLASH_SAFE_SOFTMAX=1 selects
# the running-max kernel, exact for any magnitude.
_CLAMP = 60.0


def _safe_softmax():
    """Read the safe/fast softmax toggle. Captured ONCE per forward trace
    (flash_attention_fwd) and threaded through the custom-VJP static args —
    the backward must never re-read the env var, or a toggle between
    forward and backward tracing silently corrupts gradients (the two
    kernels disagree on the lse convention: running-max base vs 0)."""
    return os.environ.get("PADDLE_TPU_FLASH_SAFE_SOFTMAX") == "1"


def _block_sizes(sq, skv, d=None):
    """Default tile sizes. Large blocks matter more than MXU-perfect ones on
    TPU: the grid is executed sequentially per core, and the per-tile VMEM
    streaming rate is the binding constraint — 512x1024 measured best at the
    GPT-125M shape on a v5e (tools/attn_ab.py), using <6MB of VMEM. Head
    dims >=256 halve the cap to stay inside VMEM with double buffering.

    PADDLE_TPU_FLASH_BLOCK=<n> overrides the cap (hardware escape hatch —
    e.g. =128 restores the round-2 tiling without a code change)."""
    try:
        env_cap = int(os.environ.get("PADDLE_TPU_FLASH_BLOCK", "0"))
    except ValueError:
        env_cap = 0
    if env_cap > 0:
        # explicit override: round to a legal sublane multiple, clamp >= 8
        capq = capk = max(8, env_cap // 8 * 8)
    else:
        capq, capk = 512, 1024
        if d is not None and d >= 256:
            capq, capk = 256, 256  # VMEM headroom for wide heads
    bq = min(capq, -(-max(8, sq) // 8) * 8)  # round up to sublane multiple
    bk = min(capk, -(-max(8, skv) // 8) * 8)
    return bq, bk


def _block_mask(q_start, k_start, bq, bk, off, causal, pad_k, skv,
                pad_q=False, sq=None):
    """Bool keep-mask for one [bq, bk] tile, built from 1-D iotas broadcast
    against each other (a 2-D iota per operand costs two full VPU
    materializations; the broadcast compare is one)."""
    row = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    col = k_start + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
    mask = None
    if causal:
        mask = col <= row + off
    if pad_k:  # kv padding tail (only when skv % bk != 0)
        m2 = jnp.broadcast_to(col < skv, (bq, bk))
        mask = m2 if mask is None else mask & m2
    if pad_q and sq is not None:  # q padding tail (dkv kernel)
        m3 = jnp.broadcast_to(row < sq, (bq, bk))
        mask = m3 if mask is None else mask & m3
    return mask


# --------------------------------------------------------------------------- #
# forward
# --------------------------------------------------------------------------- #


def _fwd_kernel(q_ref, kt_ref, v_ref, *rest_refs,
                scale, causal, sq, skv, bq, bk, nk, safe, has_kbias):
    if has_kbias:
        kb_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest_refs
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest_refs
        kb_ref = None
    i = pl.program_id(2)
    j = pl.program_id(3)

    q_start = i * bq
    k_start = j * bk
    # bottom-right-aligned causal (flash-attn convention): query at true row r
    # attends to cols <= r + (skv - sq), so decode (sq=1) sees the whole cache
    off = skv - sq
    pad_k = (skv % bk) != 0  # static: no padding -> no padding mask at all

    @pl.when(j == 0)
    def _init():
        if safe:
            m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _logits():
        # q [bq, D] x kT [D, bk]: contraction lhs-lane x rhs-sublane — the
        # MXU-native form (the nt form costs a Mosaic relayout, 2.4x slower)
        q = q_ref[0, 0]
        kt = kt_ref[0, 0]
        out = jax.lax.dot_general(
            q, kt, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        if kb_ref is not None:
            # additive per-key bias (padding mask): one fused VPU add —
            # free, since the stream over s is already being paid for.
            # the bias rides as [B, 8, Skv] (8 replicated sublanes — Mosaic
            # needs last-two block dims divisible by (8, 128)); row 0 is
            # broadcast over the tile
            out = out + kb_ref[0, :1].astype(jnp.float32)
        return out

    def _update_fast(s, v):
        # ONE fused VMEM stream: clamp + exp + row-sum + bf16 cast. No
        # running max — softmax shift invariance (see module docstring).
        p = jnp.exp(jnp.minimum(s, _CLAMP))
        l_scr[:, :1] = l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] + pv

    def _update_safe(s, v):
        # classic online softmax: an extra full pass over the tile for the
        # running-max reduce, exact for any logit magnitude
        m_prev = m_scr[:, :1]  # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [bq, bk]
        l_scr[:, :1] = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1,
                                                      keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    def _update(s, v):
        if safe:
            _update_safe(s, v)
        else:
            _update_fast(s, v)

    if causal:
        # three-way block split: interior blocks (fully below the diagonal)
        # skip ALL mask work — only diagonal-crossing blocks pay for it
        interior = k_start + bk - 1 <= q_start + off
        needed = k_start <= q_start + bq - 1 + off
        if pad_k:
            interior = interior & (j < nk - 1)

        @pl.when(interior)
        def _compute_interior():
            _update(_logits(), v_ref[0, 0])

        @pl.when(needed & ~interior)
        def _compute_diagonal():
            s = _logits()
            mask = _block_mask(q_start, k_start, bq, bk, off, True, pad_k,
                               skv)
            _update(jnp.where(mask, s, NEG_INF), v_ref[0, 0])
    elif pad_k:
        @pl.when(j < nk - 1)
        def _compute_inner():
            _update(_logits(), v_ref[0, 0])

        @pl.when(j == nk - 1)
        def _compute_tail():
            s = _logits()
            mask = _block_mask(q_start, k_start, bq, bk, off, False, True,
                               skv)
            _update(jnp.where(mask, s, NEG_INF), v_ref[0, 0])
    else:
        _update(_logits(), v_ref[0, 0])

    # last block for this row: nk-1 in general; for causal the last needed one
    if causal:
        last = jnp.clip((q_start + bq - 1 + off) // bk, 0, nk - 1)
    else:
        last = nk - 1

    @pl.when(j == last)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse rides as [B, H, Sq, 1]: a trailing singleton keeps the block's
        # last-two dims (bq, 1) legal under Mosaic's tiling rule (a [.., bq]
        # block would put the H axis second-to-last with block size 1)
        base = m_scr[:, :1] if safe else 0.0
        lse_ref[0, 0] = base + jnp.log(l_safe)


def _fwd(q, k, v, scale, causal, sq, skv, bq=None, bk=None, kbias=None,
         safe=None):
    B, H, Sqp, D = q.shape
    _, Hkv, Skvp, _ = k.shape
    if bq is None or bk is None:
        bq, bk = _block_sizes(Sqp, Skvp, d=D)
    if safe is None:
        safe = _safe_softmax()
    nq = Sqp // bq
    nk = Skvp // bk
    group = H // Hkv
    kt = jnp.swapaxes(k, 2, 3)  # [B, Hkv, D, Skv]: MXU-native QK^T layout

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, sq=sq, skv=skv,
        bq=bq, bk=bk, nk=nk, safe=safe,
        has_kbias=kbias is not None,
    )
    in_specs = [
        pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, D, bk), lambda b, h, i, j, g=group: (b, h // g, 0, j)),
        pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j, g=group: (b, h // g, j, 0)),
    ]
    args = [q, kt, v]
    if kbias is not None:  # [B, Skvp] additive per-key bias (padding mask)
        spec, arg = _kbias_spec_and_arg(kbias, B, bk,
                                        lambda b, h, i, j: (b, 0, j))
        in_specs.append(spec)
        args.append(arg)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sqp, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sqp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(*args)
    return out, lse


# --------------------------------------------------------------------------- #
# backward
# --------------------------------------------------------------------------- #


def _recompute_p(q_ref, kt_ref, lse_ref, scale, safe, kb_ref=None):
    """One fused stream: s = q@kT (MXU) then exp(s - lse) (VPU). The fast
    forward clamps logits at _CLAMP, so its backward must clamp identically
    for gradient consistency. kb_ref: optional [1, bk] additive key bias
    (padding mask) — folded in before the clamp like the forward.

    Returns (p, ds_gate): ds_gate is None in safe mode; in fast mode it is
    the boolean clamp mask — where the forward SATURATED (s >= _CLAMP),
    d p/d s is exactly 0 (the clamp is flat), so ds must be zeroed there.
    p itself stays ungated: dv = p^T @ do is correct with the saturated
    weights."""
    s = jax.lax.dot_general(
        q_ref[0, 0], kt_ref[0, 0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32
    ) * scale
    if kb_ref is not None:
        s = s + kb_ref[0, :1].astype(jnp.float32)
    if not safe:
        gate = s < _CLAMP
        return jnp.exp(jnp.minimum(s, _CLAMP) - lse_ref[0, 0]), gate
    return jnp.exp(s - lse_ref[0, 0]), None


def _bwd_dq_kernel(q_ref, kt_ref, vt_ref, k_ref, *rest_refs, scale, causal,
                   sq, skv, bq, bk, nk, safe, has_kbias):
    if has_kbias:
        kb_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr = rest_refs
    else:
        do_ref, lse_ref, delta_ref, dq_ref, dq_scr = rest_refs
        kb_ref = None
    i = pl.program_id(2)
    j = pl.program_id(3)
    q_start = i * bq
    k_start = j * bk
    off = skv - sq
    pad_k = (skv % bk) != 0

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _accum(masked):
        p, gate = _recompute_p(q_ref, kt_ref, lse_ref, scale, safe, kb_ref)
        if masked:
            mask = _block_mask(q_start, k_start, bq, bk, off, causal, pad_k,
                               skv)
            if mask is not None:
                p = jnp.where(mask, p, 0.0)
        do = do_ref[0, 0]
        # dp = do @ v^T — vT input makes this MXU-native like the recompute
        dp = jax.lax.dot_general(
            do, vt_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, 0]) * scale
        if gate is not None:  # fast path: zero ds where the clamp saturated
            ds = jnp.where(gate, ds, 0.0)
        ds = ds.astype(k_ref.dtype)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )

    if causal:
        interior = k_start + bk - 1 <= q_start + off
        needed = k_start <= q_start + bq - 1 + off
        if pad_k:
            interior = interior & (j < nk - 1)

        @pl.when(interior)
        def _compute_interior():
            _accum(masked=False)

        @pl.when(needed & ~interior)
        def _compute_masked():
            _accum(masked=True)
    elif pad_k:
        @pl.when(j < nk - 1)
        def _compute_inner():
            _accum(masked=False)

        @pl.when(j == nk - 1)
        def _compute_tail():
            _accum(masked=True)
    else:
        _accum(masked=False)

    if causal:
        last = jnp.clip((q_start + bq - 1 + off) // bk, 0, nk - 1)
    else:
        last = nk - 1

    @pl.when(j == last)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, kt_ref, vt_ref, *rest_refs, scale, causal, sq,
                    skv, bq, bk, nq, safe, has_kbias):
    if has_kbias:
        (kb_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_scr,
         dv_scr) = rest_refs
    else:
        do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest_refs
        kb_ref = None
    j = pl.program_id(2)  # kv block
    i = pl.program_id(3)  # q block
    q_start = i * bq
    k_start = j * bk
    off = skv - sq
    pad_k = (skv % bk) != 0
    pad_q = (sq % bq) != 0

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _accum(masked):
        p, gate = _recompute_p(q_ref, kt_ref, lse_ref, scale, safe, kb_ref)
        if masked:
            mask = _block_mask(q_start, k_start, bq, bk, off, causal, pad_k,
                               skv, pad_q=pad_q, sq=sq)
            if mask is not None:
                p = jnp.where(mask, p, 0.0)
        do = do_ref[0, 0]
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, vt_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, 0]) * scale
        if gate is not None:  # fast path: zero ds where the clamp saturated
            ds = jnp.where(gate, ds, 0.0)
        ds = ds.astype(q_ref.dtype)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q_ref[0, 0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )

    # causal: q block needed iff q_end + off >= k_start; interior q blocks
    # (whole block past the diagonal) need no causal mask
    if causal:
        interior = k_start + bk - 1 <= q_start + off
        needed = q_start + bq - 1 + off >= k_start
        if pad_k:
            interior = interior & (j < pl.num_programs(2) - 1)
        if pad_q:
            interior = interior & (i < nq - 1)

        @pl.when(interior)
        def _compute_interior():
            _accum(masked=False)

        @pl.when(needed & ~interior)
        def _compute_masked():
            _accum(masked=True)
    elif pad_k or pad_q:
        tail = jnp.bool_(False)
        if pad_k:
            tail = tail | (j == pl.num_programs(2) - 1)
        if pad_q:
            tail = tail | (i == nq - 1)

        @pl.when(~tail)
        def _compute_inner():
            _accum(masked=False)

        @pl.when(tail)
        def _compute_tail():
            _accum(masked=True)
    else:
        _accum(masked=False)

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(scale, causal, sq, skv, residuals, dout, bq, bk, safe,
         kbias=None):
    # (bq, bk, safe) are the FORWARD's (possibly autotuned) block sizes and
    # softmax mode, threaded through the VJP's static args — recomputing
    # them here could diverge from the forward (padding mismatch leaving
    # grid rows unwritten; an env-var toggle flipping the lse convention
    # between forward and backward, silently corrupting gradients)
    q, k, v, out, lse = residuals
    B, H, Sqp, D = q.shape
    _, Hkv, Skvp, _ = k.shape
    nq = Sqp // bq
    nk = Skvp // bk
    group = H // Hkv
    kt = jnp.swapaxes(k, 2, 3)  # [B, Hkv, D, Skv]
    vt = jnp.swapaxes(v, 2, 3)

    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B, H, Sqp, 1] like lse

    dq_specs = [
        pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, D, bk), lambda b, h, i, j, g=group: (b, h // g, 0, j)),
        pl.BlockSpec((1, 1, D, bk), lambda b, h, i, j, g=group: (b, h // g, 0, j)),
        pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j, g=group: (b, h // g, j, 0)),
    ]
    dq_args = [q, kt, vt, k]
    if kbias is not None:
        spec, arg = _kbias_spec_and_arg(kbias, B, bk,
                                        lambda b, h, i, j: (b, 0, j))
        dq_specs.append(spec)
        dq_args.append(arg)
    dq_specs += [
        pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
    ]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          sq=sq, skv=skv, bq=bq, bk=bk, nk=nk, safe=safe,
                          has_kbias=kbias is not None),
        grid=(B, H, nq, nk),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Sqp, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret_mode(),
    )(*dq_args, dout, lse, delta)

    # dk/dv over expanded heads, then group-sum for GQA
    dkv_specs = [
        pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, D, bk), lambda b, h, j, i, g=group: (b, h // g, 0, j)),
        pl.BlockSpec((1, 1, D, bk), lambda b, h, j, i, g=group: (b, h // g, 0, j)),
    ]
    dkv_args = [q, kt, vt]
    if kbias is not None:
        spec, arg = _kbias_spec_and_arg(kbias, B, bk,
                                        lambda b, h, j, i: (b, 0, j))
        dkv_specs.append(spec)
        dkv_args.append(arg)
    dkv_specs += [
        pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0)),
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          sq=sq, skv=skv, bq=bq, bk=bk, nq=nq, safe=safe,
                          has_kbias=kbias is not None),
        grid=(B, H, nk, nq),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Skvp, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, Skvp, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(*dkv_args, dout, lse, delta)

    if group > 1:
        dk = dk.reshape(B, Hkv, group, Skvp, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, group, Skvp, D).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------------- #
# public entry: [B, S, H, D] paddle layout, custom VJP
# --------------------------------------------------------------------------- #


def _pad_seq(x, block):
    s = x.shape[2]
    pad = (-s) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
    return x


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, bq, bk, safe):
    out, _ = _flash_fwd_res(q, k, v, causal, scale, bq, bk, safe)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_kb(q, k, v, kbias, causal, scale, bq, bk, safe):
    """Variant with an additive per-key bias [B, Skv] (padding mask).

    The bias is treated as DATA: its cotangent is zero (callers with a
    trainable bias must use the composite path — the functional dispatch
    checks stop_gradient for exactly this)."""
    out, _ = _flash_kb_fwd_res(q, k, v, kbias, causal, scale, bq, bk, safe)
    return out


def _kbias_spec_and_arg(kbias, B, bk, index_map):
    """BlockSpec + operand for the key bias: [B, 8, Skvp] with 8 replicated
    sublanes (Mosaic wants last-two block dims divisible by (8, 128));
    kernels read row 0 and broadcast. ONE definition — the fwd and both bwd
    kernels must stay tiled identically."""
    spec = pl.BlockSpec((1, 8, bk), index_map)
    arg = jnp.broadcast_to(kbias[:, None, :], (B, 8, kbias.shape[1]))
    return spec, arg


def _pad_kbias(kbias, skv, block):
    pad = (-skv) % block
    if pad:
        # padded key columns must stay masked even without the pad_k mask
        kbias = jnp.pad(kbias, ((0, 0), (0, pad)), constant_values=NEG_INF)
    return kbias


def _flash_kb_fwd_res(q, k, v, kbias, causal, scale, bq, bk, safe):
    B, H, Sq, D = q.shape
    Skv = k.shape[2]
    qp = _pad_seq(q, bq)
    kp = _pad_seq(k, bk)
    vp = _pad_seq(v, bk)
    kbp = _pad_kbias(kbias.astype(jnp.float32), Skv, bk)
    out, lse = _fwd(qp, kp, vp, scale, causal, Sq, Skv, bq=bq, bk=bk,
                    kbias=kbp, safe=safe)
    return out[:, :, :Sq], (qp, kp, vp, kbp, out, lse)


def _flash_kb_vjp_fwd(q, k, v, kbias, causal, scale, bq, bk, safe):
    out, res = _flash_kb_fwd_res(q, k, v, kbias, causal, scale, bq, bk,
                                 safe)
    return out, (res, q.shape[2], k.shape[2])


def _flash_kb_vjp_bwd(causal, scale, bq, bk, safe, saved, dout):
    (qp, kp, vp, kbp, outp, lse), sq, skv = saved
    dop = jnp.pad(dout, ((0, 0), (0, 0), (0, qp.shape[2] - sq), (0, 0)))
    dq, dk, dv = _bwd(scale, causal, sq, skv, (qp, kp, vp, outp, lse), dop,
                      bq, bk, safe, kbias=kbp)
    # the mask is data, not a trained parameter — zero cotangent; primal
    # kbias is f32 by construction (entry casts), so dtypes always match
    return (dq[:, :, :sq], dk[:, :, :skv], dv[:, :, :skv],
            jnp.zeros((kbp.shape[0], skv), jnp.float32))


_flash_kb.defvjp(_flash_kb_vjp_fwd, _flash_kb_vjp_bwd)


def _tuned_blocks(q, k, v, causal, scale):
    """Forward block sizes, autotuned per (seq, kv-seq) signature when
    PADDLE_TPU_AUTOTUNE=1 (reference: phi/kernels/autotune cache). Always
    goes through pick_block_sizes — disabled runs return the default fast
    but still land the chosen tile in the telemetry registry
    (autotune.chosen_tiles), so the step-timeline JSONL and bench perf line
    can attribute MFU movement to tile choices."""
    from .autotune import pick_block_sizes

    sq, skv = q.shape[2], k.shape[2]
    default = _block_sizes(sq, skv, d=q.shape[-1])

    def run_with(bq, bk):
        out, _ = _fwd(_pad_seq(q, bq), _pad_seq(k, bk), _pad_seq(v, bk),
                      scale, causal, sq, skv, bq=bq, bk=bk)
        # REAL device->host fetch: through the axon tunnel,
        # block_until_ready returns before execution finishes, which made
        # every candidate measure the same dispatch latency and the tuner
        # pick effectively at random (round-5 bench regression)
        jax.device_get(out.ravel()[0:1])

    concrete = not any(isinstance(x, jax.core.Tracer) for x in (q, k, v))
    B, H, _, D = q.shape
    return pick_block_sizes(
        "flash_fwd", sq, skv, default, run_with, allow_measure=concrete,
        signature=(B, H, k.shape[1], D, str(q.dtype), bool(causal)))


def _flash_fwd_res(q, k, v, causal, scale, bq, bk, safe):
    B, H, Sq, D = q.shape
    Skv = k.shape[2]
    qp = _pad_seq(q, bq)
    kp = _pad_seq(k, bk)
    vp = _pad_seq(v, bk)
    out, lse = _fwd(qp, kp, vp, scale, causal, Sq, Skv, bq=bq, bk=bk,
                    safe=safe)
    return out[:, :, :Sq], (qp, kp, vp, out, lse)


def _flash_vjp_fwd(q, k, v, causal, scale, bq, bk, safe):
    out, res = _flash_fwd_res(q, k, v, causal, scale, bq, bk, safe)
    return out, (res, q.shape[2], k.shape[2])


def _flash_vjp_bwd(causal, scale, bq, bk, safe, saved, dout):
    (qp, kp, vp, outp, lse), sq, skv = saved
    dop = jnp.pad(dout, ((0, 0), (0, 0), (0, qp.shape[2] - sq), (0, 0)))
    dq, dk, dv = _bwd(scale, causal, sq, skv, (qp, kp, vp, outp, lse), dop,
                      bq, bk, safe)
    return dq[:, :, :sq], dk[:, :, :skv], dv[:, :, :skv]


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention_fwd(q, k, v, causal=False, scale=None, key_bias=None):
    """Paddle-layout entry: q [B,Sq,H,D], k/v [B,Skv,Hkv,D] → [B,Sq,H,D].

    key_bias: optional [B, Skv] ADDITIVE per-key bias (the padding-mask
    case — encoder models), fused into the kernel's logits stream.
    Differentiable (custom VJP, flash backward). Reference API:
    python/paddle/nn/functional/flash_attention.py:358."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # the kernels feed the MXU raw operands, so mixed q/kv dtypes must be
    # normalized here (promote everything to q's dtype)
    k = k.astype(q.dtype)
    v = v.astype(q.dtype)
    qt = jnp.swapaxes(q, 1, 2)  # [B,H,S,D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    bq, bk = _tuned_blocks(qt, kt, vt, causal, scale)
    # softmax mode is captured HERE, at forward trace time, and rides the
    # custom-VJP static args: fwd and bwd kernels always agree on the lse
    # convention even if the env toggle flips between their traces
    safe = _safe_softmax()
    if key_bias is not None:
        # f32 primal by construction: the zero cotangent in the VJP is f32
        out = _flash_kb(qt, kt, vt, key_bias.astype(jnp.float32), causal,
                        scale, bq, bk, safe)
    else:
        out = _flash(qt, kt, vt, causal, scale, bq, bk, safe)
    return jnp.swapaxes(out, 1, 2)


flash_attention = flash_attention_fwd
