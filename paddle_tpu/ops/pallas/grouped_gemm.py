"""Grouped (ragged) GEMM for MoE expert compute, in Pallas.

Reference analog: paddle/phi/kernels/fusion/cutlass/fused_moe_kernel.cu —
the cutlass grouped GEMM that runs every expert's FFN over its own ragged
row range in one launch. TPU redesign (the megablox formulation):

- Rows are pre-sorted by expert into a UNIFORM-STRIDE layout: `lhs` is
  [E * R, K] where group e owns rows [e*R, (e+1)*R) and only the first
  `group_sizes[e]` of them are live (the MoE dispatch scatters tokens into
  exactly this layout; R is padded to the row-tile multiple). The uniform
  stride is what makes the expert dim a real mesh-shardable axis — under
  expert parallelism the same kernel runs per ep-shard on [E/ep * R, K]
  with no layout change.
- The grid walks (row tile, N tile); each row tile belongs to exactly one
  group (bm divides R), so the group's weight block rides an ordinary
  BlockSpec index map — no scalar-dependent DMA. `group_sizes` is a
  scalar-prefetch operand: tiles whose row offset is past the group's live
  rows SKIP the MXU work entirely and write zeros (compute scales with
  routed tokens rounded to bm, not with capacity — the ragged half of
  "grouped/ragged").
- Accumulation is f32 (`preferred_element_type`) whatever the input dtype,
  like every other kernel in the ladder.

Semantics (pinned by tests/test_moe.py::TestGroupedGemm): rows inside a
partially-live tile are still computed (they cost nothing extra — the MXU
runs whole tiles); rows in fully-dead tiles are zero. Callers that scatter
zeros into dead rows (the MoE layer does) therefore get exact parity with
the dense batched-GEMM formulation.

Backward (custom VJP): dlhs reuses THIS kernel with the weights transposed
(same tile skipping — dead tiles have zero cotangent by the same
semantics); dgroup weights are a batched jnp matmul over the uniform
stride, masked to the rows the forward actually computed. Autotune: tuner
name "grouped_gemm", tile family (bm over the row stride, bn over N).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import interpret_mode

__all__ = ["grouped_matmul", "default_tiles", "row_stride", "kernel_usable"]


def kernel_usable() -> bool:
    """The nn.functional kernel-dispatch rule: real Mosaic on tpu/axon,
    the interpreter when PADDLE_TPU_PALLAS_INTERPRET=1, nothing on a bare
    CPU backend (pallas_call rejects compile mode there)."""
    if interpret_mode():
        return True
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # graftlint: disable=GL003 backend probe pre-init; dispatch falls back to the einsum path
        return False


def _pad_to(n, m):
    return -(-n // m) * m


def row_stride(max_rows: int) -> int:
    """The uniform per-group row stride for `max_rows` live rows per group,
    padded so every autotune bm candidate that divides it tiles cleanly.
    Small groups quantize to 16 — the bf16 sublane minimum, so the
    sub-f32 bm bump in grouped_matmul always has a legal divisor — larger
    ones to the MXU row (128) so the (128, bn) candidates stay legal."""
    q = 16 if max_rows <= 64 else 128
    return _pad_to(max(max_rows, 1), q)


def default_tiles(R, K, N):
    """(bm, bn): bm the largest power-of-two row tile dividing R (<=128),
    bn capped so the lhs + rhs + out f32 working set stays well under
    VMEM with double buffering."""
    bm = 8
    while bm * 2 <= min(R, 128) and R % (bm * 2) == 0:
        bm *= 2
    bn = 128
    while bn * 2 <= min(N, 512) and (bm + bn * 2) * K * 4 < 6 * 1024 * 1024:
        bn *= 2
    return bm, bn


def _tile_candidates(R, K, N, default):
    cands = {default}
    for bm in (8, 16, 32, 64, 128, 256):
        if bm > R or R % bm:
            continue
        for bn in (128, 256, 512):
            if bn > _pad_to(N, 128):
                continue
            if (bm + bn) * K * 4 > 10 * 1024 * 1024:
                continue
            cands.add((bm, bn))
    return sorted(cands)


# --------------------------------------------------------------------------- #
# kernel
# --------------------------------------------------------------------------- #


def _gg_kernel(sizes_ref, lhs_ref, rhs_ref, o_ref, *, bm, tiles_per_group):
    i = pl.program_id(0)
    group = i // tiles_per_group
    off = (i % tiles_per_group) * bm
    live = sizes_ref[group]

    @pl.when(live > off)
    def _():
        o_ref[...] = jax.lax.dot_general(
            lhs_ref[...], rhs_ref[0],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(o_ref.dtype)

    @pl.when(live <= off)
    def _():
        # dead tile: zeros, not garbage — downstream reductions (dweight
        # batched matmuls, combine gathers) must never meet uninitialized
        # VMEM
        o_ref[...] = jnp.zeros_like(o_ref)


def _gg_call(lhs, rhs, sizes, bm, bn):
    """lhs [E*R, K], rhs [E, K, N], sizes [E] -> [E*R, N]."""
    E, K, N = rhs.shape
    G = lhs.shape[0]
    R = G // E
    Kp, Np = max(128, _pad_to(K, 128)), max(128, _pad_to(N, 128))
    bn = min(bn, Np)
    if Np % bn:
        bn = Np
    if lhs.shape != (G, Kp):
        lhs = jnp.pad(lhs, ((0, 0), (0, Kp - K)))
    if rhs.shape != (E, Kp, Np):
        rhs = jnp.pad(rhs, ((0, 0), (0, Kp - K), (0, Np - N)))
    tiles_per_group = R // bm
    grid = (E * tiles_per_group, Np // bn)
    kernel = functools.partial(_gg_kernel, bm=bm,
                               tiles_per_group=tiles_per_group)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((bm, Kp), lambda i, j, szs: (i, 0)),
                pl.BlockSpec((1, Kp, bn),
                             lambda i, j, szs, _t=tiles_per_group:
                             (i // _t, 0, j)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda i, j, szs: (i, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((G, Np), lhs.dtype),
        interpret=interpret_mode(),
    )(sizes.astype(jnp.int32), lhs, rhs)
    return out[:, :N]


# --------------------------------------------------------------------------- #
# custom VJP
# --------------------------------------------------------------------------- #


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _grouped_matmul(lhs, rhs, sizes, bm, bn):
    return _gg_call(lhs, rhs, sizes, bm, bn)


def _gmm_fwd(lhs, rhs, sizes, bm, bn):
    return _gg_call(lhs, rhs, sizes, bm, bn), (lhs, rhs, sizes)


def _gmm_bwd(bm, bn, res, dout):
    lhs, rhs, sizes = res
    E, K, N = rhs.shape
    R = lhs.shape[0] // E
    # dlhs: the same grouped kernel against the transposed weights — dead
    # tiles write zeros, matching the forward's "dead rows are zero" output
    # semantics exactly
    dlhs = _gg_call(dout, jnp.swapaxes(rhs, 1, 2), sizes, bm,
                    min(bn, max(128, _pad_to(K, 128))))
    # drhs[e] = lhs_e^T @ dout_e over the rows the forward COMPUTED —
    # live tiles in full (partially-live tiles run whole), dead tiles not
    # at all. The uniform stride makes this one batched matmul; masking to
    # computed rows keeps the op's own semantics exact even for callers
    # that leave garbage in dead rows.
    computed = jnp.minimum((-(-sizes // bm)) * bm, R)  # ceil(live/bm)*bm
    row = jax.lax.broadcasted_iota(jnp.int32, (E, R), 1)
    live = (row < computed[:, None])[..., None]
    lhs3 = jnp.where(live, lhs.reshape(E, R, K), 0).astype(jnp.float32)
    dout3 = jnp.where(live, dout.reshape(E, R, N), 0).astype(jnp.float32)
    drhs = jnp.einsum("erk,ern->ekn", lhs3, dout3).astype(rhs.dtype)
    dsizes = np.zeros(sizes.shape, jax.dtypes.float0)
    return dlhs.astype(lhs.dtype), drhs, dsizes


_grouped_matmul.defvjp(_gmm_fwd, _gmm_bwd)


def _tuned_tiles(lhs, rhs, sizes, R):
    """Consult the autotuner for this signature (tuner name
    "grouped_gemm"): candidates vary the row tile over divisors of the
    layout stride R and the N tile; the winner is cached per
    (E, R, K, N, dtype). Under a trace the consult is cache-only (the
    standard priming rule — call grouped_matmul with concrete arrays of
    the production shape to fill the cache, ops/pallas/README.md)."""
    from .autotune import pick_block_sizes

    E, K, N = rhs.shape
    default = default_tiles(R, K, N)

    def run_with(bm, bn):
        out = _gg_call(lhs, rhs, sizes, bm, bn)
        jax.device_get(out.ravel()[0:1])  # real device fetch (see fused_norm)

    concrete = not any(isinstance(v, jax.core.Tracer)
                       for v in (lhs, rhs, sizes))
    return pick_block_sizes(
        "grouped_gemm", lhs.shape[0], N, default, run_with,
        allow_measure=concrete,
        signature=(E, R, K, N, str(lhs.dtype)),
        candidates=_tile_candidates(R, K, N, default))


def grouped_matmul(lhs, rhs, group_sizes, block=None):
    """Ragged grouped GEMM: out[r] = lhs[r] @ rhs[r // R] with
    R = lhs.shape[0] // rhs.shape[0] the uniform group stride.

    lhs: [E*R, K] rows pre-sorted by group; rhs: [E, K, N] stacked group
    weights; group_sizes: [E] int32 live rows per group. Rows past
    `group_sizes[g]` in a fully-dead row tile come back zero; rows inside
    a partially-live tile are computed (MXU tiles are all-or-nothing).
    Differentiable in lhs/rhs (custom VJP; group_sizes gets a symbolic
    zero). `block` overrides the autotuned (bm, bn)."""
    E = rhs.shape[0]
    G = lhs.shape[0]
    if G % E:
        raise ValueError(
            f"lhs rows {G} not a multiple of the group count {E} — the "
            f"uniform-stride layout needs rows padded per group "
            f"(see row_stride())")
    R = G // E
    bm, bn = block if block is not None else _tuned_tiles(
        lhs, rhs, group_sizes, R)
    if R % bm:
        raise ValueError(f"row tile {bm} does not divide group stride {R}")
    # sub-f32 dtypes need a 16-sublane minimum tile on real Mosaic (the
    # interpreter doesn't care); row_stride() quantizes small strides to 16
    # so the bump always has a legal divisor — a hand-built layout that
    # doesn't gets a clear error instead of a Mosaic lowering failure
    if jnp.dtype(lhs.dtype).itemsize < 4 and bm < 16:
        if R % 16:
            raise ValueError(
                f"sub-f32 grouped_matmul needs a 16-divisible group stride "
                f"(got R={R}); lay rows out with row_stride()")
        bm = 16
    return _grouped_matmul(lhs, rhs, group_sizes.astype(jnp.int32), bm, bn)
