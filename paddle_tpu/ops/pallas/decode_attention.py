"""Paged-KV and dense-cache decode attention for TPU, in Pallas.

Reference analogs: block_multihead_attention's paged decode path
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu,
block_attn.h) and masked_multihead_attention
(fusion/gpu/masked_multihead_attention_kernel.cu, mmha_util.cu.h).

TPU-native design: decode is HBM-bound — the entire job is streaming the KV
cache through VMEM exactly once per step. The paged variant prefetches the
block table as a scalar operand (pltpu.PrefetchScalarGridSpec) so the
per-page physical index is resolved in the BlockSpec index_map: the pipeline
DMAs each logical page straight from its physical slot, no gathered copy of
the cache is ever materialized (the jnp composite's `kc[tables]` gather is
exactly what XLA does badly — SURVEY §7 hard parts). Pages past a row's
length are skipped (no DMA cost model change, but no MXU/VPU work), and the
final page is masked per-slot. GQA: grid is (batch, kv_head, page) and each
step attends the head-group [g, D] block against one [page, D] page.

Single-token decode (q = one step per row), inference only (no VJP).

Quantized fast path: with `kv_scales`, the caches are int8 page payloads and
`kv_scales` the per-(page, head) f32 dequant scales (`x ≈ q * scale`,
`BlockPool(quantized=True)` layout). The same grid loads the int8 page into
VMEM, dequantizes there (one scalar multiply per page fetched as a (1, 1)
block), and accumulates in f32 exactly like the full-precision kernel —
decode is HBM-bound, so halving/quartering the streamed bytes is the whole
win and the dequant multiply rides the VPU for free.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import interpret_mode
from .flash_attention import NEG_INF

__all__ = ["paged_decode_attention", "dense_decode_attention",
           "paged_kv_write", "paged_kv_write_q8", "KV_QMAX"]

# symmetric int8 range for KV pages: ±127 (not -128) so the running-max
# rescale in paged_kv_write_q8 can never overflow the negative extreme
KV_QMAX = 127.0


def _decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, *rest,
                   scale, ps, np_, g, paged, quantized):
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest
    b = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = lens_ref[b]
    base = p * ps
    valid_page = base < length
    if paged:
        valid_page = valid_page & (tables_ref[b, p] >= 0)

    # scratch rows are padded to >=8 for TPU tiling; compute on the first g
    @pl.when(valid_page)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)      # [g, D]
        k = k_ref[0, 0].astype(jnp.float32)      # [ps, D]
        if quantized:
            k = k * ks_ref[0, 0]                 # dequant in VMEM
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                # [g, ps]
        slot = base + jax.lax.broadcasted_iota(jnp.int32, (g, ps), 1)
        s = jnp.where(slot < length, s, NEG_INF)

        m_prev = m_scr[0:g, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        pr = jnp.exp(s - m_new)
        pr = jnp.where(slot < length, pr, 0.0)
        l_scr[0:g, :] = jnp.broadcast_to(
            alpha * l_scr[0:g, 0:1] + jnp.sum(pr, axis=-1, keepdims=True),
            (g, l_scr.shape[1]))
        v = v_ref[0, 0].astype(jnp.float32)      # [ps, D]
        if quantized:
            v = v * vs_ref[0, 0]
        pv = jax.lax.dot_general(
            pr, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[0:g, :] = acc_scr[0:g, :] * alpha + pv
        m_scr[0:g, :] = jnp.broadcast_to(m_new, (g, m_scr.shape[1]))

    @pl.when(p == np_ - 1)
    def _finish():
        l = l_scr[0:g, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[0:g, :] / l_safe).astype(o_ref.dtype)


def _default_dense_ps(s_max):
    """Dense-cache sequence tile: largest power of two <= 256 dividing the
    static cache capacity."""
    ps = min(256, s_max)
    while s_max % ps:
        ps //= 2
    return ps


def _run_decode(q, kc, vc, tables, lengths, scale, paged, ps=None,
                kv_scales=None):
    """q: [B, Hkv, g, D]; kc/vc paged [n_pages, Hkv, ps, D] or dense
    [B, Hkv, S_max, D] (viewed as ps-sized pages). tables: [B, P] (paged) or
    a dummy [B, 1] (dense). For the dense layout `ps` selects the sequence
    tile (autotunable); paged `ps` IS the cache's physical page size.
    kv_scales: (k_scale, v_scale) per-(page, head) f32 [n_pages, Hkv] for
    int8 caches (paged only) — dequant is fused into the page load."""
    B, Hkv, g, D = q.shape
    quantized = kv_scales is not None
    if paged:
        _, _, ps, _ = kc.shape
        P = tables.shape[1]

        def kmap(b, h, p, tabs, lens):
            t = tabs[b, p]
            return (jnp.where(t < 0, 0, t), h, 0, 0)

        def smap(b, h, p, tabs, lens):
            t = tabs[b, p]
            return (jnp.where(t < 0, 0, t), h)
    else:
        assert not quantized, "quantized cache is paged-only"
        S_max = kc.shape[2]
        if ps is None:
            ps = _default_dense_ps(S_max)
        P = S_max // ps

        def kmap(b, h, p, tabs, lens):
            return (b, h, p, 0)

    kernel = functools.partial(
        _decode_kernel, scale=scale, ps=ps, np_=P, g=g, paged=paged,
        quantized=quantized)
    in_specs = [
        pl.BlockSpec((1, 1, g, D), lambda b, h, p, tabs, lens: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, ps, D), kmap),
        pl.BlockSpec((1, 1, ps, D), kmap),
    ]
    operands = [q, kc, vc]
    if quantized:
        # one f32 scalar per (page, head), fetched beside its page
        in_specs += [pl.BlockSpec((1, 1), smap), pl.BlockSpec((1, 1), smap)]
        operands += [kv_scales[0].astype(jnp.float32),
                     kv_scales[1].astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, P),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, D),
                               lambda b, h, p, tabs, lens: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((max(g, 8), 128), jnp.float32),
            pltpu.VMEM((max(g, 8), 128), jnp.float32),
            pltpu.VMEM((max(g, 8), D), jnp.float32),
        ],
    )
    # paged: cache already [n_pages, Hkv, ps, D]; dense: the index_map views
    # the [B, Hkv, S_max, D] cache as ps-sized blocks of the sequence axis
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, g, D), q.dtype),
        interpret=interpret_mode(),
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), *operands)
    return out


def _split_heads(q, Hkv):
    B, H, D = q.shape
    g = H // Hkv
    return q.reshape(B, Hkv, g, D), g


def paged_decode_attention(q, key_cache, value_cache, block_tables, lengths,
                           scale=None, kv_scales=None):
    """q: [B, H, D] (one decode step); key/value_cache:
    [n_pages, Hkv, page_size, D]; block_tables: [B, P] physical page ids
    (-1 unused); lengths: [B] valid tokens incl. the current one (caller has
    already written the step's K/V into the cache). With `kv_scales`
    (= (k_scale, v_scale) f32 [n_pages, Hkv]) the caches are int8 payloads
    and dequantization is fused into the page load. Returns [B, H, D]."""
    B, H, D = q.shape
    Hkv = key_cache.shape[1]
    if scale is None:
        scale = D ** -0.5
    q4, g = _split_heads(q, Hkv)
    _consult_tuner_paged(q4, key_cache, block_tables,
                         quantized=kv_scales is not None)
    out = _run_decode(q4, key_cache, value_cache, block_tables, lengths,
                      scale, paged=True, kv_scales=kv_scales)
    return out.reshape(B, H, D)


def _consult_tuner_paged(q4, kc, tables, quantized=False):
    """The paged kernel's tile (page_size, D) is the cache POOL's physical
    layout — tunable at pool construction, not per launch — so the only
    candidate is the layout itself. Consulting the tuner anyway keeps all
    the Pallas kernels uniform in telemetry: the tile lands in
    chosen_tiles() / the step-timeline record as source "fixed" (the
    single-candidate consult never sweeps and never counts a fallback).
    The dequant-fused int8 variant records under its own tuner name so the
    telemetry distinguishes which decode path actually ran."""
    from .autotune import pick_block_sizes

    B, Hkv, g, D = q4.shape
    ps = kc.shape[2]
    pick_block_sizes(
        "decode_paged_q8" if quantized else "decode_paged",
        1, ps, (ps, D), lambda bq, bk: None,
        allow_measure=False,
        signature=(B, Hkv, g, D, str(q4.dtype), tables.shape[1]),
        candidates=[(ps, D)])


def paged_kv_write(cache, new, block_tables, lengths):
    """Scatter one decode step's K (or V) rows into the paged cache.

    cache: [n_pages, Hkv, page_size, D]; new: [B, Hkv, D] (this step's
    projection per row); block_tables: [B, P] physical page ids (-1 unused);
    lengths: [B] tokens already present per row — the write lands at logical
    slot `lengths[b]`, i.e. physical (tables[b, lengths[b]//ps],
    lengths[b]%ps). Rows whose target table entry is -1 (parked/batch-pad
    rows) are routed to physical page 0, the pool's reserved null page, which
    no live block table ever references. Pure/jittable; owns the page layout
    so callers never index the cache themselves."""
    B = new.shape[0]
    ps = cache.shape[2]
    lengths = lengths.astype(jnp.int32)
    page = block_tables[jnp.arange(B), lengths // ps]
    page = jnp.where(page < 0, 0, page)
    return cache.at[page, :, lengths % ps].set(new.astype(cache.dtype))


def paged_kv_write_q8(cache, scales, new, block_tables, lengths):
    """Quantized-append analog of `paged_kv_write`: scatter one decode
    step's K (or V) rows into an int8 paged cache with per-(page, head)
    scales.

    cache: int8 [n_pages, Hkv, page_size, D]; scales: f32 [n_pages, Hkv]
    (dequant = int8 * scale); new: [B, Hkv, D]. The page scale is a RUNNING
    abs-max: if this step's row exceeds the page's current abs-max, the
    scale grows and the page's existing payload is requantized under the new
    scale in the same scatter (ratio multiply + round — exact when the scale
    is unchanged, one bounded rounding step when it grows). A write at slot 0
    restarts the running max (and zeroes the rest of the page): appends are
    strictly sequential, so slot 0 is always a page's first write, and a
    page recycled through the free list must not inherit the previous
    tenant's scale. The whole update is therefore a function of the page's
    appended history only, so page content is bit-identical across
    scheduling, COW, and spill/resume orders — the invariance the
    quantized-engine tests pin.
    Parked rows (table entry -1) land on null page 0 like the f32 path.
    Returns (cache, scales); pure/jittable."""
    B = new.shape[0]
    ps = cache.shape[2]
    lengths = lengths.astype(jnp.int32)
    page = block_tables[jnp.arange(B), lengths // ps]
    page = jnp.where(page < 0, 0, page)
    slot = lengths % ps

    new32 = new.astype(jnp.float32)                        # [B, Hkv, D]
    row_scale = jnp.max(jnp.abs(new32), axis=-1) / KV_QMAX  # [B, Hkv]
    # slot 0 is always a page's FIRST write (appends are sequential; a
    # boundary crossing allocates a fresh page), so it restarts the running
    # max — a recycled free-list page must not seed its scale (or payload,
    # zeroed below via ratio == 0) from the previous tenant's leftovers
    old_scale = jnp.where(slot[:, None] == 0, 0.0, scales[page])  # [B, Hkv]
    new_scale = jnp.maximum(old_scale, row_scale)
    safe = jnp.where(new_scale == 0.0, 1.0, new_scale)
    # requantize prior payload under the (possibly grown) scale; ratio == 1
    # (bit-exact no-op) unless this row raised the page abs-max
    ratio = old_scale / safe                                # <= 1
    pg = cache[page].astype(jnp.float32)                    # [B, Hkv, ps, D]
    pg = jnp.round(pg * ratio[:, :, None, None])
    q_row = jnp.clip(jnp.round(new32 / safe[:, :, None]), -KV_QMAX, KV_QMAX)
    at_slot = (jax.lax.broadcasted_iota(jnp.int32, (B, 1, ps, 1), 2)
               == slot[:, None, None, None])
    pg = jnp.where(at_slot, q_row[:, :, None, :], pg)
    # live rows never share a write page (COW guarantees); only parked rows
    # collide — all on null page 0, where last-writer-wins is harmless
    cache = cache.at[page].set(pg.astype(jnp.int8))
    scales = scales.at[page].set(new_scale)
    return cache, scales


def _tuned_dense_ps(q4, kc, vc, lengths, scale):
    """Dense-decode sequence tile, autotuned per signature when
    PADDLE_TPU_AUTOTUNE=1 — candidates are the powers of two dividing the
    static cache capacity (decode streams the whole cache once; the tile
    trades DMA granularity against grid overhead). Cache-only under trace."""
    from .autotune import pick_block_sizes

    B, Hkv, g, D = q4.shape
    S_max = kc.shape[2]
    default = (_default_dense_ps(S_max), D)
    cands = sorted({default} | {
        (p, D) for p in (8, 16, 32, 64, 128, 256, 512) if S_max % p == 0})
    dummy = jnp.zeros((B, 1), jnp.int32)

    def run_with(ps, _d):
        out = _run_decode(q4, kc, vc, dummy, lengths, scale, paged=False,
                          ps=ps)
        jax.device_get(out.ravel()[0:1])

    concrete = not any(isinstance(x, jax.core.Tracer)
                       for x in (q4, kc, lengths))
    ps, _ = pick_block_sizes(
        "decode_dense", 1, S_max, default, run_with,
        allow_measure=concrete, signature=(B, Hkv, g, D, str(q4.dtype)),
        candidates=cands)
    return ps


def dense_decode_attention(q, key_cache, value_cache, lengths, scale=None):
    """MMHA analog on a dense cache: q [B, H, D]; key/value_cache
    [B, Hkv, S_max, D]; lengths [B] valid tokens incl. current. -> [B, H, D]."""
    B, H, D = q.shape
    Hkv = key_cache.shape[1]
    if scale is None:
        scale = D ** -0.5
    q4, g = _split_heads(q, Hkv)
    ps = _tuned_dense_ps(q4, key_cache, value_cache, lengths, scale)
    dummy_tables = jnp.zeros((B, 1), jnp.int32)
    out = _run_decode(q4, key_cache, value_cache, dummy_tables, lengths,
                      scale, paged=False, ps=ps)
    return out.reshape(B, H, D)
