"""Framework core: Tensor, dtype, RNG, flags, save/load."""

from . import dtype
from .core import (
    Parameter,
    Tensor,
    enable_grad,
    in_tracing,
    is_grad_enabled,
    no_grad,
    register_tensor_method,
    run_op,
    set_grad_enabled,
    to_tensor,
    tracing_guard,
)
from .dtype import get_default_dtype, set_default_dtype
from .random import get_rng_state, seed, set_rng_state
from .flags import get_flags, set_flags
from .io import load, save

__all__ = [
    "Tensor",
    "Parameter",
    "to_tensor",
    "no_grad",
    "enable_grad",
    "seed",
    "save",
    "load",
    "get_default_dtype",
    "set_default_dtype",
    "get_flags",
    "set_flags",
]
