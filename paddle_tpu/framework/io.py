"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:773,1020).

State dicts are nested dicts of Tensors; serialized with pickle over numpy
arrays (same wire-compatibility stance as the reference's pickled state_dicts).
"""

from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from .core import Tensor


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._value)}
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        seq = [_to_serializable(v) for v in obj]
        return seq if isinstance(obj, list) else tuple(seq)
    return obj


def _from_serializable(obj):
    if isinstance(obj, dict):
        if obj.get("__tensor__") is True:
            return Tensor(jnp.asarray(obj["data"]))
        return {k: _from_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        seq = [_from_serializable(v) for v in obj]
        return seq if isinstance(obj, list) else tuple(seq)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_serializable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return _from_serializable(pickle.load(f))
