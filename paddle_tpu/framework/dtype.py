"""Dtype registry and default-dtype state.

TPU-native analog of the reference's dtype surface
(reference: paddle/phi/common/data_type.h, python/paddle/framework/framework.py
set_default_dtype/get_default_dtype). We expose paddle-style dtype names backed
directly by numpy/jax dtypes — there is no separate enum because jax.Array
carries its dtype natively.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical name -> jnp dtype
_NAME_TO_DTYPE = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

# paddle-style aliases
_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bf16": "bfloat16",
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
}

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_default_dtype = jnp.float32


def convert_dtype(dtype):
    """Normalize any dtype spec (str, np dtype, jnp dtype) to a numpy dtype obj."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name in _NAME_TO_DTYPE:
            return np.dtype(_NAME_TO_DTYPE[name])
        return np.dtype(name)
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    """Paddle-style name for a dtype ('float32', 'bfloat16', ...)."""
    return np.dtype(dtype).name


def set_default_dtype(d):
    global _default_dtype
    nd = convert_dtype(d)
    if nd.kind not in ("f",) and nd != np.dtype(jnp.bfloat16):
        raise TypeError(
            f"set_default_dtype only supports float dtypes, got {d!r}"
        )
    _default_dtype = nd


def get_default_dtype():
    return np.dtype(_default_dtype).name


def default_float_dtype():
    return _default_dtype


def is_floating_point_dtype(dtype) -> bool:
    d = np.dtype(dtype)
    return d.kind == "f" or d == np.dtype(jnp.bfloat16)


def is_integer_dtype(dtype) -> bool:
    return np.dtype(dtype).kind in ("i", "u", "b")


def is_complex_dtype(dtype) -> bool:
    return np.dtype(dtype).kind == "c"
