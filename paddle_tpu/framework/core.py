"""Core Tensor type and the eager autograd tape.

TPU-native redesign of the reference's eager stack:

- The reference pairs a C++ DenseTensor (paddle/phi/core/dense_tensor.h:37) with
  per-tensor AutogradMeta (paddle/fluid/eager/autograd_meta.h:61) and hand-written
  / generated GradNode classes wired per op (paddle/fluid/eager/grad_node_info.h:197,
  eager_gen.py). Here a `Tensor` wraps a `jax.Array` (XLA owns memory, layout and
  dtype dispatch — the phi KernelFactory has no TPU analog to build), and the grad
  graph is obtained *for free* per-op from `jax.vjp`: every op executed through
  `run_op` records a Wengert-list `GradNode` holding the op's VJP closure.
- `backward()` (reference: paddle/fluid/eager/backward.cc:105 RunBackward) walks
  nodes in reverse creation order — creation ids give a valid topological order of
  the DAG, so no in-degree map is needed.
- Gradient hooks fire exactly like the reference's (reducer / sequence-parallel
  allreduce hooks attach here).

Under `jax.jit` tracing (to_static / functional training step) tensors wrap
tracers; tape recording is disabled and differentiation happens through jax.grad
on the functional path instead.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
from typing import Any, Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtype_mod

__all__ = [
    "Tensor",
    "Parameter",
    "to_tensor",
    "run_op",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "in_tracing",
    "tracing_guard",
    "register_tensor_method",
    "dispatch_cache_stats",
    "clear_dispatch_cache",
]

# --------------------------------------------------------------------------- #
# global modes
# --------------------------------------------------------------------------- #

_mode = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_mode, "grad_enabled", True)


def set_grad_enabled(flag: bool):
    _mode.grad_enabled = bool(flag)


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad equivalent (reference: python/paddle/base/dygraph/base.py)."""

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


def in_tracing() -> bool:
    """True while executing inside a jax trace (to_static / functional path)."""
    return getattr(_mode, "tracing", False)


@contextlib.contextmanager
def tracing_guard(flag: bool = True):
    prev = in_tracing()
    _mode.tracing = flag
    try:
        yield
    finally:
        _mode.tracing = prev


# Interceptor hook point (used by amp autocast, analog of the AMP branch in
# generated ad_func entry points — reference:
# paddle/fluid/eager/api/manual/eager_manual/forwards/multiply_fwd_func.cc:49-70).
# Signature: fn(op_name, values) -> values.
#
# Two registration surfaces share one dispatch slot (`_op_input_interceptor`,
# read by run_op): the legacy single "base" slot (set_* — amp autocast owns
# it, save/restore style) and an additive chain (add_*/remove_* — graftlint
# runtime census, telemetry). The composed slot is rebuilt on any change so
# the hot path stays a single attribute read + call; enabling amp no longer
# clobbers chained observers (the pre-chain bug docs/LINTING.md documented).
_op_input_interceptor: Callable | None = None
_base_op_input_interceptor: Callable | None = None
_op_input_interceptor_chain: list = []


def _compose_op_input_interceptor():
    global _op_input_interceptor
    base, chain = _base_op_input_interceptor, tuple(_op_input_interceptor_chain)
    if not chain:
        _op_input_interceptor = base
        return
    if base is None and len(chain) == 1:
        _op_input_interceptor = chain[0]
        return

    def _dispatch(name, values, _base=base, _chain=chain):
        if _base is not None:
            out = _base(name, values)
            if out is not None:
                values = out
        for fn in _chain:
            out = fn(name, values)
            if out is not None:
                values = out
        return values

    _op_input_interceptor = _dispatch


def set_op_input_interceptor(fn):
    """Install/replace the base interceptor; returns the previous base so
    save/restore callers (amp autocast) can chain-restore correctly."""
    global _base_op_input_interceptor
    prev = _base_op_input_interceptor
    _base_op_input_interceptor = fn
    _compose_op_input_interceptor()
    return prev


def add_op_input_interceptor(fn):
    """Append `fn` to the interceptor chain (composes with the base slot and
    every other chained interceptor); returns `fn` for remove_*."""
    _op_input_interceptor_chain.append(fn)
    _compose_op_input_interceptor()
    return fn


def remove_op_input_interceptor(fn):
    try:
        _op_input_interceptor_chain.remove(fn)
    except ValueError:
        pass
    _compose_op_input_interceptor()


# --------------------------------------------------------------------------- #
# autograd tape
# --------------------------------------------------------------------------- #

_node_counter = itertools.count()


class GradNode:
    """One recorded op on the tape.

    Holds the VJP closure from jax.vjp plus edges to the input tensors
    (the closure's residuals play the role of the reference's TensorWrapper,
    paddle/fluid/eager/tensor_wrapper.h:39).
    """

    __slots__ = ("id", "name", "vjp_fn", "fwd_fn", "inputs", "out_avals",
                 "weak_outputs")

    def __init__(self, name, vjp_fn, inputs, out_avals, fwd_fn=None):
        self.id = next(_node_counter)
        self.name = name
        self.vjp_fn = vjp_fn
        # the pure forward fn — lets autograd.grad(create_graph=True)
        # replay the subgraph functionally and differentiate through it
        self.fwd_fn = fwd_fn
        self.inputs = inputs  # list[Tensor]
        self.out_avals = out_avals  # list[jax.ShapeDtypeStruct]
        self.weak_outputs = []  # list[weakref.ref[Tensor]], set by run_op

    def set_outputs(self, tensors):
        import weakref

        self.weak_outputs = [weakref.ref(t) for t in tensors]

    def __repr__(self):
        return f"<GradNode {self.name} id={self.id}>"


def _is_float0(g):
    return getattr(g, "dtype", None) == jax.dtypes.float0


def backward(tensor: "Tensor", grad_tensor: "Tensor" = None, retain_graph: bool = False):
    """Reverse-mode execution of the tape from `tensor`.

    Reference: egr::Backward / RunBackward (paddle/fluid/eager/backward.cc:441,105).
    Node ids are monotonically increasing in creation order, so visiting reachable
    nodes in decreasing id order is a valid reverse-topological schedule.
    """
    root = tensor._grad_node
    if root is None:
        # leaf: backward on a leaf just seeds its own grad
        if not tensor.stop_gradient:
            seed = grad_tensor._value if grad_tensor is not None else jnp.ones_like(tensor._value)
            tensor._accumulate_grad(seed)
        return

    if grad_tensor is None:
        if tensor._value.size != 1:
            raise RuntimeError(
                "backward() on a non-scalar tensor requires an explicit grad_tensor"
            )
        seed = jnp.ones_like(tensor._value)
    else:
        seed = grad_tensor._value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    # collect reachable nodes
    reachable: dict[int, GradNode] = {}
    stack = [root]
    while stack:
        node = stack.pop()
        if node.id in reachable:
            continue
        reachable[node.id] = node
        for t in node.inputs:
            if t._grad_node is not None and t._grad_node.id not in reachable:
                stack.append(t._grad_node)

    # cotangent buffers: node.id -> list per output slot
    cots: dict[int, list] = {root.id: [None] * len(root.out_avals)}
    idx = tensor._out_index
    cots[root.id][idx] = seed

    for nid in sorted(reachable.keys(), reverse=True):
        node = reachable[nid]
        out_cots = cots.get(nid)
        if out_cots is None:
            continue  # not on any path from the root
        full = [
            c if c is not None else jnp.zeros(av.shape, av.dtype)
            for c, av in zip(out_cots, node.out_avals)
        ]
        # Hooks fire on (and may modify) the full accumulated grad of each
        # output tensor; retain_grads() captures it into .grad — this is
        # where the reference's intermediate-tensor hooks live
        # (paddle/fluid/eager/backward.cc hook dispatch).
        for i, wref in enumerate(node.weak_outputs):
            t = wref()
            if t is None:
                continue
            g = full[i]
            if t._hooks:
                for fn in list(t._hooks.values()):
                    out = fn(Tensor(g))
                    if out is not None:
                        g = out._value if isinstance(out, Tensor) else out
                full[i] = g
            if t._retain_grads and not t.stop_gradient:
                t._raw_accumulate_grad(g)
        full = tuple(full)
        if len(full) == 1:
            in_grads = node.vjp_fn(full[0])
        else:
            in_grads = node.vjp_fn(full)
        if not isinstance(in_grads, tuple):
            in_grads = (in_grads,)
        for t, g in zip(node.inputs, in_grads):
            if g is None or _is_float0(g):
                continue
            src = t._grad_node
            if src is not None:
                buf = cots.setdefault(src.id, [None] * len(src.out_avals))
                j = t._out_index
                buf[j] = g if buf[j] is None else buf[j] + g
            elif not t.stop_gradient:
                t._accumulate_grad(g)
        if not retain_graph:
            cots.pop(nid, None)


# --------------------------------------------------------------------------- #
# Tensor
# --------------------------------------------------------------------------- #

_tensor_methods: dict[str, Callable] = {}


def register_tensor_method(name: str, fn: Callable):
    """Attach a functional op as a Tensor method (how python/paddle/tensor/__init__.py
    monkey-patches methods onto the pybind Tensor in the reference)."""
    _tensor_methods[name] = fn
    setattr(Tensor, name, fn)


_tensor_ctr = 0


class Tensor:
    """User-facing tensor handle: jax.Array value + autograd slot.

    Reference: paddle::Tensor (paddle/phi/api/include/tensor.h:82) +
    AutogradMeta (paddle/fluid/eager/autograd_meta.h:61).
    """

    __slots__ = (
        "_value",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_out_index",
        "_hooks",
        "_retain_grads",
        "name",
        # semi-auto parallel metadata (distributed/auto_parallel/api.py)
        "process_mesh",
        "placements",
        "dist_attr",
        "is_dist_tensor",
        # creation ordinal: lets the SOT capture (jit/sot.py) detect tensors
        # produced during a recording by paths that bypass run_op (nested
        # jits) — those cannot be replayed and force an eager fallback
        "_ctr",
        # True when the value was materialized from host data (to_tensor on
        # scalars/ndarrays) — a frame CONSTANT the SOT capture may bake
        "_host_const",
        # True for PRNG-key tensors (framework.random.rng_tensor): the SOT
        # capture must re-draw these per replay, never bake or reuse them
        "_rng_key",
        "__weakref__",
    )

    def __init__(self, value, stop_gradient: bool = True, name: str | None = None):
        if isinstance(value, Tensor):
            value = value._value
        self._value = value
        global _tensor_ctr
        _tensor_ctr += 1
        self._ctr = _tensor_ctr
        self._host_const = False
        self._rng_key = False
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self._hooks = None
        self._retain_grads = False
        self.name = name
        # semi-auto parallel metadata (distributed/auto_parallel/api.py _attach)
        self.process_mesh = None
        self.placements = None
        self.dist_attr = None
        self.is_dist_tensor = False

    # -- basic metadata ---------------------------------------------------- #

    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.ndim else 1

    @property
    def T(self):
        return _tensor_methods["t"](self)

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def place(self):
        devs = getattr(self._value, "devices", None)
        if devs is None:
            return "unknown"
        try:
            return str(next(iter(devs())))
        except Exception:
            return "unknown"

    def __len__(self):
        if self._value.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={dtype_mod.dtype_name(self.dtype)}"
            f"{grad_info},\n       {self._value})"
        )

    def __hash__(self):
        return id(self)

    def __bool__(self):
        if _sync_observer is not None:
            _sync_observer("bool", self)
        return bool(self._value)

    def __int__(self):
        if _sync_observer is not None:
            _sync_observer("int", self)
        return int(self._value)

    def __float__(self):
        # NOTE: float() coerces __float__'s return to exact float in
        # CPython 3.12+, so the SOT capture's deferred-guard scalar cannot
        # ride this path (it does ride .item()); observers get the exact
        # value guard here
        if _sync_observer is not None:
            _sync_observer("float", self)
        return float(self._value)

    def __format__(self, spec):
        if self._value.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    # -- conversion -------------------------------------------------------- #

    def numpy(self):
        if _sync_observer is not None:
            _sync_observer("array", self)
        return np.asarray(self._value)

    def item(self, *args):
        if _sync_observer is not None:
            rep = _sync_observer("item" if not args else "array", self)
            if rep is not None:
                return rep
        if args:
            return np.asarray(self._value).item(*args)
        return np.asarray(self._value).item()

    def tolist(self):
        if _sync_observer is not None:
            _sync_observer("array", self)
        return np.asarray(self._value).tolist()

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def clone(self):
        return run_op("clone", lambda a: a + jnp.zeros((), a.dtype), [self])

    def astype(self, dtype):
        nd = dtype_mod.convert_dtype(dtype)
        return run_op(
            "cast", lambda a: a.astype(jnp.dtype(nd)), [self]
        )

    cast = astype

    def cpu(self):
        return self

    def to(self, *args, **kwargs):
        """paddle Tensor.to(device|dtype|tensor). Device strings are accepted
        and are no-ops (single-controller placement is owned by jax); an
        argument that is neither a device string, a dtype, nor a Tensor is an
        error — a silently-ignored typo here poisons whole ports."""
        _DEVICES = ("cpu", "gpu", "tpu", "xpu", "npu", "custom")
        out = self
        for a in list(args) + list(kwargs.values()):
            if a is None:
                continue
            if isinstance(a, Tensor):
                out = out.astype(a._value.dtype)
                continue
            if isinstance(a, str) and a.split(":")[0].lower() in _DEVICES:
                continue  # device placement: no-op by design
            if type(a).__name__.endswith("Place"):
                continue  # CPUPlace/TPUPlace/CUDAPlace objects: placement no-op
            if isinstance(a, bool):
                continue  # blocking= flag
            try:
                nd = dtype_mod.convert_dtype(a)
            except TypeError:
                raise ValueError(
                    f"Tensor.to(): cannot interpret {a!r} as a device, "
                    f"dtype, or Tensor")
            if nd is None:
                raise ValueError(
                    f"Tensor.to(): cannot interpret {a!r} as a device, "
                    f"dtype, or Tensor")
            out = out.astype(nd)
        return out

    # -- autograd ---------------------------------------------------------- #

    def backward(self, grad_tensor=None, retain_graph=False):
        backward(self, grad_tensor, retain_graph)

    def _accumulate_grad(self, g):
        if self._hooks:
            for fn in list(self._hooks.values()):
                out = fn(Tensor(g))
                if out is not None:
                    g = out._value if isinstance(out, Tensor) else out
        self._raw_accumulate_grad(g)

    def _raw_accumulate_grad(self, g):
        if g.dtype != self._value.dtype:
            g = g.astype(self._value.dtype)
        if self.grad is None:
            self.grad = Tensor(g, stop_gradient=True)
        else:
            self.grad = Tensor(self.grad._value + g, stop_gradient=True)

    def retain_grads(self):
        """Make backward() populate .grad on this non-leaf tensor
        (reference: Tensor._retain_grads / retain_graph semantics)."""
        self._retain_grads = True

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def register_hook(self, fn):
        if self._hooks is None:
            self._hooks = {}
        hid = len(self._hooks)
        while hid in self._hooks:
            hid += 1
        self._hooks[hid] = fn

        class _Handle:
            def __init__(self, hooks, key):
                self._hooks, self._key = hooks, key

            def remove(self):
                self._hooks.pop(self._key, None)

        return _Handle(self._hooks, hid)

    @property
    def requires_grad(self):
        return not self.stop_gradient

    @requires_grad.setter
    def requires_grad(self, flag):
        self.stop_gradient = not flag

    # -- in-place-style helpers (JAX arrays are immutable; these rebind) ---- #

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        value = jnp.asarray(value)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._value.shape}"
            )
        self._value = value.astype(self._value.dtype)
        return self

    def copy_(self, other, *_):
        return self.set_value(other)

    def _inplace_update(self, new_tensor: "Tensor"):
        """Rebind this handle to a tape-produced value (x[i]=v, x.add_(y), ...).

        If the producing node holds this same handle as an input, swap in a
        snapshot carrying the pre-update tape state so the graph stays acyclic.
        In-place mutation of a *leaf* that requires grad is an error, matching
        the reference ("leaf Variable that requires grad is used in an
        in-place operation").
        """
        node = new_tensor._grad_node
        if node is not None and any(t is self for t in node.inputs):
            if self._grad_node is None and not self.stop_gradient:
                raise RuntimeError(
                    "in-place operation on a leaf Tensor that requires grad "
                    "is not allowed; use .detach() or no_grad(), or assign "
                    "with set_value()"
                )
            snap = Tensor(self._value, stop_gradient=self.stop_gradient, name=self.name)
            snap._grad_node = self._grad_node
            snap._out_index = self._out_index
            snap._hooks = self._hooks
            snap._retain_grads = self._retain_grads
            node.inputs = [snap if t is self else t for t in node.inputs]
        self._value = new_tensor._value
        self._grad_node = new_tensor._grad_node
        self._out_index = new_tensor._out_index
        if node is not None:
            # this handle is now the node's output: route hooks/retain here
            import weakref

            node.weak_outputs = [
                weakref.ref(self) if w() is new_tensor else w
                for w in node.weak_outputs
            ]
        return self

    # -- indexing ---------------------------------------------------------- #

    def __getitem__(self, idx):
        idx = _normalize_index(idx)
        return run_op("getitem", lambda a: a[idx], [self])

    def __setitem__(self, idx, value):
        idx = _normalize_index(idx)
        if isinstance(value, Tensor):
            out = run_op(
                "setitem",
                lambda a, v: a.at[idx].set(v.astype(a.dtype)),
                [self, value],
            )
        else:
            val = value
            out = run_op("setitem", lambda a: a.at[idx].set(val), [self])
        self._inplace_update(out)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __array__(self, dtype=None):
        arr = np.asarray(self._value)
        return arr.astype(dtype) if dtype is not None else arr

    # dim helpers
    def dim(self):
        return self.ndim

    def numel(self):
        return self.size


class Parameter(Tensor):
    """Trainable tensor (reference: python/paddle/base/framework.py EagerParamBase);
    stop_gradient defaults to False and it carries a trainable flag."""

    __slots__ = ("trainable", "optimize_attr", "is_distributed", "regularizer",
                 "need_clip",
                 # False on the non-owner copy of a weight tied across
                 # pipeline stages (reference shared-param convention) so
                 # distributed grad-norm reductions count it exactly once
                 "is_firstly_shared",
                 # f32 grad accumulator for the eager mixed-precision path
                 # (fleet/utils/mix_precision_utils.py MixPrecisionLayer)
                 "main_grad", "_register_grad_hook_handle")

    def __init__(self, value, trainable: bool = True, name: str | None = None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.is_distributed = False
        self.is_firstly_shared = True
        self.regularizer = None
        self.need_clip = True
        # distributed placement: a jax PartitionSpec (or None = replicated);
        # consumed by distributed.DistributedTrainStep (GSPMD partitioning)
        self.dist_attr = None


def _normalize_index(idx):
    def conv(x):
        if isinstance(x, Tensor):
            return x._value
        return x

    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


# --------------------------------------------------------------------------- #
# op execution
# --------------------------------------------------------------------------- #

# Eager dispatch cache: (op name, fn code, value-hashed closure/defaults,
# input avals, grad?) -> jitted executables. The reference makes the per-op
# path microsecond-scale with generated C++ ad_func entry points
# (eager_gen.py); here the same role is played by caching one compiled XLA
# program (and one compiled VJP pair) per op signature, so eager mode stops
# re-tracing fn / jax.vjp on every call. Keys hash the *values* of fn's
# closure cells and defaults, so attr changes (axis=0 vs axis=1) key
# separately; ops whose closures hold unhashable objects (arrays, rich
# objects) or whose bodies cannot be jitted (value-dependent output shapes)
# fall back to the uncached path permanently (per code object).

from collections import OrderedDict as _OrderedDict


class _NoKey(Exception):
    pass


def _token(v, depth=0):
    """Hashable token reflecting the VALUE of a closure cell / default."""
    if depth > 4:
        raise _NoKey
    if v is None:
        return v
    if isinstance(v, (int, float, bool, complex)):
        # type-tagged: 1 == 1.0 == True hash-equal, but an int constant baked
        # into a trace produces different output dtype than a float
        return (type(v).__name__, v)
    if isinstance(v, (str, bytes)):
        return v
    if isinstance(v, slice):
        return ("sl", _token(v.start, depth + 1), _token(v.stop, depth + 1),
                _token(v.step, depth + 1))
    if isinstance(v, np.dtype):
        return ("dt", v.str)
    if isinstance(v, type):
        return ("ty", v.__module__, v.__qualname__)
    if isinstance(v, (tuple, list)):
        return ("sq", isinstance(v, tuple),
                tuple(_token(x, depth + 1) for x in v))
    if isinstance(v, dict):
        return ("dc", tuple(sorted(
            ((repr(k), _token(x, depth + 1)) for k, x in v.items()))))
    if callable(v) and hasattr(v, "__code__"):
        return _fn_token(v, depth + 1)
    raise _NoKey


def _fn_token(fn, depth=0):
    code = getattr(fn, "__code__", None)
    if code is None:
        # ufuncs / builtins: module-level singletons, key by the object
        try:
            hash(fn)
        except TypeError:
            raise _NoKey
        return ("obj", fn)
    try:
        cells = fn.__closure__ or ()
        return ("fn", code,
                tuple(_token(c.cell_contents, depth + 1) for c in cells),
                tuple(_token(d, depth + 1) for d in (fn.__defaults__ or ())))
    except (_NoKey, ValueError):  # ValueError: empty cell
        raise _NoKey


_DISPATCH_CACHE: "_OrderedDict[tuple, tuple]" = _OrderedDict()
_DISPATCH_CAP = 8192
_UNCACHEABLE: set = set()  # (name, code) pairs that failed to jit
_dispatch_stats = {"hits": 0, "misses": 0, "bypass": 0}
_bypassed_ops: dict = {}  # op name -> eager-bypass count (hot ops visible)
_dispatch_lock = threading.Lock()


def dispatch_cache_stats():
    """Cache counters plus the op names that are NOT being cached:
    "uncacheable_ops" = blacklisted after a failed jit (every call of these
    retraces eagerly — a hot op here is a perf regression), "bypassed_ops" =
    per-name eager-bypass counts (unhashable closures, blacklist hits)."""
    with _dispatch_lock:
        stats = dict(_dispatch_stats)
        stats["uncacheable_ops"] = sorted({n for n, _ in _UNCACHEABLE})
        stats["bypassed_ops"] = dict(_bypassed_ops)
    return stats


def _mark_uncacheable(failed_pair):
    with _dispatch_lock:
        if failed_pair in _UNCACHEABLE:
            return
        _UNCACHEABLE.add(failed_pair)
    import warnings

    warnings.warn(
        f"op '{failed_pair[0]}' could not be jit-compiled and is now "
        "permanently dispatched eagerly (per-call retrace); see "
        "dispatch_cache_stats()['uncacheable_ops']",
        RuntimeWarning, stacklevel=3)


def clear_dispatch_cache():
    with _dispatch_lock:
        _DISPATCH_CACHE.clear()
        _UNCACHEABLE.clear()
        _bypassed_ops.clear()
        _dispatch_stats.update(hits=0, misses=0, bypass=0)


def _dispatch_key(name, fn, values, need_grad):
    try:
        if (name, getattr(fn, "__code__", fn)) in _UNCACHEABLE:
            return None
        # weak_type matters: jax.jit retraces on weak-vs-strong scalars, and
        # two traces under one entry would desynchronize the bwd treedef
        avals = tuple(
            (v.shape, str(v.dtype), bool(getattr(v, "weak_type", False)))
            for v in values
        )
        return (name, _fn_token(fn), avals, need_grad)
    except (_NoKey, TypeError, AttributeError):
        return None


def _cache_get(key):
    with _dispatch_lock:
        entry = _DISPATCH_CACHE.get(key)
        if entry is not None:
            _DISPATCH_CACHE.move_to_end(key)
            _dispatch_stats["hits"] += 1
        return entry


def _cache_put(key, entry):
    with _dispatch_lock:
        _dispatch_stats["misses"] += 1
        _DISPATCH_CACHE[key] = entry
        if len(_DISPATCH_CACHE) > _DISPATCH_CAP:
            _DISPATCH_CACHE.popitem(last=False)


def _make_grad_pair(fn):
    """Jitted (fwd, bwd): fwd returns (out, flat residuals); bwd reapplies.

    jax.vjp's returned Partial is a pytree whose leaves are the residual
    arrays; its treedef (the staged backward computation) is static per
    input-aval signature, which is exactly our cache granularity — so the
    treedef captured at fwd trace time is the right one for every bwd call
    through this entry.
    """
    store = {}

    def fwd_raw(*xs):
        out, vjp_fn = jax.vjp(fn, *xs)
        res, tree = jax.tree_util.tree_flatten(vjp_fn)
        # first trace wins; the outer cache key (avals incl. weak_type) gives
        # one trace per entry, and _finish_op guards the leaf count so a
        # pathological retrace degrades to an error, never silent corruption
        store.setdefault("tree", tree)
        store.setdefault("n_res", len(res))
        return out, res

    def bwd_raw(res, cts):
        vjp_fn = jax.tree_util.tree_unflatten(store["tree"], res)
        return vjp_fn(cts)

    return jax.jit(fwd_raw), jax.jit(bwd_raw), store


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor (reference: python/paddle/tensor/creation.py to_tensor)."""
    del place
    if isinstance(data, Tensor):
        val = data._value
        if dtype is not None:
            nd = dtype_mod.convert_dtype(dtype)
            if np.dtype(val.dtype) != nd:
                val = val.astype(jnp.dtype(nd))
        return Tensor(val, stop_gradient=stop_gradient)
    nd = dtype_mod.convert_dtype(dtype)
    if nd is None and isinstance(data, (float,)):
        nd = dtype_mod.default_float_dtype()
    if nd is None and isinstance(data, (list, tuple)):
        flat = np.asarray(data)
        if flat.dtype == np.float64:
            nd = dtype_mod.default_float_dtype()
    if nd is None and isinstance(data, np.ndarray) and data.dtype == np.float64:
        # match paddle: numpy float64 keeps its dtype only when explicit;
        # default behavior converts to default dtype
        nd = data.dtype
    host_src = not isinstance(data, jax.Array) and not (
        isinstance(data, jax.core.Tracer))
    val = jnp.asarray(data, dtype=None if nd is None else jnp.dtype(nd))
    t = Tensor(val, stop_gradient=stop_gradient)
    t._host_const = host_src
    return t


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._value
    return x


def as_tensors(args) -> list[Tensor]:
    return [a if isinstance(a, Tensor) else to_tensor(a) for a in args]


# Op event hook (profiler): fn(op_name, start_ns, end_ns) called around every
# eager dispatch — the host-side analog of the reference's RecordEvent
# instrumentation in the generated ad_func bodies
# (paddle/fluid/eager/api/manual/eager_manual/forwards/*.cc RecordEvent).
_op_event_hook: Callable | None = None

# Op check hook (amp.debugging / FLAGS_check_nan_inf): fn(op_name, result)
# called on every eager dispatch result; may raise — the analog of the
# reference's CheckTensorHasNanOrInf pass (paddle/fluid/eager/nan_inf_utils.cc).
_op_check_hook: Callable | None = None


def set_op_event_hook(fn):
    global _op_event_hook
    _op_event_hook = fn


def set_op_check_hook(fn):
    global _op_check_hook
    _op_check_hook = fn


# Op recorder (paddle.static Program capture): fn(op_name, fn, inputs, result)
# called after every eager dispatch — the analog of the reference's static
# Program op-desc appending under program_guard (python/paddle/base/
# framework.py append_op).
_op_recorder: Callable | None = None
# Called with (kind, tensor) when Python control flow consumes a concrete
# tensor value (__bool__/__int__/__float__) — the graph-break points the SOT
# capture (jit/sot.py) segments compiled subgraphs around.
#
# Same two-surface model as the op-input interceptor: a base slot (set_* —
# the SOT capture save/restores it around a recording) plus an additive
# chain (add_*/remove_* — graftlint runtime sync enforcement, telemetry's
# StepTimeline). A chained observer returning non-None proposes a
# replacement value for `item()` (last non-None wins, base first).
_sync_observer: Callable | None = None
_base_sync_observer: Callable | None = None
_sync_observer_chain: list = []


def set_op_recorder(fn):
    global _op_recorder
    _op_recorder = fn


def _compose_sync_observer():
    global _sync_observer
    base, chain = _base_sync_observer, tuple(_sync_observer_chain)
    if not chain:
        _sync_observer = base
        return
    if base is None and len(chain) == 1:
        _sync_observer = chain[0]
        return

    def _dispatch(kind, tensor, _base=base, _chain=chain):
        rep = _base(kind, tensor) if _base is not None else None
        for fn in _chain:
            out = fn(kind, tensor)
            if out is not None:
                rep = out
        return rep

    _sync_observer = _dispatch


def set_sync_observer(fn):
    """Install/replace the base observer; returns the previous base. NEVER
    read `core._sync_observer` to save state — that is the composed dispatch
    slot, and re-setting it as a base would double-fire the chain."""
    global _base_sync_observer
    prev = _base_sync_observer
    _base_sync_observer = fn
    _compose_sync_observer()
    return prev


def add_sync_observer(fn):
    """Append `fn` to the sync-observer chain; returns `fn` for remove_*."""
    _sync_observer_chain.append(fn)
    _compose_sync_observer()
    return fn


def remove_sync_observer(fn):
    try:
        _sync_observer_chain.remove(fn)
    except ValueError:
        pass
    _compose_sync_observer()


def run_op(name: str, fn: Callable, inputs: Sequence, n_outputs: int | None = None):
    ev, ck, rec = _op_event_hook, _op_check_hook, _op_recorder
    if ev is None and ck is None and rec is None:
        return _run_op_impl(name, fn, inputs, n_outputs)
    import time

    t0 = time.perf_counter_ns() if ev is not None else 0
    try:
        out = _run_op_impl(name, fn, inputs, n_outputs)
    finally:
        if ev is not None:
            ev(name, t0, time.perf_counter_ns())
    if ck is not None:
        ck(name, out)
    if rec is not None:
        rec(name, fn, inputs, out)
    return out


def _run_op_impl(name: str, fn: Callable, inputs: Sequence, n_outputs: int | None = None):
    """Execute `fn(*raw_values)` and record it on the tape when needed.

    This is the entire analog of the reference's generated `<op>_ad_func` entry
    points (paddle/fluid/eager/auto_code_generator/generator/eager_gen.py):
    autocast interception, forward execution, grad-node wiring.

    `fn` must be a pure jax-traceable function of the tensor inputs only
    (non-tensor attrs are captured in its closure). Multiple outputs are
    returned as a tuple of Tensors when fn returns a tuple.
    """
    tensors = [a if isinstance(a, Tensor) else to_tensor(a) for a in inputs]
    values = [t._value for t in tensors]

    if _op_input_interceptor is not None:
        values = _op_input_interceptor(name, values)

    need_grad = (
        is_grad_enabled()
        and not in_tracing()
        and any(not t.stop_gradient or t._grad_node is not None for t in tensors)
    )

    # Dispatch cache lookup — bypassed inside traces (the functional/jit path
    # must stay a plain trace) and for tracer inputs.
    key = None
    if not in_tracing() and not any(isinstance(v, jax.core.Tracer) for v in values):
        key = _dispatch_key(name, fn, values, need_grad)
    failed_pair = None
    if key is not None:
        entry = _cache_get(key)
        if entry is None:
            try:
                if need_grad:
                    fwd, bwd, store = _make_grad_pair(fn)
                    out, res = fwd(*values)  # trace + compile now
                    entry = ("grad", fwd, bwd, store, fn)
                else:
                    jfn = jax.jit(fn)
                    out = jfn(*values)
                    entry = ("nograd", jfn, fn)
                _cache_put(key, entry)
            except Exception:
                # fn may not be jittable (e.g. value-dependent output shape)
                # — or the call itself may be bad (shape mismatch). Fall
                # through to the eager path; blacklist only if eager succeeds.
                failed_pair = (name, getattr(fn, "__code__", fn))
                entry = None
            if entry is not None:
                return _finish_op(name, out, res if need_grad else None,
                                  entry, tensors, need_grad)
        else:
            if need_grad:
                out, res = entry[1](*values)
                return _finish_op(name, out, res, entry, tensors, True)
            out = entry[1](*values)
            return _finish_op(name, out, None, entry, tensors, False)
    else:
        with _dispatch_lock:
            _dispatch_stats["bypass"] += 1
            if not in_tracing():  # only hot eager calls, not jit-trace passes
                _bypassed_ops[name] = _bypassed_ops.get(name, 0) + 1

    if not need_grad:
        out = fn(*values)
        if failed_pair is not None:
            _mark_uncacheable(failed_pair)
        if isinstance(out, tuple):
            return tuple(Tensor(o) for o in out)
        return Tensor(out)

    out, vjp_fn = jax.vjp(fn, *values)
    if failed_pair is not None:
        _mark_uncacheable(failed_pair)
    return _wrap_grad_outputs(name, out, vjp_fn, tensors, fn)


def _wrap_grad_outputs(name, out, vjp_fn, tensors, fwd_fn=None):
    """Tape wiring shared by the cached and uncached grad paths."""
    multi = isinstance(out, tuple)
    outs = out if multi else (out,)
    avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs]
    node = GradNode(name, vjp_fn, tensors, avals, fwd_fn)
    result = []
    for i, o in enumerate(outs):
        t = Tensor(o, stop_gradient=False)
        t._grad_node = node
        t._out_index = i
        result.append(t)
    node.set_outputs(result)
    return tuple(result) if multi else result[0]


def _finish_op(name, out, res, entry, tensors, need_grad):
    """Wrap cached-dispatch outputs into Tensors (+ tape node when needed)."""
    if not need_grad:
        if isinstance(out, tuple):
            return tuple(Tensor(o) for o in out)
        return Tensor(out)
    bwd, store = entry[2], entry[3]
    if len(res) != store.get("n_res", len(res)):
        raise RuntimeError(
            f"dispatch cache: op '{name}' retraced with a different residual "
            "structure; clear_dispatch_cache() and report this op")
    vjp_fn = lambda cts: bwd(res, cts)  # noqa: E731
    return _wrap_grad_outputs(name, out, vjp_fn, tensors, entry[4])
