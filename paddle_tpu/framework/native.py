"""Loader for the native C++ runtime library (native/*.cc).

Reference analogs: the pybind layer (paddle/fluid/pybind/) binding phi's C++
runtime into python. Here the runtime pieces that must be native (socket
rendezvous, watchdog thread, shm transport) live in
libpaddle_tpu_native.so, bound via ctypes; everything compute-side is XLA.

The library is built lazily with `make -C native` on first use and cached;
all consumers degrade gracefully (pure-python fallbacks) when no compiler
is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lib = None
_lock = threading.Lock()
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO = os.path.join(_NATIVE_DIR, "libpaddle_tpu_native.so")


def _build():
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _stale():
    """True if any native source is newer than the built .so."""
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    for f in os.listdir(_NATIVE_DIR):
        if f.endswith((".cc", ".h")) or f == "Makefile":
            if os.path.getmtime(os.path.join(_NATIVE_DIR, f)) > so_mtime:
                return True
    return False


def load():
    """Return the ctypes lib, (re)building when sources changed; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        # Rebuild whenever a source file is newer than the .so — a prebuilt
        # library must never mask edits to native/*.cc. An exclusive file
        # lock serializes concurrent ranks on one host (all ranks' first
        # load() would otherwise race `make` against a sibling's dlopen);
        # held through CDLL so no sibling truncates the .so mid-map. If no
        # toolchain is available, fall back to an existing (possibly stale)
        # build.
        import fcntl

        lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
        try:
            lock_fd = open(lock_path, "w")
            fcntl.flock(lock_fd, fcntl.LOCK_EX)
        except OSError:
            lock_fd = None
        try:
            if _stale() and not _build() and not os.path.exists(_SO):
                return None
            try:
                lib = ctypes.CDLL(_SO)
            except OSError:
                return None
        finally:
            if lock_fd is not None:
                try:
                    fcntl.flock(lock_fd, fcntl.LOCK_UN)
                except OSError:
                    pass
                lock_fd.close()
        # tcp store
        lib.tcp_store_server_start.restype = ctypes.c_void_p
        lib.tcp_store_server_start.argtypes = [ctypes.c_int]
        lib.tcp_store_server_port.restype = ctypes.c_int
        lib.tcp_store_server_port.argtypes = [ctypes.c_void_p]
        lib.tcp_store_server_stop.argtypes = [ctypes.c_void_p]
        lib.tcp_store_connect.restype = ctypes.c_ssize_t
        lib.tcp_store_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                          ctypes.c_int, ctypes.c_int]
        lib.tcp_store_set.restype = ctypes.c_int
        lib.tcp_store_set.argtypes = [ctypes.c_ssize_t, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_long]
        lib.tcp_store_get.restype = ctypes.c_long
        lib.tcp_store_get.argtypes = [ctypes.c_ssize_t, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_long]
        if hasattr(lib, "tcp_store_tryget"):  # absent in pre-existing builds
            lib.tcp_store_tryget.restype = ctypes.c_long
            lib.tcp_store_tryget.argtypes = [ctypes.c_ssize_t, ctypes.c_char_p,
                                             ctypes.c_char_p, ctypes.c_long]
        lib.tcp_store_add.restype = ctypes.c_int
        lib.tcp_store_add.argtypes = [ctypes.c_ssize_t, ctypes.c_char_p,
                                      ctypes.c_longlong,
                                      ctypes.POINTER(ctypes.c_longlong)]
        lib.tcp_store_wait.restype = ctypes.c_int
        lib.tcp_store_wait.argtypes = [ctypes.c_ssize_t, ctypes.c_char_p]
        lib.tcp_store_delete.restype = ctypes.c_int
        lib.tcp_store_delete.argtypes = [ctypes.c_ssize_t, ctypes.c_char_p]
        lib.tcp_store_close.argtypes = [ctypes.c_ssize_t]
        # watchdog
        lib.watchdog_create.restype = ctypes.c_void_p
        lib.watchdog_create.argtypes = [ctypes.c_long]
        lib.watchdog_destroy.argtypes = [ctypes.c_void_p]
        lib.watchdog_register.restype = ctypes.c_longlong
        lib.watchdog_register.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_long]
        lib.watchdog_complete.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.watchdog_timeout_count.restype = ctypes.c_longlong
        lib.watchdog_timeout_count.argtypes = [ctypes.c_void_p]
        lib.watchdog_drain_report.restype = ctypes.c_long
        lib.watchdog_drain_report.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                              ctypes.c_long]
        lib.watchdog_inflight.restype = ctypes.c_longlong
        lib.watchdog_inflight.argtypes = [ctypes.c_void_p]
        # shm ring
        lib.shm_ring_create.restype = ctypes.c_void_p
        lib.shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_long]
        lib.shm_ring_attach.restype = ctypes.c_void_p
        lib.shm_ring_attach.argtypes = [ctypes.c_char_p]
        lib.shm_ring_push.restype = ctypes.c_int
        lib.shm_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_long]
        lib.shm_ring_pop.restype = ctypes.c_long
        lib.shm_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_long]
        lib.shm_ring_peek.restype = ctypes.c_long
        lib.shm_ring_peek.argtypes = [ctypes.c_void_p]
        lib.shm_ring_close.argtypes = [ctypes.c_void_p]
        lib.shm_ring_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None
