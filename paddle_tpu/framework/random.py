"""Global RNG state.

The reference keeps per-device Philox generators (paddle/phi/core/generator.h)
seeded by `paddle.seed`. On TPU/JAX randomness is functional: we keep one global
threefry key and split it per draw. Under tracing (jit), stateful splitting would
leak host state into the trace, so traced code should use `split_for_trace` keys
captured at trace time, or the nn-layer RNG plumbing (see paddle_tpu.jit).
"""

from __future__ import annotations

import threading

import jax

_state = threading.local()


def _get_key():
    key = getattr(_state, "key", None)
    if key is None:
        key = jax.random.PRNGKey(0)
        _state.key = key
    return key


def seed(value: int):
    """Set the global RNG seed (paddle.seed equivalent;
    reference: python/paddle/framework/random.py:seed)."""
    _state.key = jax.random.PRNGKey(int(value))
    return None


def next_key():
    """Split the global key and return a fresh subkey (stateful, eager-only)."""
    key = _get_key()
    key, sub = jax.random.split(key)
    _state.key = key
    return sub


def rng_tensor():
    """A fresh subkey wrapped as a Tensor and tagged `_rng_key`.

    Random ops must pass THIS as a run_op input (never close over the raw
    key): the tagged input keeps the op's closure hashable for the dispatch
    cache, and tells the SOT capture (jit/sot.py) to re-draw the key on
    every segment replay instead of freezing the record-time draw."""
    from .core import Tensor

    t = Tensor(next_key(), stop_gradient=True)
    t._rng_key = True
    return t


def get_rng_state():
    return (_get_key(),)


def set_rng_state(state):
    _state.key = state[0]


class rng_guard:
    """Context manager that snapshots/restores the global RNG state
    (analog of the reference's RNG-state preservation in recompute,
    python/paddle/distributed/fleet/recompute/recompute.py)."""

    def __init__(self, key=None):
        self._key = key
        self._saved = None

    def __enter__(self):
        self._saved = get_rng_state()
        if self._key is not None:
            _state.key = self._key
        return self

    def __exit__(self, *exc):
        set_rng_state(self._saved)
        return False
