"""Global flag registry.

Reference: paddle/common/flags.h:38-83 + flags_native.cc expose 187 runtime
flags through paddle.set_flags/get_flags (python/paddle/base/framework.py:132,157).
Here flags are a plain registry; the handful that matter on TPU are wired to
jax.config / XLA options, the rest are accepted and stored so reference-style
scripts keep working.
"""

from __future__ import annotations

import os
from typing import Any

_FLAGS: dict[str, Any] = {
    # numerics
    "FLAGS_check_nan_inf": False,
    "FLAGS_check_nan_inf_level": 0,
    "FLAGS_cudnn_deterministic": False,  # maps to deterministic XLA reductions
    "FLAGS_embedding_deterministic": 0,
    # memory (informational on TPU; XLA/PJRT owns HBM)
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_eager_delete_tensor_gb": 0.0,
    # matmul precision: 'default' | 'high' | 'highest'
    "FLAGS_matmul_precision": "default",
    # distributed
    "FLAGS_distributed_collective_timeout_s": 600,
    "FLAGS_benchmark": False,
}


def _load_env():
    for k in list(os.environ):
        if k.startswith("FLAGS_"):
            v = os.environ[k]
            if v.lower() in ("true", "false"):
                _FLAGS[k] = v.lower() == "true"
            else:
                try:
                    _FLAGS[k] = int(v)
                except ValueError:
                    try:
                        _FLAGS[k] = float(v)
                    except ValueError:
                        _FLAGS[k] = v


_load_env()


def set_flags(flags: dict):
    """paddle.set_flags equivalent."""
    import jax

    for k, v in flags.items():
        _FLAGS[k] = v
        if k == "FLAGS_matmul_precision":
            jax.config.update(
                "jax_default_matmul_precision",
                {"default": None, "high": "bfloat16_3x", "highest": "float32"}.get(v, None),
            )


def get_flags(keys):
    if isinstance(keys, str):
        keys = [keys]
    return {k: _FLAGS.get(k) for k in keys}
