"""Incubate optimizers (reference: python/paddle/incubate/optimizer/ —
lookahead.py LookAhead, modelaverage.py ModelAverage; LBFGS and the fused
LAMB live in paddle.optimizer / the ZeRO-sharded update respectively)."""

from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import no_grad

__all__ = ["LookAhead", "ModelAverage"]


def _flat_params(plist):
    out = []
    for p in plist or []:
        out.extend(p["params"] if isinstance(p, dict) else [p])
    return out


class LookAhead:
    """reference: incubate/optimizer/lookahead.py — k fast steps with the
    inner optimizer, then slow weights move alpha toward the fast weights
    and the fast weights reset to the slow ones (Zhang et al. 2019)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be in [0, 1], got {alpha}")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_count = 0
        self._slow = None  # param id -> slow-weight value

    def __getattr__(self, name):
        return getattr(self.__dict__["inner_optimizer"], name)

    @no_grad()
    def step(self):
        params = _flat_params(self.inner_optimizer._parameter_list)
        if self._slow is None:
            self._slow = {id(p): p._value for p in params}
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            for p in params:
                slow = self._slow.get(id(p))
                if slow is None:
                    slow = p._value
                slow = slow + self.alpha * (p._value - slow)
                p._value = slow
                self._slow[id(p)] = slow

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["@lookahead_step"] = self._step_count
        if self._slow is not None:
            # slow weights are core LookAhead state: without them a resume
            # re-snapshots from the fast weights and changes the trajectory
            import numpy as np

            params = _flat_params(self.inner_optimizer._parameter_list)
            sd["@lookahead_slow"] = [
                np.asarray(self._slow[id(p)]) for p in params]
        return sd

    def set_state_dict(self, state):
        self._step_count = state.pop("@lookahead_step", 0)
        slow = state.pop("@lookahead_slow", None)
        out = self.inner_optimizer.set_state_dict(state)
        if slow is not None:
            params = _flat_params(self.inner_optimizer._parameter_list)
            self._slow = {id(p): jnp.asarray(v)
                          for p, v in zip(params, slow)}
        return out


class ModelAverage:
    """reference: incubate/optimizer/modelaverage.py — running average of
    parameter values over a trailing window; apply()/restore() swap the
    averaged weights in for evaluation."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._params = _flat_params(parameters)
        self._sum = {id(p): jnp.zeros_like(p._value) for p in self._params}
        self._num = 0
        self._backup = None

    @no_grad()
    def step(self):
        """Accumulate the current parameter values (call after the real
        optimizer's step). The trailing window restarts when the
        accumulation count exceeds min(max_average_window,
        num_updates * average_window_rate) — the reference's rate-scaled
        window."""
        self._updates = getattr(self, "_updates", 0) + 1
        self._num += 1
        window = min(self.max_average_window,
                     max(self.min_average_window,
                         int(self._updates * self.average_window)))
        restart = self._num > window
        for p in self._params:
            if restart:
                self._sum[id(p)] = p._value
            else:
                self._sum[id(p)] = self._sum[id(p)] + p._value
        if restart:
            self._num = 1

    def clear_grad(self, set_to_zero=True):
        for p in self._params:
            p.clear_grad()

    @no_grad()
    def apply(self, executor=None, need_restore=True):
        """Swap the averaged weights in (context-manager style supported)."""
        if self._num == 0:
            return self
        self._backup = {id(p): p._value for p in self._params}
        for p in self._params:
            p._value = (self._sum[id(p)] / self._num).astype(p._value.dtype)
        self._need_restore = need_restore
        return self

    @no_grad()
    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._value = self._backup[id(p)]
        self._backup = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if getattr(self, "_need_restore", True):
            self.restore()
        return False
