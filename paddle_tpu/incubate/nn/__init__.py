from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm,
    FusedFeedForward,
    FusedMultiHeadAttention,
    FusedMultiTransformer,
    FusedTransformerEncoderLayer,
)

__all__ = ["functional", "FusedMultiTransformer", "FusedMultiHeadAttention",
           "FusedFeedForward", "FusedTransformerEncoderLayer",
           "FusedBiasDropoutResidualLayerNorm"]
