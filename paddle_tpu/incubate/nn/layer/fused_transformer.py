"""FusedMultiTransformer — the fused decoder stack for inference.

Reference: python/paddle/incubate/nn/layer/fused_transformer.py:1071
(FusedMultiTransformer) backed by the monolithic CUDA kernel
fusion/gpu/fused_multi_transformer_kernel.cu (963 lines) +
fused_multi_transformer_op.cu.h (3097 lines): per layer
LN → fused QKV GEMM → cached attention → out proj → FFN, all in one launch.

TPU-native redesign: per-layer weights are STACKED into [L, ...] arrays and
the whole stack is ONE lax.scan over layers inside one jit — XLA fuses each
layer body (the reference's hand-fusion) and the scan keeps compile time and
program size O(1) in depth. KV caches are functional state threaded through
the scan, shaped [L, 2, B, S_max, Hkv, D].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn
from ....framework.core import Tensor, run_op
from ....nn.functional._attn_math import masked_attention as _masked_attention

__all__ = ["FusedMultiTransformer"]


class FusedMultiTransformer(nn.Layer):
    """API-parity subset of the reference layer: normalize_before=True,
    layernorm/rmsnorm, gelu/relu activation, optional GQA, optional rope.
    Quant, beam search, ring_id TP and pre_caches are not supported."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, ln_scale_attrs=None,
                 ln_bias_attrs=None, qkv_weight_attrs=None, qkv_bias_attrs=None,
                 linear_weight_attrs=None, linear_bias_attrs=None,
                 ffn_ln_scale_attrs=None, ffn_ln_bias_attrs=None,
                 ffn1_weight_attrs=None, ffn1_bias_attrs=None,
                 ffn2_weight_attrs=None, ffn2_bias_attrs=None, epsilon=1e-5,
                 residual_alpha=1.0, num_layers=-1, nranks=1, trans_qkvw=True,
                 ring_id=-1, norm_type="layernorm", use_neox_rotary_style=False,
                 gqa_group_size=-1, name=None):
        super().__init__()
        assert normalize_before, "only pre-norm is supported (LLM decoders)"
        assert norm_type in ("layernorm", "rmsnorm")
        assert activation in ("gelu", "relu")
        if num_layers < 0:
            ws = qkv_weight_attrs
            assert isinstance(ws, (list, tuple)), \
                "num_layers or per-layer attr lists required"
            num_layers = len(ws)
        self.num_layers = num_layers
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.kv_heads = num_heads if gqa_group_size <= 0 \
            else num_heads // gqa_group_size
        self.head_dim = embed_dim // num_heads
        self.dim_feedforward = dim_feedforward
        self.activation = activation
        self.norm_type = norm_type
        self.epsilon = epsilon
        self.residual_alpha = residual_alpha
        self.use_neox_rotary_style = use_neox_rotary_style

        L, M, F = num_layers, embed_dim, dim_feedforward
        H, Hkv, D = self.num_heads, self.kv_heads, self.head_dim
        qkv_out = (H + 2 * Hkv) * D
        mk = self.create_parameter
        self.ln_scale = mk([L, M], default_initializer=nn.initializer.Constant(1.0))
        self.ln_bias = mk([L, M], is_bias=True)
        # trans_qkvw layout (reference default): [qkv_out, M]
        self.qkv_weight = mk([L, qkv_out, M])
        self.qkv_bias = mk([L, qkv_out], is_bias=True)
        self.linear_weight = mk([L, H * D, M])
        self.linear_bias = mk([L, M], is_bias=True)
        self.ffn_ln_scale = mk([L, M], default_initializer=nn.initializer.Constant(1.0))
        self.ffn_ln_bias = mk([L, M], is_bias=True)
        self.ffn1_weight = mk([L, M, F])
        self.ffn1_bias = mk([L, F], is_bias=True)
        self.ffn2_weight = mk([L, F, M])
        self.ffn2_bias = mk([L, M], is_bias=True)

    def init_caches(self, batch_size, max_seq_len, dtype="float32"):
        """[L, 2, B, S_max, Hkv, D] functional KV cache."""
        shape = (self.num_layers, 2, batch_size, max_seq_len,
                 self.kv_heads, self.head_dim)
        return Tensor(jnp.zeros(shape, jnp.dtype(dtype)))

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, beam_offset=None,
                seq_lens=None, time_step=None):
        """src [B, S, M]. With caches: returns (out, new_caches); time_step is
        the decode offset (scalar Tensor/int; None = prefill at offset 0)."""
        assert pre_caches is None and beam_offset is None, "unsupported"
        cfg = dict(
            H=self.num_heads, Hkv=self.kv_heads, D=self.head_dim,
            eps=self.epsilon, norm=self.norm_type, act=self.activation,
            alpha=self.residual_alpha, neox=self.use_neox_rotary_style,
            rope=rotary_embs is not None or rotary_emb_dims > 0,
        )
        params = [src, self.ln_scale, self.ln_bias, self.qkv_weight,
                  self.qkv_bias, self.linear_weight, self.linear_bias,
                  self.ffn_ln_scale, self.ffn_ln_bias, self.ffn1_weight,
                  self.ffn1_bias, self.ffn2_weight, self.ffn2_bias]
        has_cache = caches is not None
        has_mask = attn_mask is not None
        off_in = time_step if time_step is not None else 0
        if has_cache:
            params.append(caches)
        if has_mask:
            params.append(attn_mask)
        params.append(off_in if isinstance(off_in, Tensor) else Tensor(jnp.int32(off_in)))

        def fn(x, lns, lnb, wqkv, bqkv, wo, bo, flns, flnb, w1, b1, w2, b2, *rest):
            it = iter(rest)
            cache = next(it) if has_cache else None
            mask = next(it) if has_mask else None
            off = next(it).astype(jnp.int32).reshape(())
            return _fmt_stack(x, lns, lnb, wqkv, bqkv, wo, bo, flns, flnb,
                              w1, b1, w2, b2, cache, mask, off, cfg)

        out = run_op("fused_multi_transformer", fn, params)
        if has_cache:
            return out  # (hidden, new_caches)
        return out


def _norm(x, scale, bias, kind, eps):
    xf = x.astype(jnp.float32)
    if kind == "rmsnorm":
        y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
    else:
        mu = xf.mean(-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _rope(x, pos, D, neox):
    """x [B, S, H, D]; pos [S] absolute positions — reuses the shared rotary
    tables from incubate.nn.functional (fused_rope_utils.h analog)."""
    from ..functional import _apply_rope_one, _rope_tables

    cos, sin = _rope_tables(x.shape[1], D, 10000.0, x.dtype,
                            position_ids=pos[None, :])
    return _apply_rope_one(x, cos, sin, neox)


def _fmt_stack(x, lns, lnb, wqkv, bqkv, wo, bo, flns, flnb, w1, b1, w2, b2,
               cache, mask, off, cfg):
    B, S, M = x.shape
    H, Hkv, D = cfg["H"], cfg["Hkv"], cfg["D"]
    act = jax.nn.gelu if cfg["act"] == "gelu" else jax.nn.relu
    pos = off + jnp.arange(S)

    def layer(carry, p):
        h = carry
        (ls, lb, wq, bq, woi, boi, fls, flb, w1i, b1i, w2i, b2i, ci) = p
        y = _norm(h, ls, lb, cfg["norm"], cfg["eps"])
        qkv = jnp.einsum("bsm,om->bso", y, wq) + bq
        q = qkv[..., :H * D].reshape(B, S, H, D)
        k = qkv[..., H * D:(H + Hkv) * D].reshape(B, S, Hkv, D)
        v = qkv[..., (H + Hkv) * D:].reshape(B, S, Hkv, D)
        if cfg["rope"]:
            q = _rope(q, pos, D, cfg["neox"])
            k = _rope(k, pos, D, cfg["neox"])
        if ci is not None:
            kc = jax.lax.dynamic_update_slice(ci[0], k.astype(ci.dtype), (0, off, 0, 0))
            vc = jax.lax.dynamic_update_slice(ci[1], v.astype(ci.dtype), (0, off, 0, 0))
            k_all, v_all = kc, vc
            S_k = kc.shape[1]
            new_ci = jnp.stack([kc, vc], 0)
        else:
            k_all, v_all = k, v
            S_k = S
            new_ci = None
        keep = (jnp.arange(S_k)[None, :] <= pos[:, None])[None, None]
        attn = _masked_attention(q, k_all, v_all, keep=keep, add_mask=mask)
        attn = attn.reshape(B, S, H * D).astype(x.dtype)
        o = jnp.einsum("bso,om->bsm", attn, woi) + boi
        h = h * cfg["alpha"] + o
        y2 = _norm(h, fls, flb, cfg["norm"], cfg["eps"])
        f = act(jnp.einsum("bsm,mf->bsf", y2, w1i) + b1i)
        f = jnp.einsum("bsf,fm->bsm", f, w2i) + b2i
        h = h * cfg["alpha"] + f
        return h, new_ci

    if cache is not None:
        def body(h, p):
            return layer(h, p)
        params = (lns, lnb, wqkv, bqkv, wo, bo, flns, flnb, w1, b1, w2, b2, cache)
        h, new_caches = jax.lax.scan(body, x, params)
        return h, new_caches
    params = (lns, lnb, wqkv, bqkv, wo, bo, flns, flnb, w1, b1, w2, b2)
    h, _ = jax.lax.scan(lambda hh, p: layer(hh, p + (None,)), x, params)
    return h
