"""FusedMultiHeadAttention / FusedFeedForward / FusedTransformerEncoderLayer /
FusedBiasDropoutResidualLayerNorm (reference:
python/paddle/incubate/nn/layer/fused_transformer.py:103 (BDRLN), :378
(FusedMultiHeadAttention), :703 (FusedFeedForward), :870
(FusedTransformerEncoderLayer)).

Thin parameter-holders over the fused functional ops — the fusion itself
lives in functional/fused_attention_ops.py as single-XLA-program
compositions. TP: qkv/linear weights carry column/row dist_attr specs the
way the reference calls _set_var_distributed when nranks > 1."""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

import paddle_tpu.nn as nn
from ....framework.core import Tensor
from ....nn import initializer as _I

_ONES = _I.Constant(1.0)
from ..functional.fused_attention_ops import (
    fused_bias_dropout_residual_layer_norm,
    fused_feedforward,
    fused_multi_head_attention,
)

__all__ = [
    "FusedMultiHeadAttention",
    "FusedFeedForward",
    "FusedTransformerEncoderLayer",
    "FusedBiasDropoutResidualLayerNorm",
]


class FusedMultiHeadAttention(nn.Layer):
    """reference: fused_transformer.py:378."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, transpose_qkv_wb=False, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        assert not need_weights, "need_weights=True is not supported"
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self.transpose_qkv_wb = transpose_qkv_wb
        self._epsilon = epsilon
        self.name = name
        if transpose_qkv_wb:
            qkv_w_shape = [embed_dim, 3 * embed_dim]
            qkv_b_shape = [3 * embed_dim]
        else:
            qkv_w_shape = [3, num_heads, self.head_dim, embed_dim]
            qkv_b_shape = [3, num_heads, self.head_dim]
        self.qkv_weight = self.create_parameter(qkv_w_shape,
                                                attr=qkv_weight_attr)
        self.qkv_bias = (None if qkv_bias_attr is False else
                         self.create_parameter(qkv_b_shape,
                                               attr=qkv_bias_attr,
                                               is_bias=True))
        self.linear_weight = self.create_parameter(
            [num_heads * self.head_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = (None if linear_bias_attr is False else
                            self.create_parameter([embed_dim],
                                                  attr=linear_bias_attr,
                                                  is_bias=True))
        # TP layout (reference _set_var_distributed): qkv column-parallel
        # over heads, out-proj row-parallel
        if not transpose_qkv_wb:
            self.qkv_weight.dist_attr = P(None, "mp", None, None)
            if self.qkv_bias is not None:
                self.qkv_bias.dist_attr = P(None, "mp", None)
            # out-proj row-parallel only when qkv is head-sharded; the
            # transpose_qkv_wb [E, 3E] layout keeps BOTH replicated (a
            # row-parallel out-proj against an unsharded context would
            # mis-shard the matmul and the grad-norm accounting)
            self.linear_weight.dist_attr = P("mp", None)
            self.linear_weight.is_distributed = True
            self.qkv_weight.is_distributed = True
            if self.qkv_bias is not None:
                self.qkv_bias.is_distributed = True
        if normalize_before:
            self.pre_ln_scale = self.create_parameter(
                [embed_dim], attr=pre_ln_scale_attr, default_initializer=_ONES)
            self.pre_ln_bias = self.create_parameter(
                [embed_dim], attr=pre_ln_bias_attr, is_bias=True)
            self.ln_scale = None
            self.ln_bias = None
        else:
            self.pre_ln_scale = None
            self.pre_ln_bias = None
            self.ln_scale = self.create_parameter(
                [embed_dim], attr=ln_scale_attr, default_initializer=_ONES)
            self.ln_bias = self.create_parameter(
                [embed_dim], attr=ln_bias_attr, is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self._epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self._epsilon, training=self.training,
            num_heads=self.num_heads,
            transpose_qkv_wb=self.transpose_qkv_wb, name=self.name)

    def extra_repr(self):
        return (f"embed_dim={self.embed_dim}, num_heads={self.num_heads}, "
                f"dropout_rate={self.dropout_rate}, "
                f"attn_dropout_rate={self.attn_dropout_rate}, "
                f"epsilon={self._epsilon}")


class FusedFeedForward(nn.Layer):
    """reference: fused_transformer.py:703."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-05, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._d_model = d_model
        self._dim_feedforward = dim_feedforward
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                  else act_dropout_rate)
        self._act_method = activation
        self._normalize_before = normalize_before
        self._epsilon = epsilon
        self.name = name
        self._linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self._linear1_bias = (None if linear1_bias_attr is False else
                              self.create_parameter([dim_feedforward],
                                                    attr=linear1_bias_attr,
                                                    is_bias=True))
        self._linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self._linear2_bias = (None if linear2_bias_attr is False else
                              self.create_parameter([d_model],
                                                    attr=linear2_bias_attr,
                                                    is_bias=True))
        self._linear1_weight.dist_attr = P(None, "mp")
        self._linear2_weight.dist_attr = P("mp", None)
        self._linear1_weight.is_distributed = True
        self._linear2_weight.is_distributed = True
        if self._linear1_bias is not None:
            self._linear1_bias.dist_attr = P("mp")
            self._linear1_bias.is_distributed = True
        if normalize_before:
            self._ln1_scale = self.create_parameter(
                [d_model], attr=ln1_scale_attr, default_initializer=_ONES)
            self._ln1_bias = self.create_parameter(
                [d_model], attr=ln1_bias_attr, is_bias=True)
            self._ln2_scale = None
            self._ln2_bias = None
        else:
            self._ln1_scale = None
            self._ln1_bias = None
            self._ln2_scale = self.create_parameter(
                [d_model], attr=ln2_scale_attr, default_initializer=_ONES)
            self._ln2_bias = self.create_parameter(
                [d_model], attr=ln2_bias_attr, is_bias=True)

    def forward(self, src, cache=None):
        return fused_feedforward(
            src, self._linear1_weight, self._linear2_weight,
            linear1_bias=self._linear1_bias, linear2_bias=self._linear2_bias,
            ln1_scale=self._ln1_scale, ln1_bias=self._ln1_bias,
            ln2_scale=self._ln2_scale, ln2_bias=self._ln2_bias,
            dropout1_rate=self._act_dropout_rate,
            dropout2_rate=self._dropout_rate,
            activation=self._act_method, ln1_epsilon=self._epsilon,
            ln2_epsilon=self._epsilon,
            pre_layer_norm=self._normalize_before, training=self.training,
            name=self.name)

    def extra_repr(self):
        return (f"d_model={self._d_model}, "
                f"dim_feedforward={self._dim_feedforward}, "
                f"dropout_rate={self._dropout_rate}, "
                f"epsilon={self._epsilon}, "
                f"activation={self._act_method}, "
                f"normalize_before={self._normalize_before}")


class FusedTransformerEncoderLayer(nn.Layer):
    """reference: fused_transformer.py:870 — FusedMultiHeadAttention +
    FusedFeedForward."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        act_dropout_rate = (dropout_rate if act_dropout_rate is None
                            else act_dropout_rate)
        self.normalize_before = normalize_before
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before,
            qkv_weight_attr=weight_attr, qkv_bias_attr=bias_attr,
            linear_weight_attr=weight_attr, linear_bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
            linear1_weight_attr=weight_attr, linear1_bias_attr=bias_attr,
            linear2_weight_attr=weight_attr, linear2_bias_attr=bias_attr)

    def forward(self, src, src_mask=None, cache=None):
        if cache is not None:
            out, new_cache = self.fused_attn(src, attn_mask=src_mask,
                                             cache=cache)
            return self.ffn(out), new_cache
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedBiasDropoutResidualLayerNorm(nn.Layer):
    """reference: fused_transformer.py:103."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-05, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.name = name
        self.linear_bias = (None if bias_attr is False else
                            self.create_parameter([embed_dim],
                                                  attr=bias_attr,
                                                  is_bias=True))
        self.ln_scale = self.create_parameter([embed_dim], attr=weight_attr,
                                              default_initializer=_ONES)
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        return fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self._epsilon, training=self.training,
            name=self.name)

    def extra_repr(self):
        return (f"embed_dim={self.embed_dim}, "
                f"dropout_rate={self.dropout_rate}, "
                f"epsilon={self._epsilon}")
