from .fused_transformer import FusedMultiTransformer  # noqa: F401

__all__ = ["FusedMultiTransformer"]
