from .fused_transformer import FusedMultiTransformer  # noqa: F401
from .fused_attention_layers import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm,
    FusedFeedForward,
    FusedMultiHeadAttention,
    FusedTransformerEncoderLayer,
)

__all__ = ["FusedMultiTransformer", "FusedMultiHeadAttention",
           "FusedFeedForward", "FusedTransformerEncoderLayer",
           "FusedBiasDropoutResidualLayerNorm"]
