"""fused_dot_product_attention / fused_gate_attention / fused_matmul_bias
(reference: python/paddle/incubate/nn/functional/
fused_dot_product_attention.py:129 (cudnn fused attention),
fused_gate_attention.py:26 (the AlphaFold gate attention mega-op,
fusion/gpu/fused_gate_attention_op.cu), fused_matmul_bias.py:31
(cublasLt gemm epilogue)).

TPU formulation: single traced compositions — XLA fuses the bias/gating
epilogues into the dots (the role of cublasLt epilogues / the hand-written
CUDA mega-kernel); the maskless dropoutless attention core rides the Pallas
flash kernel via scaled_dot_product_attention."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....framework.core import Tensor, run_op, to_tensor

__all__ = [
    "fused_dot_product_attention",
    "fused_gate_attention",
    "fused_matmul_bias",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def fused_dot_product_attention(query, key, value, attn_mask=None,
                                dropout_p=0.0, is_causal=False,
                                scaling_factor=None, training=True,
                                name=None):
    """reference: fused_dot_product_attention.py:129 — q/k/v
    [B, S, H, D]; additive float mask; routes to the same SDPA core as
    nn.functional (Pallas flash when maskless + dropoutless)."""
    from ....nn.functional.flash_attention import scaled_dot_product_attention

    if scaling_factor is None:
        return scaled_dot_product_attention(
            query, key, value, attn_mask=attn_mask, dropout_p=dropout_p,
            is_causal=is_causal, training=training)
    # custom scale: fold it into q (SDPA uses 1/sqrt(D) internally)
    d = _t(query).shape[-1]
    q = _t(query) * (scaling_factor * (d ** 0.5))
    return scaled_dot_product_attention(
        q, key, value, attn_mask=attn_mask, dropout_p=dropout_p,
        is_causal=is_causal, training=training)


def fused_gate_attention(query, key=None, query_weight=None, key_weight=None,
                         value_weight=None, qkv_weight=None,
                         gate_linear_weight=None, gate_linear_bias=None,
                         out_linear_weight=None, out_linear_bias=None,
                         nonbatched_bias=None, attn_mask=None,
                         has_gating=True, merge_qkv=True,
                         use_flash_attn=False):
    """reference: fused_gate_attention.py:26 — the AlphaFold Evoformer
    attention block: per-head projections over [n, b, q, a] activations,
    optional nonbatched bias, sigmoid gating, output projection."""
    if out_linear_weight is None:
        raise ValueError("out_linear_weight is required")
    if has_gating and (gate_linear_weight is None or gate_linear_bias is None):
        raise ValueError(
            "has_gating=True requires gate_linear_weight and "
            "gate_linear_bias")
    if merge_qkv:
        if qkv_weight is None:
            raise ValueError("merge_qkv=True requires qkv_weight")
        m = query if key is None else key
        opt = {"gate_w": gate_linear_weight, "gate_b": gate_linear_bias,
               "nb_bias": nonbatched_bias, "mask": attn_mask}
    else:
        if query_weight is None or key_weight is None or value_weight is None:
            raise ValueError(
                "merge_qkv=False requires query/key/value weights")
        m = query if key is None else key
        opt = {"qw": query_weight, "kw": key_weight, "vw": value_weight,
               "gate_w": gate_linear_weight, "gate_b": gate_linear_bias,
               "nb_bias": nonbatched_bias, "mask": attn_mask}
    names = [k for k, v in opt.items() if v is not None]
    ins = [_t(query), _t(m)]
    if merge_qkv:
        ins.append(_t(qkv_weight))
    ins += [_t(opt[k]) for k in names]
    ow = _t(out_linear_weight)
    ob = _t(out_linear_bias) if out_linear_bias is not None else None
    ins.append(ow)
    if ob is not None:
        ins.append(ob)

    def fn(q_data, m_data, *rest):
        it = iter(rest)
        if merge_qkv:
            qkv_w = next(it)
        o = {k: next(it) for k in names}
        out_w = next(it)
        out_b = next(it, None)
        if merge_qkv:
            # qkv_w [3, H, D, A]
            q = jnp.einsum("nbqa,hda->nbqhd", q_data, qkv_w[0])
            k = jnp.einsum("nbka,hda->nbkhd", m_data, qkv_w[1])
            v = jnp.einsum("nbka,hda->nbkhd", m_data, qkv_w[2])
        else:
            q = jnp.einsum("nbqa,ahd->nbqhd", q_data, o["qw"])
            k = jnp.einsum("nbka,ahd->nbkhd", m_data, o["kw"])
            v = jnp.einsum("nbka,ahd->nbkhd", m_data, o["vw"])
        d = q.shape[-1]
        logits = jnp.einsum("nbqhd,nbkhd->nbhqk", q * (d ** -0.5), k)
        logits = logits.astype(jnp.float32)
        if "mask" in o:
            m = o["mask"]
            if m.dtype == jnp.bool_:
                logits = jnp.where(m, logits, -1e30)  # keep-mask convention
            elif jnp.issubdtype(m.dtype, jnp.integer):
                logits = jnp.where(m != 0, logits, -1e30)
            else:
                logits = logits + m.astype(jnp.float32)
        if "nb_bias" in o:
            logits = logits + o["nb_bias"].astype(jnp.float32)[:, None]
        w = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
        ctx = jnp.einsum("nbhqk,nbkhd->nbqhd", w, v)
        if has_gating:
            gate = jnp.einsum("nbqa,ahd->nbqhd", q_data, o["gate_w"])
            gate = gate + o["gate_b"]
            ctx = ctx * jax.nn.sigmoid(gate)
        out = jnp.einsum("nbqhd,hdo->nbqo", ctx, out_w)
        if out_b is not None:
            out = out + out_b
        return out

    return run_op("fused_gate_attention", fn, ins)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """reference: fused_matmul_bias.py:31 (cublasLt epilogue) — XLA fuses
    the bias add into the dot."""
    ins = [_t(x), _t(y)] + ([_t(bias)] if bias is not None else [])

    def fn(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if rest:
            out = out + rest[0]
        return out

    return run_op("fused_matmul_bias", fn, ins)
