"""fused_multi_head_attention / fused_feedforward /
fused_bias_dropout_residual_layer_norm (reference:
python/paddle/incubate/nn/functional/fused_transformer.py:513,47,334;
kernels paddle/phi/kernels/fusion/gpu/fused_attention_kernel.cu,
fused_feedforward_kernel.cu).

TPU formulation: each op is ONE run_op composition — LN + projections +
attention + residual epilogues trace into a single XLA program which fuses
the epilogues into the matmuls (what the reference's hand-written mega
kernels do by construction). The attention core routes to the Pallas flash
kernel when it is maskless/dropoutless causal-free self-attention;
otherwise the f32-softmax composite runs (still fused around the dots)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....framework import random as rnd
from ....framework.core import Tensor, run_op, to_tensor
from ....nn.functional._attn_math import NEG_INF
from ....nn.functional._attn_math import masked_attention as _masked_attn

__all__ = [
    "fused_multi_head_attention",
    "fused_attention",
    "fused_feedforward",
    "fused_bias_dropout_residual_layer_norm",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _ln(v, scale, bias, eps):
    # stats in f32 (repo LN convention, nn/functional/norm.py — matches the
    # reference fused kernels' float accumulators)
    v32 = v.astype(jnp.float32)
    mu = v32.mean(-1, keepdims=True)
    var = ((v32 - mu) ** 2).mean(-1, keepdims=True)
    out = (v32 - mu) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        out = out * scale.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(v.dtype)


def _dropout(v, rate, key, training, mode):
    if rate == 0.0 or key is None:
        if mode == "downscale_in_infer" and not training:
            return v * (1.0 - rate)
        return v
    if not training:
        return v if mode == "upscale_in_train" else v * (1.0 - rate)
    keep = jax.random.bernoulli(key, 1.0 - rate, v.shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, v / (1.0 - rate), 0.0)
    return jnp.where(keep, v, 0.0)


def _maybe_keys(training, *rates):
    return [rnd.next_key() if (training and r > 0.0) else None
            for r in rates]


def _use_flash():
    from ....nn.functional.flash_attention import _use_pallas_kernel

    return _use_pallas_kernel()


def fused_multi_head_attention(
    x,
    qkv_weight,
    linear_weight,
    pre_layer_norm=False,
    pre_ln_scale=None,
    pre_ln_bias=None,
    ln_scale=None,
    ln_bias=None,
    pre_ln_epsilon=1e-05,
    qkv_bias=None,
    linear_bias=None,
    cache_kv=None,
    attn_mask=None,
    dropout_rate=0.5,
    attn_dropout_rate=0.5,
    ln_epsilon=1e-05,
    training=True,
    mode="upscale_in_train",
    ring_id=-1,
    add_residual=True,
    num_heads=-1,
    transpose_qkv_wb=False,
    name=None,
):
    """reference: fused_transformer.py:513 — self-attention block with
    (pre|post) LN, qkv projection, scaled-dot-product attention with mask +
    attention dropout, output projection, residual + dropout."""
    opt = {
        "pre_ln_scale": pre_ln_scale, "pre_ln_bias": pre_ln_bias,
        "ln_scale": ln_scale, "ln_bias": ln_bias, "qkv_bias": qkv_bias,
        "linear_bias": linear_bias, "cache_kv": cache_kv,
        "attn_mask": attn_mask,
    }
    names = [k for k, v in opt.items() if v is not None]
    ins = [_t(x), _t(qkv_weight), _t(linear_weight)] + [
        _t(opt[k]) for k in names]
    akey, dkey = _maybe_keys(training, attn_dropout_rate, dropout_rate)

    if transpose_qkv_wb and num_heads <= 0:
        raise ValueError(
            "transpose_qkv_wb=True requires num_heads > 0 (the [E, 3E] "
            "weight layout does not carry the head count)")

    def fn(xv, qkv_w, lin_w, *rest):
        o = dict(zip(names, rest))
        B, S, E = xv.shape
        residual = xv
        h = _ln(xv, o.get("pre_ln_scale"), o.get("pre_ln_bias"),
                pre_ln_epsilon) if pre_layer_norm else xv
        # q/k/v in paddle layout [B, S, H, D]
        if transpose_qkv_wb:
            H = num_heads
            qkv = h @ qkv_w  # [B, S, 3E]
            if "qkv_bias" in o:
                qkv = qkv + o["qkv_bias"]
            qkv = qkv.reshape(B, S, 3, H, E // H)
        else:
            # [B,S,E] x [3,H,D,E] -> [B,S,3,H,D]
            qkv = jnp.einsum("bse,jhde->bsjhd", h, qkv_w)
            if "qkv_bias" in o:
                qkv = qkv + o["qkv_bias"]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        D = q.shape[-1]
        new_cache = None
        if "cache_kv" in o:
            # reference cache layout [2, B, H, S_cache, D]
            ck = jnp.moveaxis(o["cache_kv"][0], 1, 2)  # -> [B, S, H, D]
            cv = jnp.moveaxis(o["cache_kv"][1], 1, 2)
            k = jnp.concatenate([ck.astype(k.dtype), k], axis=1)
            v = jnp.concatenate([cv.astype(v.dtype), v], axis=1)
            new_cache = jnp.stack([jnp.moveaxis(k, 1, 2),
                                   jnp.moveaxis(v, 1, 2)])
        keep = add_mask = None
        if "attn_mask" in o:
            m = o["attn_mask"]
            if m.dtype == jnp.bool_:
                keep = m
            elif jnp.issubdtype(m.dtype, jnp.integer):
                keep = m != 0
            else:
                add_mask = m
        drop_active = akey is not None
        if not drop_active and keep is None and add_mask is None \
                and _use_flash():
            from ....ops.pallas.flash_attention import flash_attention_fwd

            ctx = flash_attention_fwd(q, k, v, causal=False)
        elif not drop_active:
            # shared f32 softmax/mask policy (nn/functional/_attn_math.py)
            ctx = _masked_attn(q, k, v, keep=keep, add_mask=add_mask)
        else:
            s = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                           k.astype(jnp.float32)) * (D ** -0.5)
            if keep is not None:
                s = jnp.where(keep, s, NEG_INF)
            if add_mask is not None:
                s = s + add_mask.astype(jnp.float32)
            p = jax.nn.softmax(s, axis=-1)
            p = _dropout(p, attn_dropout_rate, akey, training, mode)
            ctx = jnp.einsum("bhst,bthd->bshd", p,
                             v.astype(jnp.float32)).astype(xv.dtype)
        ctx = ctx.reshape(B, S, -1)
        out = ctx @ lin_w
        if "linear_bias" in o:
            out = out + o["linear_bias"]
        out = _dropout(out, dropout_rate, dkey, training, mode)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            out = _ln(out, o.get("ln_scale"), o.get("ln_bias"), ln_epsilon)
        out = out.astype(xv.dtype)
        if new_cache is not None:
            return out, new_cache
        return out

    out = run_op("fused_multi_head_attention", fn, ins,
                 n_outputs=2 if cache_kv is not None else None)
    return out


fused_attention = fused_multi_head_attention


def fused_feedforward(
    x,
    linear1_weight,
    linear2_weight,
    linear1_bias=None,
    linear2_bias=None,
    ln1_scale=None,
    ln1_bias=None,
    ln2_scale=None,
    ln2_bias=None,
    dropout1_rate=0.5,
    dropout2_rate=0.5,
    activation="relu",
    ln1_epsilon=1e-5,
    ln2_epsilon=1e-5,
    pre_layer_norm=False,
    training=True,
    mode="upscale_in_train",
    ring_id=-1,
    add_residual=True,
    name=None,
):
    """reference: fused_transformer.py:47 —
    out = linear2(dropout1(act(linear1(maybe_ln1(x))))); residual + dropout2;
    post-LN when not pre_layer_norm."""
    opt = {
        "linear1_bias": linear1_bias, "linear2_bias": linear2_bias,
        "ln1_scale": ln1_scale, "ln1_bias": ln1_bias,
        "ln2_scale": ln2_scale, "ln2_bias": ln2_bias,
    }
    names = [k for k, v in opt.items() if v is not None]
    ins = [_t(x), _t(linear1_weight), _t(linear2_weight)] + [
        _t(opt[k]) for k in names]
    k1, k2 = _maybe_keys(training, dropout1_rate, dropout2_rate)
    acts = {
        "relu": jax.nn.relu,
        "gelu": lambda v: jax.nn.gelu(v, approximate=False),  # paddle exact
        "silu": jax.nn.silu, "swish": jax.nn.silu, "tanh": jnp.tanh,
    }
    act = acts[activation]

    def fn(xv, w1, w2, *rest):
        o = dict(zip(names, rest))
        residual = xv
        h = _ln(xv, o.get("ln1_scale"), o.get("ln1_bias"),
                ln1_epsilon) if pre_layer_norm else xv
        h = h @ w1
        if "linear1_bias" in o:
            h = h + o["linear1_bias"]
        h = _dropout(act(h), dropout1_rate, k1, training, mode)
        h = h @ w2
        if "linear2_bias" in o:
            h = h + o["linear2_bias"]
        h = _dropout(h, dropout2_rate, k2, training, mode)
        if add_residual:
            h = residual + h
        if not pre_layer_norm:
            h = _ln(h, o.get("ln2_scale"), o.get("ln2_bias"), ln2_epsilon)
        return h.astype(xv.dtype)

    return run_op("fused_feedforward", fn, ins)


def fused_bias_dropout_residual_layer_norm(
    x,
    residual,
    bias=None,
    ln_scale=None,
    ln_bias=None,
    dropout_rate=0.5,
    ln_epsilon=1e-5,
    training=True,
    mode="upscale_in_train",
    name=None,
):
    """reference: fused_transformer.py:334 —
    layer_norm(residual + dropout(x + bias))."""
    opt = {"bias": bias, "ln_scale": ln_scale, "ln_bias": ln_bias}
    names = [k for k, v in opt.items() if v is not None]
    ins = [_t(x), _t(residual)] + [_t(opt[k]) for k in names]
    (key,) = _maybe_keys(training, dropout_rate)

    def fn(xv, res, *rest):
        o = dict(zip(names, rest))
        h = xv + o["bias"] if "bias" in o else xv
        h = res + _dropout(h, dropout_rate, key, training, mode)
        return _ln(h, o.get("ln_scale"), o.get("ln_bias"),
                   ln_epsilon).astype(xv.dtype)

    return run_op("fused_bias_dropout_residual_layer_norm", fn, ins)
