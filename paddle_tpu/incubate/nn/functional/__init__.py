"""Fused-op functional APIs (reference: python/paddle/incubate/nn/functional/).

The reference backs each of these with a hand-written CUDA fusion kernel
(SURVEY §2.2). On TPU the fusion itself is XLA's job — these entry points
express the op as a single traceable function so XLA fuses the whole epilogue
into the surrounding matmuls; the hot ones additionally route to Pallas
kernels on TPU (paddle_tpu.ops.pallas) where XLA's automatic fusion is not
enough (flash attention; see nn/functional/flash_attention.py).

API parity targets:
- swiglu                              (python/paddle/incubate/nn/functional/swiglu.py:26)
- fused_rotary_position_embedding     (.../fused_rotary_position_embedding.py;
                                       kernel paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu)
- fused_rms_norm                      (.../fused_rms_norm.py:59;
                                       kernel fusion/gpu/fused_layernorm_kernel.cu)
- fused_layer_norm                    (.../fused_layer_norm.py)
- fused_bias_act                      (kernel fusion/gpu/fused_bias_act_kernel.cu)
- fused_dropout_add                   (kernel gpu/fused_dropout_add_kernel.cu)
- fused_linear / fused_linear_activation
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....framework.core import Tensor, run_op, to_tensor


def _pallas_decode_on():
    # Route decode attention to the Pallas kernels on TPU (or under the
    # interpreter); jnp composites elsewhere.
    from ....nn.functional.flash_attention import _use_pallas_kernel

    return _use_pallas_kernel()


def _fused_norm_route():
    """Trace-time capture of the PADDLE_TPU_FUSED_NORM toggle + Pallas
    availability (see nn/functional/norm.py _fused_norm_route)."""
    from ....ops.pallas.fused_norm import fused_norm_on

    return fused_norm_on() and _pallas_decode_on()


def _fused_rope_route():
    """Trace-time capture of the PADDLE_TPU_FUSED_ROPE toggle + Pallas
    availability; captured into the traced closure so an env flip between
    forward and backward tracing cannot mix kernel and composite paths."""
    from ....ops.pallas.fused_rope import fused_rope_on

    return fused_rope_on() and _pallas_decode_on()

__all__ = [
    "swiglu",
    "fused_rotary_position_embedding",
    "fused_rms_norm",
    "fused_layer_norm",
    "fused_bias_act",
    "fused_dropout_add",
    "fused_linear",
    "fused_linear_activation",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def swiglu(x, y=None, name=None):
    """silu(x) * y; single-input form splits x in half on the last dim
    (reference: swiglu.py:26, kernel paddle/phi/kernels/gpu/ swiglu)."""
    if y is None:
        def fn(a):
            u, v = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(u.astype(jnp.float32)).astype(a.dtype) * v

        return run_op("swiglu", fn, [_t(x)])

    def fn2(a, b):
        return jax.nn.silu(a.astype(jnp.float32)).astype(a.dtype) * b

    return run_op("swiglu", fn2, [_t(x), _t(y)])


def _rope_tables(seq_len, head_dim, theta, dtype, position_ids=None):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if position_ids is None:
        pos = jnp.arange(seq_len, dtype=jnp.float32)[None, :]  # [1, S]
    else:
        pos = position_ids.astype(jnp.float32)  # [B, S]
    freqs = pos[..., None] * inv[None, None, :]  # [B?, S, D/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def _apply_rope_one(x, cos, sin, neox):
    """x: [B, S, H, D]. neox style rotates (x[..., :D/2], x[..., D/2:]) pairs;
    GPT-J style rotates interleaved even/odd lanes (the reference's
    use_neox_rotary_style flag, fused_rope_utils.h)."""
    f32 = jnp.float32
    c = cos[:, :, None, :].astype(f32)
    s = sin[:, :, None, :].astype(f32)
    xf = x.astype(f32)
    if neox:
        x1, x2 = jnp.split(xf, 2, axis=-1)
        out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    else:
        x1 = xf[..., 0::2]
        x2 = xf[..., 1::2]
        r1 = x1 * c - x2 * s
        r2 = x2 * c + x1 * s
        out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def fused_rotary_position_embedding(
    q,
    k=None,
    v=None,
    sin=None,
    cos=None,
    position_ids=None,
    use_neox_rotary_style=True,
    time_major=False,
    rotary_emb_base=10000.0,
    name=None,
):
    """Rotary embedding on q/k(/v), layout [B, S, H, D]
    (reference: fused_rotary_position_embedding.py; kernel fused_rope_kernel.cu).
    Returns a tuple matching the number of non-None inputs."""
    tensors = [_t(q)]
    has_k = k is not None
    has_v = v is not None
    if has_k:
        tensors.append(_t(k))
    if has_v:
        tensors.append(_t(v))
    ext = []
    has_tables = sin is not None and cos is not None
    if has_tables:
        ext = [_t(cos), _t(sin)]
    if position_ids is not None:
        ext.append(_t(position_ids))
    has_pos = position_ids is not None
    n_qkv = len(tensors)
    use_fused = _fused_rope_route()

    def fn(*args):
        qkv = list(args[:n_qkv])
        rest = list(args[n_qkv:])
        if time_major:
            qkv = [jnp.swapaxes(t, 0, 1) for t in qkv]
        B, S, H, D = qkv[0].shape
        if has_tables:
            c, s = rest[0], rest[1]
            rest = rest[2:]
            # reference accepts [1, S, 1, D] or [S, D]; canonicalize to [B?, S, D/2]
            c = c.reshape(-1, c.shape[-1] if c.ndim > 1 else c.shape[0])[-S:, :]
            s = s.reshape(-1, s.shape[-1])[-S:, :]
            if c.shape[-1] == D:  # full-D tables store each half/duplicate
                c = c[:, : D // 2] if use_neox_rotary_style else c[:, 0::2]
                s = s[:, : D // 2] if use_neox_rotary_style else s[:, 0::2]
            c = c[None]
            s = s[None]
            if has_pos:
                pid = rest[0].astype(jnp.int32)
                c = jnp.take(c[0], pid, axis=0)
                s = jnp.take(s[0], pid, axis=0)
        else:
            pid = rest[0] if has_pos else None
            c, s = _rope_tables(S, D, rotary_emb_base, qkv[0].dtype, pid)
        if use_fused and D % 2 == 0:
            # one Pallas pass over every given tensor (q, k, and v when the
            # caller rotates it) — paddle_tpu.ops.pallas.fused_rope
            from ....ops.pallas.fused_rope import apply_fused_rope

            outs = list(apply_fused_rope(
                tuple(qkv), c, s, interleaved=not use_neox_rotary_style))
        else:
            outs = [_apply_rope_one(t, c, s, use_neox_rotary_style)
                    for t in qkv]
        if time_major:
            outs = [jnp.swapaxes(t, 0, 1) for t in outs]
        return tuple(outs) if len(outs) > 1 else outs[0]

    out = run_op("fused_rope", fn, tensors + ext)
    if n_qkv == 1:
        return (out, None, None)
    outs = list(out) + [None] * (3 - n_qkv)
    return tuple(outs)


def fused_rms_norm(
    x,
    norm_weight,
    norm_bias=None,
    epsilon=1e-6,
    begin_norm_axis=-1,
    bias=None,
    residual=None,
    quant_scale=-1,
    quant_round_type=0,
    quant_max_bound=0,
    quant_min_bound=0,
    name=None,
):
    """RMSNorm fused with optional residual-add + bias
    (reference: fused_rms_norm.py:59; fused_layernorm_kernel.cu residual path).
    Returns (out, residual_out) like the reference. Last-axis norms route to
    the fused Pallas kernel (PADDLE_TPU_FUSED_NORM toggle, default on)."""
    ins = [_t(x), _t(norm_weight)]
    has_nb = norm_bias is not None
    has_b = bias is not None
    has_r = residual is not None
    for extra, flag in ((norm_bias, has_nb), (bias, has_b), (residual, has_r)):
        if flag:
            ins.append(_t(extra))
    fused = _fused_norm_route()

    def fn(a, w, *rest):
        i = 0
        nb = rest[i] if has_nb else None
        i += has_nb
        b = rest[i] if has_b else None
        i += has_b
        r = rest[i] if has_r else None
        ax = begin_norm_axis if begin_norm_axis >= 0 else a.ndim + begin_norm_axis
        # ONE pre-add block feeding both paths, so the fused/composite A/B
        # cannot diverge on bias/residual handling. With no pre-adds h
        # stays in the input dtype — the fused kernel upcasts in-stream
        # (no f32 copy); the composite upcasts below.
        h, res_out = _norm_preadd(a, b, r)
        if (fused and ax == a.ndim - 1 and a.ndim >= 2 and w.ndim == 1
                and (nb is None or nb.ndim == 1)):
            from ....ops.pallas.fused_norm import rms_norm_fwd

            return (rms_norm_fwd(h, w, epsilon, bias=nb).astype(a.dtype),
                    res_out)
        h = h.astype(jnp.float32)
        axes = tuple(range(ax, a.ndim))
        var = jnp.mean(jnp.square(h), axis=axes, keepdims=True)
        out = h * jax.lax.rsqrt(var + epsilon) * w.astype(jnp.float32)
        if nb is not None:
            out = out + nb.astype(jnp.float32)
        return out.astype(a.dtype), res_out

    out, res_out = run_op("fused_rms_norm", fn, ins)
    return out, res_out


def _norm_preadd(a, b, r, alpha=1.0):
    """Shared fused_rms_norm / fused_layer_norm pre-norm adds: h = a (+ b)
    (+ r * alpha) in f32, and the residual_out in a's dtype. With neither
    b nor r, returns `a` itself untouched (the reference's res_out equals
    the input exactly in that case)."""
    if b is None and r is None:
        return a, a
    h = a.astype(jnp.float32)
    if b is not None:
        h = h + b.astype(jnp.float32)
    if r is not None:
        h = h + r.astype(jnp.float32) * alpha
    return h, h.astype(a.dtype)


def fused_layer_norm(
    x,
    norm_weight,
    norm_bias=None,
    epsilon=1e-5,
    begin_norm_axis=-1,
    bias=None,
    residual=None,
    residual_alpha=1.0,
    quant_scale=-1,
    quant_round_type=0,
    quant_max_bound=0,
    quant_min_bound=0,
    name=None,
):
    """LayerNorm fused with residual-add (+alpha) and bias
    (reference: fused_layer_norm.py; residual_alpha at
    fused_layernorm_kernel.cu:1003). Returns (out, residual_out). Last-axis
    norms route to the fused Pallas kernel (PADDLE_TPU_FUSED_NORM)."""
    ins = [_t(x), _t(norm_weight)]
    has_nb = norm_bias is not None
    has_b = bias is not None
    has_r = residual is not None
    for extra, flag in ((norm_bias, has_nb), (bias, has_b), (residual, has_r)):
        if flag:
            ins.append(_t(extra))
    fused = _fused_norm_route()

    def fn(a, w, *rest):
        i = 0
        nb = rest[i] if has_nb else None
        i += has_nb
        b = rest[i] if has_b else None
        i += has_b
        r = rest[i] if has_r else None
        ax = begin_norm_axis if begin_norm_axis >= 0 else a.ndim + begin_norm_axis
        h, res_out = _norm_preadd(a, b, r, alpha=residual_alpha)
        if (fused and ax == a.ndim - 1 and a.ndim >= 2 and w.ndim == 1
                and (nb is None or nb.ndim == 1)):
            from ....ops.pallas.fused_norm import layer_norm_fwd

            return (layer_norm_fwd(h, w, nb, epsilon).astype(a.dtype),
                    res_out)
        h = h.astype(jnp.float32)
        axes = tuple(range(ax, a.ndim))
        mean = jnp.mean(h, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(h - mean), axis=axes, keepdims=True)
        out = (h - mean) * jax.lax.rsqrt(var + epsilon) * w.astype(jnp.float32)
        if nb is not None:
            out = out + nb.astype(jnp.float32)
        return out.astype(a.dtype), res_out

    out, res_out = run_op("fused_layer_norm", fn, ins)
    return out, res_out


_ACTS = {
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "geglu": None,  # handled below (gated)
    "swiglu": None,
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
    "identity": lambda x: x,
}


def fused_bias_act(
    x,
    bias=None,
    dequant_scales=None,
    shift=None,
    smooth=None,
    act_method="gelu",
    compute_dtype="default",
    quant_scale=-1,
    quant_round_type=0,
    quant_max_bound=0,
    quant_min_bound=0,
    name=None,
):
    """bias-add + activation epilogue (reference: fused_bias_act_kernel.cu;
    python API incubate/nn/functional/fused_bias_act). Gated acts (geglu /
    swiglu) halve the last dim."""
    ins = [_t(x)]
    has_b = bias is not None
    if has_b:
        ins.append(_t(bias))
    method = act_method.lower()

    def fn(a, *rest):
        h = a.astype(jnp.float32)
        if has_b:
            h = h + rest[0].astype(jnp.float32)
        if method in ("geglu", "swiglu"):
            u, v = jnp.split(h, 2, axis=-1)
            g = jax.nn.gelu(u, approximate=False) if method == "geglu" else jax.nn.silu(u)
            out = g * v
        else:
            out = _ACTS[method](h)
        return out.astype(a.dtype)

    return run_op("fused_bias_act", fn, ins)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      seed=None, name=None):
    """dropout(x) + y in one op (reference: fused_dropout_add_kernel.cu,
    python/paddle/incubate/nn/functional/fused_dropout_add.py)."""
    ins = [_t(x), _t(y)]
    if not training or p == 0.0:
        return run_op("fused_dropout_add", lambda a, b: a + b, ins)
    from ....framework import random as rnd

    def fn(a, b, key):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        if mode == "upscale_in_train":
            d = jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        else:
            d = jnp.where(keep, a, 0.0).astype(a.dtype)
        return d + b

    return run_op("fused_dropout_add", fn, ins + [rnd.rng_tensor()])


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """matmul + bias epilogue (reference: incubate fused_linear →
    cutlass gemm_epilogue)."""
    ins = [_t(x), _t(weight)]
    has_b = bias is not None
    if has_b:
        ins.append(_t(bias))

    def fn(a, w, *rest):
        if transpose_weight:
            w = w.T
        out = jnp.matmul(a, w)
        if has_b:
            out = out + rest[0]
        return out

    return run_op("fused_linear", fn, ins)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    """matmul + bias + act (reference: fused_gemm_epilogue)."""
    ins = [_t(x), _t(y), _t(bias)]
    method = activation.lower()

    if method not in ("none",) and _ACTS.get(method) is None and method not in ("geglu", "swiglu"):
        raise ValueError(f"unsupported activation {activation!r}")

    def fn(a, w, b):
        if trans_x:
            a = jnp.swapaxes(a, -1, -2)
        if trans_y:
            w = jnp.swapaxes(w, -1, -2)
        out = jnp.matmul(a, w) + b
        if method in ("geglu", "swiglu"):
            h = out.astype(jnp.float32)
            u, vv = jnp.split(h, 2, axis=-1)
            gate = jax.nn.gelu(u, approximate=False) if method == "geglu" else jax.nn.silu(u)
            out = (gate * vv).astype(out.dtype)
        elif method != "none":
            out = _ACTS[method](out.astype(jnp.float32)).astype(out.dtype)
        return out

    return run_op("fused_linear_activation", fn, ins)


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn1_scale=None, ffn2_bias=None, ffn2_scale=None,
              quant_method="None", moe_topk=2, norm_topk_prob=True,
              group_moe=False, name=None):
    """Fused mixture-of-experts FFN (reference:
    python/paddle/incubate/nn/functional/fused_moe.py backed by the cutlass
    grouped GEMM fusion/cutlass/fused_moe_kernel.cu).

    TPU-native: dense GShard dispatch->batched-expert-GEMM->combine in one
    traced function (all-expert einsums batch onto the MXU; when expert
    weights are mesh-sharded GSPMD inserts the all-to-alls). Capacity is
    4*ceil(topk*T/E) so drops are negligible at inference batch sizes; the
    reference kernel is drop-free. group_moe=True partitions the E experts
    into moe_topk equal groups, softmaxes WITHIN each group and routes to
    the top-1 expert per group (the ERNIE-MoE grouped-routing scheme).

    Shapes: x [B, S, M] or [T, M]; gate_weight [M, E];
    ffn1_weight [E, M, 2H] (swiglu layout: act on the FIRST half, matching
    this module's swiglu) or [E, M, H] (gelu); ffn2_weight [E, H, M].
    """
    import math

    weight_only = quant_method == "weight_only_int8"
    if quant_method not in ("None", None, "none", "weight_only_int8"):
        raise NotImplementedError(
            f"fused_moe quant_method {quant_method!r} is not supported on "
            f"TPU (weight_only_int8 is)")
    if weight_only and (ffn1_scale is None or ffn2_scale is None):
        raise ValueError("weight_only_int8 requires ffn1_scale and ffn2_scale")

    from ...distributed.models.moe.gate import _topk_dispatch

    has_b1 = ffn1_bias is not None
    has_b2 = ffn2_bias is not None

    def fn(xv, gw, w1, w2, *rest):
        bi = iter(rest)
        if weight_only:
            # int8 expert weights dequantize per expert/out-channel; the
            # scale multiply folds into the expert GEMMs (reference:
            # cutlass weight-only grouped GEMM)
            s1 = next(bi)
            s2 = next(bi)
            w1 = w1.astype(xv.dtype) * s1.reshape(w1.shape[0], 1, -1).astype(xv.dtype)
            w2 = w2.astype(xv.dtype) * s2.reshape(w2.shape[0], 1, -1).astype(xv.dtype)
        b1 = next(bi) if has_b1 else None
        b2 = next(bi) if has_b2 else None
        shape = xv.shape
        xt = xv.reshape(-1, shape[-1])
        T, _M = xt.shape
        E = gw.shape[-1]
        glu = w1.shape[-1] == 2 * w2.shape[1]
        cap = max(1, min(T, 4 * math.ceil(moe_topk * T / E)))

        logits = (xt @ gw).astype(jnp.float32)
        if group_moe:
            if E % moe_topk != 0:
                raise ValueError(
                    f"group_moe needs num_experts ({E}) divisible by "
                    f"moe_topk ({moe_topk})")
            Eg = E // moe_topk
            gp = jax.nn.softmax(logits.reshape(T, moe_topk, Eg), axis=-1)
            sel = jnp.argmax(gp, axis=-1)  # top-1 expert per group
            probs = (gp * jax.nn.one_hot(sel, Eg, dtype=gp.dtype)
                     ).reshape(T, E)
        else:
            probs = jax.nn.softmax(logits, axis=-1)
        combine, dispatch, _ = _topk_dispatch(probs, moe_topk, cap,
                                              normalize_topk=norm_topk_prob)
        dispatch = dispatch.astype(xt.dtype)

        xe = jnp.einsum("tec,tm->ecm", dispatch, xt)
        h = jnp.einsum("ecm,emh->ech", xe, w1)
        if b1 is not None:
            h = h + b1.reshape(E, 1, -1)
        if glu:
            u, g = jnp.split(h, 2, axis=-1)
            h = jax.nn.silu(u) * g
        else:
            h = jax.nn.gelu(h)
        ye = jnp.einsum("ech,ehm->ecm", h, w2)
        if b2 is not None:
            ye = ye + b2.reshape(E, 1, -1)
        out = jnp.einsum("tec,ecm->tm", combine.astype(xt.dtype), ye)
        return out.reshape(shape)

    args = [x, gate_weight, ffn1_weight, ffn2_weight]
    if weight_only:
        args += [ffn1_scale, ffn2_scale]
    if has_b1:
        args.append(ffn1_bias)
    if has_b2:
        args.append(ffn2_bias)
    return run_op("fused_moe", fn, args)


__all__.append("fused_moe")


def masked_multihead_attention(
    x, cache_kv=None, bias=None, src_mask=None, cum_offsets=None,
    sequence_lengths=None, rotary_tensor=None, beam_cache_offset=None,
    qkv_out_scale=None, out_shift=None, out_smooth=None, seq_len=1,
    rotary_emb_dims=0, use_neox_rotary_style=False, compute_dtype="default",
    out_scale=-1, quant_round_type=1, quant_max_bound=127.0,
    quant_min_bound=-127.0, name=None):
    """Single-step decode attention with KV cache (reference:
    python/paddle/incubate/nn/functional/masked_multihead_attention.py, kernel
    fusion/gpu/masked_multihead_attention_kernel.cu / mmha_util.cu.h).

    TPU-native: one traced function — per-row dynamic cache write
    (dynamic_update_slice) + masked attention over the static-capacity cache;
    XLA fuses the epilogue. Decode is HBM-bound, so keeping the cache resident
    and reading it once is the whole game.

    x: [B, 3*H*D] fused qkv for ONE step. cache_kv: [2, B, H, S_max, D]
    (reference layout). sequence_lengths: [B] current lengths (cache write
    offset). Returns (out [B, H*D], updated cache_kv). Quant/beam/rotary
    tensor paths are not supported.
    """
    from ....nn.functional._attn_math import masked_attention

    if any(a is not None for a in (rotary_tensor, beam_cache_offset,
                                   qkv_out_scale, out_shift, out_smooth)) \
            or out_scale != -1:
        raise NotImplementedError(
            "masked_multihead_attention: quant/beam/rotary-tensor paths are "
            "not supported on TPU")
    assert cache_kv is not None, "cache_kv is required"

    ins = [_t(x), _t(cache_kv)]
    has_bias = bias is not None
    has_mask = src_mask is not None
    has_lens = sequence_lengths is not None
    if has_bias:
        ins.append(_t(bias))
    if has_mask:
        ins.append(_t(src_mask))
    if has_lens:
        ins.append(_t(sequence_lengths))

    def fn(xv, cache, *rest):
        it = iter(rest)
        b = next(it) if has_bias else None
        mask = next(it) if has_mask else None
        lens = next(it) if has_lens else None
        B = xv.shape[0]
        _, _, H, S_max, D = cache.shape
        qkv = xv.reshape(B, 3, H, D)
        if b is not None:
            qkv = qkv + b.reshape(1, 3, H, D)
        q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [B, H, D]
        if lens is None:
            lens = jnp.zeros((B,), jnp.int32)
        lens = lens.reshape(B).astype(jnp.int32)

        # per-row cache write at offset lens[b]
        def write(cache_row, kv, off):
            # cache_row [H, S_max, D]; kv [H, D]
            return jax.lax.dynamic_update_slice(
                cache_row, kv[:, None, :].astype(cache_row.dtype), (0, off, 0))

        k_cache = jax.vmap(write)(cache[0], k_new, lens)
        v_cache = jax.vmap(write)(cache[1], v_new, lens)

        if mask is None and _pallas_decode_on():
            from ....ops.pallas.decode_attention import dense_decode_attention

            out = dense_decode_attention(q, k_cache, v_cache, lens + 1)
            new_cache = jnp.stack([k_cache, v_cache], 0)
            return out.reshape(B, H * D).astype(xv.dtype), new_cache

        keep = (jnp.arange(S_max)[None, :] <= lens[:, None])[:, None, None, :]
        add = mask.reshape(B, 1, 1, -1)[..., :S_max] if mask is not None else None
        out = masked_attention(
            q[:, None],  # [B, 1, H, D]
            jnp.moveaxis(k_cache, 1, 2), jnp.moveaxis(v_cache, 1, 2),
            keep=keep, add_mask=add)
        new_cache = jnp.stack([k_cache, v_cache], 0)
        return out.reshape(B, H * D).astype(xv.dtype), new_cache

    return run_op("masked_multihead_attention", fn, ins)


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size=None, name=None):
    """Max enc/dec lengths for block attention (reference:
    paddle/phi/kernels/fusion/gpu/blha_get_max_len.cu)."""
    def fn(e, d):
        return jnp.max(e).reshape(1), jnp.max(d).reshape(1)

    return run_op("blha_get_max_len", fn, [_t(seq_lens_encoder), _t(seq_lens_decoder)])


def block_multihead_attention(
    qkv, key_cache, value_cache, seq_lens_encoder, seq_lens_decoder,
    seq_lens_this_time, padding_offsets=None, cum_offsets=None,
    cu_seqlens_q=None, cu_seqlens_k=None, block_tables=None,
    pre_key_cache=None, pre_value_cache=None, cache_k_quant_scales=None,
    cache_v_quant_scales=None, cache_k_dequant_scales=None,
    cache_v_dequant_scales=None, qkv_out_scale=None, qkv_bias=None,
    out_shift=None, out_smooth=None, max_enc_len_this_time=None,
    max_dec_len_this_time=None, rope_emb=None, mask=None, tgt_mask=None,
    max_seq_len=-1, block_size=64, use_neox_style=False, name=None, **quant_kw):
    """Paged-KV-cache attention (reference: block_multihead_attention,
    python/paddle/incubate/nn/functional/block_multihead_attention.py, kernel
    fusion/gpu/block_multi_head_attention_kernel.cu + block_attn.h).

    TPU-native redesign with DENSE PADDED batches (static shapes for XLA)
    instead of the reference's ragged packed-token layout:
    - qkv: [B, S, 3*H*D] (prefill: S = prompt len; decode: S = 1)
    - key_cache/value_cache: [max_blocks, kv_heads, block_size, head_dim]
      (the reference's paged layout) — functionally updated and returned
    - block_tables: [B, max_blocks_per_seq] page ids (-1 = unused)
    - seq_lens_encoder: [B] prompt lens (prefill rows; 0 = decode row)
    - seq_lens_decoder: [B] tokens already in cache (decode offset)
    Mode is per-row: rows with seq_lens_encoder > 0 run prefill (causal over
    their prompt); rows with seq_lens_this_time == 1 run paged decode.

    rope_emb ([2, B(or 1), max_seq, 1, head_dim//2] cos/sin) fuses rotary
    application to q and the new k AT THE ABSOLUTE CACHE POSITION before the
    cache write — the reference decode loop's fused cache-write+rope
    (fused_multi_transformer_op.cu.h:3097). pre_key_cache/pre_value_cache
    ([B, kv_heads, P, head_dim]) are a shared prefix every valid query
    attends before the paged cache (reference pre_cache path).
    Returns (out [B, S, H*D], qkv, key_cache, value_cache) like the reference.
    Activation-quant paths (qkv_out_scale/out_shift/out_smooth) are
    unsupported.
    """
    from ....nn.functional._attn_math import masked_attention

    if any(v is not None for v in (qkv_out_scale, out_shift, out_smooth)):
        raise NotImplementedError("block_multihead_attention activation-"
                                  "quant paths are not supported on TPU")
    assert block_tables is not None, "block_tables is required"
    if (pre_key_cache is None) != (pre_value_cache is None):
        raise ValueError("pre_key_cache and pre_value_cache must be given "
                         "together")

    _scales = (cache_k_quant_scales, cache_v_quant_scales,
               cache_k_dequant_scales, cache_v_dequant_scales)
    cache_quant = any(s is not None for s in _scales)
    if cache_quant and any(s is None for s in _scales):
        # a partially-supplied set must not silently disable quantization
        raise ValueError(
            "int8 cache quant needs all four cache_{k,v}_{quant,dequant}"
            "_scales")

    ins = [_t(qkv), _t(key_cache), _t(value_cache), _t(seq_lens_encoder),
           _t(seq_lens_decoder), _t(block_tables)]
    if cache_quant:
        ins += [_t(cache_k_quant_scales), _t(cache_v_quant_scales),
                _t(cache_k_dequant_scales), _t(cache_v_dequant_scales)]
    has_bias = qkv_bias is not None
    if has_bias:
        ins.append(_t(qkv_bias))
    has_rope = rope_emb is not None
    if has_rope:
        ins.append(_t(rope_emb))
    has_pre = pre_key_cache is not None
    if has_pre:
        ins += [_t(pre_key_cache), _t(pre_value_cache)]

    def fn(qkv_v, kc, vc, enc_lens, dec_lens, tables, *rest):
        ri = iter(rest)
        if cache_quant:
            kqs, vqs, kdqs, vdqs = (next(ri) for _ in range(4))
        b = next(ri) if has_bias else None
        rope = next(ri) if has_rope else None
        pre_k = next(ri) if has_pre else None
        pre_v = next(ri) if has_pre else None
        B, S = qkv_v.shape[0], qkv_v.shape[1]
        n_blocks, Hkv, bs, D = kc.shape
        HD3 = qkv_v.shape[-1]
        H = (HD3 // D - 2 * Hkv)
        q3 = qkv_v.reshape(B, S, -1, D)
        if b is not None:
            q3 = q3 + b.reshape(1, 1, -1, D)
        q = q3[:, :, :H]                       # [B, S, H, D]
        k_new = q3[:, :, H:H + Hkv]            # [B, S, Hkv, D]
        v_new = q3[:, :, H + Hkv:]
        enc_lens = enc_lens.reshape(B).astype(jnp.int32)
        dec_lens = dec_lens.reshape(B).astype(jnp.int32)
        offs = jnp.where(enc_lens > 0, 0, dec_lens)  # write offset per row
        pos = offs[:, None] + jnp.arange(S)[None, :]          # [B, S] absolute

        if rope is not None:
            # fused rope at the ABSOLUTE cache position, applied to q and the
            # new k before the write (reference decode loop fuses these:
            # fused_multi_transformer_op.cu.h:3097)
            ce, se = rope[0], rope[1]            # [B|1, max_seq, 1, D//2]
            if ce.shape[0] == 1 and B > 1:
                ce = jnp.broadcast_to(ce, (B,) + ce.shape[1:])
                se = jnp.broadcast_to(se, (B,) + se.shape[1:])
            gather_pos = jnp.minimum(pos, ce.shape[1] - 1)
            ce = jnp.take_along_axis(
                ce.astype(jnp.float32), gather_pos[:, :, None, None], axis=1)
            se = jnp.take_along_axis(
                se.astype(jnp.float32), gather_pos[:, :, None, None], axis=1)
            # shared rotary math with fused_rotary_position_embedding —
            # one implementation, conventions cannot drift
            q = _apply_rope_one(q, ce[:, :, 0], se[:, :, 0], use_neox_style)
            k_new = _apply_rope_one(k_new, ce[:, :, 0], se[:, :, 0],
                                    use_neox_style)

        # ---- scatter new K/V into pages (invalid writes -> OOB page, drop) --
        page_idx = pos // bs
        slot = pos % bs
        page_ids = jnp.take_along_axis(
            jnp.where(tables >= 0, tables, n_blocks),
            jnp.minimum(page_idx, tables.shape[1] - 1), axis=1)  # [B, S]
        write_valid = pos < (offs + jnp.where(enc_lens > 0, enc_lens, 1))[:, None]
        flat_pages = jnp.where(write_valid, page_ids, n_blocks).reshape(-1)
        flat_slot = slot.reshape(-1)
        kn = k_new.reshape(B * S, Hkv, D)
        vn = v_new.reshape(B * S, Hkv, D)
        if cache_quant:
            # int8 cache (reference CacheKVInt8 path): per-kv-head symmetric
            # scales; new K/V quantize on write, pages dequantize on read
            kn = jnp.clip(jnp.round(
                kn * kqs.reshape(1, Hkv, 1)), -128, 127)
            vn = jnp.clip(jnp.round(
                vn * vqs.reshape(1, Hkv, 1)), -128, 127)
        kc = kc.at[flat_pages, :, flat_slot].set(kn.astype(kc.dtype), mode="drop")
        vc = vc.at[flat_pages, :, flat_slot].set(vn.astype(vc.dtype), mode="drop")

        total = offs + jnp.where(enc_lens > 0, enc_lens, 1)
        if S == 1 and not cache_quant and pre_k is None and _pallas_decode_on():
            # hot decode loop: paged Pallas kernel — block table resolved in
            # the BlockSpec index_map, no gathered cache copy materialized
            from ....ops.pallas.decode_attention import paged_decode_attention

            out = paged_decode_attention(q[:, 0], kc, vc, tables, total)
            return (out.reshape(B, S, H * D).astype(qkv_v.dtype), qkv_v, kc, vc)

        # ---- gather pages & attend ----
        max_pages = tables.shape[1]
        S_max = max_pages * bs
        gk = kc[jnp.where(tables >= 0, tables, 0)]             # [B, P, Hkv, bs, D]
        gv = vc[jnp.where(tables >= 0, tables, 0)]
        gk = jnp.moveaxis(gk, 2, 3).reshape(B, S_max, Hkv, D)
        gv = jnp.moveaxis(gv, 2, 3).reshape(B, S_max, Hkv, D)
        if cache_quant:
            gk = gk.astype(q.dtype) * kdqs.reshape(1, 1, Hkv, 1).astype(q.dtype)
            gv = gv.astype(q.dtype) * vdqs.reshape(1, 1, Hkv, 1).astype(q.dtype)
        # causal w.r.t. absolute positions; also clip to valid cache range
        qpos = pos                                              # [B, S]
        kpos = jnp.arange(S_max)[None, :]
        keep = kpos[:, None, :] <= qpos[..., None]              # [B, S, S_max]
        keep = keep & (kpos[:, None, :] < total[:, None, None])
        if pre_k is not None:
            # shared prefix KV [B, Hkv, P, D]: logically BEFORE position 0,
            # so every valid query row attends the whole prefix
            P = pre_k.shape[2]
            gk = jnp.concatenate(
                [jnp.moveaxis(pre_k, 1, 2).astype(gk.dtype), gk], axis=1)
            gv = jnp.concatenate(
                [jnp.moveaxis(pre_v, 1, 2).astype(gv.dtype), gv], axis=1)
            row_valid = (enc_lens > 0) | (dec_lens > 0)        # [B]
            keep_pre = jnp.broadcast_to(
                row_valid[:, None, None], (B, S, P))
            keep = jnp.concatenate([keep_pre, keep], axis=-1)
        out = masked_attention(q, gk, gv, keep=keep[:, None])
        return (out.reshape(B, S, H * D).astype(qkv_v.dtype), qkv_v, kc, vc)

    return run_op("block_multihead_attention", fn, ins)


def variable_length_memory_efficient_attention(
    query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
    causal=False, pre_cache_length=0, name=None):
    """Varlen attention on padded batches (reference:
    python/paddle/incubate/nn/functional/variable_length_memory_efficient_attention.py,
    cutlass memory_efficient_attention kernel). q/k/v: [B, H, S, D];
    seq_lens/kv_seq_lens: [B] valid lengths. Causal masking is bottom-right
    aligned per row (last query row ↔ last valid key — flash-attn convention)."""
    from ....nn.functional._attn_math import bottom_right_causal_keep, masked_attention

    ins = [_t(query), _t(key), _t(value), _t(seq_lens), _t(kv_seq_lens)]
    has_mask = mask is not None
    if has_mask:
        ins.append(_t(mask))

    def fn(q, k, v, ql, kl, *rest):
        m = rest[0] if has_mask else None
        B, H, Sq, D = q.shape
        Sk = k.shape[2]
        ql = ql.reshape(B).astype(jnp.int32)
        kl = kl.reshape(B).astype(jnp.int32)
        if causal:
            keep = bottom_right_causal_keep(Sq, Sk, q_lens=ql, kv_lens=kl)
        else:
            keep = (jnp.arange(Sk)[None, :] < kl[:, None])[:, None, None, :]
        out = masked_attention(jnp.moveaxis(q, 1, 2), jnp.moveaxis(k, 1, 2),
                               jnp.moveaxis(v, 1, 2), keep=keep, add_mask=m,
                               scale=scale)
        return jnp.moveaxis(out, 1, 2)

    return run_op("variable_length_memory_efficient_attention", fn, ins)


__all__ += ["masked_multihead_attention", "blha_get_max_len",
            "block_multihead_attention",
            "variable_length_memory_efficient_attention"]


from .fused_attention_ops import (  # noqa: E402,F401
    fused_attention,
    fused_bias_dropout_residual_layer_norm,
    fused_feedforward,
    fused_multi_head_attention,
)

__all__ += ["fused_attention", "fused_multi_head_attention",
            "fused_feedforward", "fused_bias_dropout_residual_layer_norm"]

from .fused_misc_ops import (  # noqa: E402,F401
    fused_dot_product_attention,
    fused_gate_attention,
    fused_matmul_bias,
)

__all__ += ["fused_dot_product_attention", "fused_gate_attention",
            "fused_matmul_bias"]
