"""Fused incubate operators (reference: python/paddle/incubate/operators/).

TPU note: both softmax-mask fusions are expressed as single jax functions
under one run_op — XLA fuses the add + masked softmax into one HBM pass on
TPU, which is all the reference's hand-written CUDA kernel
(paddle/phi/kernels/fusion/gpu/fused_softmax_mask_kernel.cu) buys on GPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op

__all__ = ["softmax_mask_fuse", "softmax_mask_fuse_upper_triangle"]


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fused pass (reference:
    python/paddle/incubate/operators/softmax_mask_fuse.py:26; kernel
    fused_softmax_mask_kernel.cu). x: [B, H, S, S] scores, mask
    broadcastable [B, 1, S, S] additive mask."""

    def fn(a, m):
        return jax.nn.softmax(a + m.astype(a.dtype), axis=-1)

    return run_op("fused_softmax_mask", fn, [x, mask])


def softmax_mask_fuse_upper_triangle(x, name=None):
    """softmax over the causal (lower-triangle-visible) scores: positions
    j > i get -inf before the softmax (reference:
    python/paddle/incubate/operators/softmax_mask_fuse_upper_triangle.py:26;
    kernel fused_softmax_mask_upper_triangle_kernel.cu). x: [B, H, S, S]."""

    def fn(a):
        s = a.shape[-1]
        causal = jnp.tril(jnp.ones((s, s), bool))
        neg = jnp.asarray(jnp.finfo(a.dtype).min, a.dtype)
        return jax.nn.softmax(jnp.where(causal, a, neg), axis=-1)

    return run_op("fused_softmax_mask_upper_triangle", fn, [x])
