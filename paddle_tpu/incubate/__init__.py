"""paddle.incubate equivalent — fused-op APIs and experimental features
(reference: python/paddle/incubate/)."""

from . import nn  # noqa: F401

__all__ = ["nn"]
from . import distributed  # noqa: F401
__all__.append("distributed")
