"""paddle.incubate equivalent — fused-op APIs and experimental features
(reference: python/paddle/incubate/)."""

from . import nn  # noqa: F401

__all__ = ["nn"]
from . import distributed  # noqa: F401
__all__.append("distributed")
from . import optimizer  # noqa: E402,F401
from .optimizer import LookAhead, ModelAverage  # noqa: E402,F401
__all__ += ["optimizer", "LookAhead", "ModelAverage"]
from . import operators  # noqa: E402,F401
from .operators import (  # noqa: E402,F401
    softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)
__all__ += ["operators", "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle"]
