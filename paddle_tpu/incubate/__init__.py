"""paddle.incubate equivalent — fused-op APIs and experimental features
(reference: python/paddle/incubate/)."""

from . import nn  # noqa: F401

__all__ = ["nn"]
from . import distributed  # noqa: F401
__all__.append("distributed")
from . import optimizer  # noqa: E402,F401
from .optimizer import LookAhead, ModelAverage  # noqa: E402,F401
__all__ += ["optimizer", "LookAhead", "ModelAverage"]
