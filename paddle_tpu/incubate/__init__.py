"""paddle.incubate equivalent — fused-op APIs and experimental features
(reference: python/paddle/incubate/)."""

from . import nn  # noqa: F401

__all__ = ["nn"]
from . import distributed  # noqa: F401
__all__.append("distributed")
from . import optimizer  # noqa: E402,F401
from .optimizer import LookAhead, ModelAverage  # noqa: E402,F401
__all__ += ["optimizer", "LookAhead", "ModelAverage"]
from . import operators  # noqa: E402,F401
from .operators import (  # noqa: E402,F401
    softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)
__all__ += ["operators", "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle"]


class _AutotuneNS:
    """reference paddle.incubate.autotune.set_config — maps onto the Pallas
    block-size autotuner (ops/pallas/autotune.py, PADDLE_TPU_AUTOTUNE)."""

    @staticmethod
    def set_config(config=None):
        import json
        import os
        import warnings

        if isinstance(config, str):
            # reference warns and falls back to defaults on an unreadable or
            # invalid JSON path (python/paddle/incubate/autotune.py)
            try:
                with open(config) as f:
                    config = json.load(f)
            except Exception as e:
                warnings.warn(
                    f"set_config: cannot load config file {config!r} "
                    f"({type(e).__name__}: {e}); using default config.")
                config = None
        if config is not None and not isinstance(config, dict):
            warnings.warn(
                f"set_config expects None, dict, or a JSON file path; got "
                f"{type(config).__name__}; using default config.")
            config = None
        if config is None:
            # default = enable all tuning, reference behavior
            os.environ["PADDLE_TPU_AUTOTUNE"] = "1"
            return
        # reference only touches kernel autotune when the dict actually
        # carries a kernel section
        kernel = config.get("kernel")
        if isinstance(kernel, dict) and "enable" in kernel:
            os.environ["PADDLE_TPU_AUTOTUNE"] = \
                "1" if bool(kernel["enable"]) else "0"


autotune = _AutotuneNS()
__all__.append("autotune")


class _JitNS:
    """reference paddle.incubate.jit.inference — compile a callable/Layer
    for inference (maps to to_static + eval)."""

    @staticmethod
    def inference(function=None, **kw):
        from .. import jit as _jit
        import paddle_tpu.nn as _nn

        if kw:
            import warnings

            warnings.warn(
                f"incubate.jit.inference: ignoring unsupported options "
                f"{sorted(kw)} (XLA owns caching and precision here)",
                stacklevel=2)

        def wrap(f):
            if isinstance(f, _nn.Layer):
                f.eval()
            return _jit.to_static(f)

        return wrap if function is None else wrap(function)


jit = _JitNS()
__all__.append("jit")
