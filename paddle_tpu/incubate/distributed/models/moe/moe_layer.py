"""Mixture-of-experts layer with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:261
(MoELayer) — there, routing produces index lists, tokens are exchanged with
custom `global_scatter`/`global_gather` NCCL all-to-alls, and each rank runs
its local experts.

TPU-native redesign (GShard formulation — MoE was born on TPU): routing
produces dense dispatch/combine tensors and the whole layer is three einsums

    xe  = einsum('tec,tm->ecm', dispatch, x)      # dispatch
    ye  = expert_ffn(xe)                          # [E,C,M] -> [E,C,M] batched GEMMs
    out = einsum('tec,ecm->tm', combine, ye)      # combine

When the expert axis E is sharded over a mesh axis (expert parallelism), the
sharding constraint on `xe`/`ye` makes GSPMD insert the all-to-alls on ICI —
the compiled equivalent of the reference's global_scatter/global_gather.
Static shapes (capacity) keep everything jit-compatible; overflow tokens are
dropped exactly as the reference's capacity pruning does.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu.nn as nn
from .....framework.core import Tensor, run_op
from ..... import distributed as _dist_pkg  # noqa: F401  (package init ordering)
from .....distributed import env as _env
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer", "ExpertFFN"]


_constrain_value = _env.constrain_array


class ExpertFFN(nn.Layer):
    """Stacked expert FFN: all experts' weights in one [E, ...] tensor so the
    expert dimension is a real mesh-shardable axis and the per-expert GEMMs
    batch onto the MXU (replaces the reference's per-expert Linear list +
    fused_moe cutlass grouped GEMM, fusion/cutlass/fused_moe_kernel.cu)."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu",
                 ep_axis=None):
        super().__init__()
        self.num_experts = num_experts
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden])
        self.b1 = self.create_parameter([num_experts, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model])
        self.b2 = self.create_parameter([num_experts, 1, d_model], is_bias=True)
        self.activation = activation
        if ep_axis:
            for p in (self.w1, self.b1, self.w2, self.b2):
                p.dist_attr = P(ep_axis, *([None] * (len(p.shape) - 1)))
                p.is_distributed = True

    def forward(self, xe):
        """xe: [E, C, M] -> [E, C, M]."""
        act = getattr(jax.nn, self.activation)

        def fn(x, w1, b1, w2, b2):
            h = jnp.einsum("ecm,emh->ech", x, w1) + b1
            h = act(h)
            return jnp.einsum("ech,ehm->ecm", h, w2) + b2

        return run_op("expert_ffn", fn, [xe, self.w1, self.b1, self.w2, self.b2])


class MoELayer(nn.Layer):
    """reference: moe_layer.py:261 — MoELayer(d_model, experts, gate, moe_group).

    `experts` is either an ExpertFFN (stacked fast path, expert-parallel
    capable) or a list of nn.Layer (reference-parity path; each expert applied
    to its [C, M] slice — replicated, eager/jit both fine).
    `gate` is a BaseGate instance or a config dict {"type": "gshard"|"switch"|
    "naive", "top_k": k} exactly like the reference's gate config.
    `ep_axis` names the mesh axis experts shard over (the analog of
    moe_group — the reference uses the data-parallel group)."""

    def __init__(self, d_model, experts, gate=None, moe_group=None, mp_group=None,
                 recompute_interval=0, ep_axis=None, name=None):
        super().__init__()
        self.d_model = d_model
        self.ep_axis = ep_axis
        if isinstance(experts, ExpertFFN):
            self.experts = experts
            self.num_expert = experts.num_experts
            self._stacked = True
        else:
            self.experts = nn.LayerList(experts)
            self.num_expert = len(experts)
            self._stacked = False

        if isinstance(gate, BaseGate):
            self.gate = gate
        else:
            cfg = dict(gate or {})
            gtype = cfg.pop("type", "gshard")
            topk = cfg.pop("top_k", 2)
            cls = {"gshard": GShardGate, "switch": SwitchGate, "naive": NaiveGate}[gtype]
            self.gate = cls(d_model, self.num_expert, topk=topk, **cfg)

    @property
    def l_aux(self):
        return self.gate.l_aux

    def forward(self, inp):
        shape = inp.shape
        x = inp.reshape([-1, self.d_model])
        combine, dispatch, _l_aux = self.gate(x)

        spec_e = P(self.ep_axis, None, None) if self.ep_axis else None

        def dispatch_fn(d, xv):
            xe = jnp.einsum("tec,tm->ecm", d, xv)
            if spec_e is not None:
                xe = _constrain_value(xe, spec_e)
            return xe

        xe = run_op("moe_dispatch", dispatch_fn, [dispatch, x])

        if self._stacked:
            ye = self.experts(xe)
        else:
            outs = [self.experts[e](xe[e]) for e in range(self.num_expert)]
            ye = run_op("moe_stack", lambda *ys: jnp.stack(ys, 0), outs)

        def combine_fn(c, yv):
            if spec_e is not None:
                yv = _constrain_value(yv, spec_e)
            return jnp.einsum("tec,ecm->tm", c, yv)

        out = run_op("moe_combine", combine_fn, [combine, ye])
        return out.reshape(shape[:-1] + [self.d_model] if isinstance(shape, list)
                           else list(shape[:-1]) + [self.d_model])
