"""Mixture-of-experts layer with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:261
(MoELayer) — there, routing produces index lists, tokens are exchanged with
custom `global_scatter`/`global_gather` NCCL all-to-alls, and each rank runs
its local experts.

Two TPU formulations share one gate (gate.py `_probs_and_keep`), selected by
`PADDLE_TPU_MOE_FAST` (default on, read once per forward trace and captured
into the traced program like the PR-7 safe-softmax / PR-12 fused-kernel
toggles — an env flip between forward and backward can never mix paths):

**Dense reference path** (`PADDLE_TPU_MOE_FAST=0` — the parity oracle).
The GShard einsum formulation: routing produces dense dispatch/combine
tensors and the whole layer is three einsums

    xe  = einsum('tec,tm->ecm', dispatch, x)      # dispatch
    ye  = expert_ffn(xe)                          # [E,C,M] batched GEMMs
    out = einsum('tec,ecm->tm', combine, ye)      # combine

Correct, but the one-hot dispatch/combine einsums burn O(T·E·C·M) FLOPs on
masks that are almost entirely zeros.

**Sorted fast path** (default). Routing keeps only (expert id, weight) per
(token, choice); tokens are scattered by a cheap positional permutation into
a uniform-stride [E, R, M] buffer (R = per-expert row stride; capacity
overflow is a `pos >= capacity` drop mask on the scatter, not one-hot
pruning), the experts run as a Pallas grouped/ragged GEMM over the
contiguous per-expert row groups (ops/pallas/grouped_gemm.py — dead row
tiles skip the MXU entirely), and outputs gather back through the saved
permutation. Dispatch+combine cost drops from O(T·E·C·M) to O(T·k·M) index
arithmetic; expert FLOPs scale with routed tokens, not capacity.

**Expert parallelism.** With `ep_axis` set and that mesh axis > 1, the
[E, R, M] buffer is split into `PADDLE_TPU_MOE_A2A_CHUNKS` row chunks; each
chunk is constrained to the expert-sharded layout (the dispatch all-to-all
GSPMD materializes from the token-sharded producer), runs its grouped GEMMs
under shard_map over `ep` (expert-stacked weights sharded on `ep`, the
SpecLayout `expert_stacked` group), and combines back per chunk — so chunk
k+1's all-to-all overlaps chunk k's expert GEMM (the T3 chunking pattern,
arxiv 2401.16677). Per-step a2a volume is registered at trace time
(distributed/moe_comm.py) and re-emitted host-side each step as
`collective_{calls,bytes}_total{op="all_to_all"}` + `comm_task(kind="a2a")`
intervals, so `overlap_fraction` covers MoE traffic (docs/MOE.md).
"""

from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import paddle_tpu.nn as nn
from .....framework.core import run_op
from ..... import distributed as _dist_pkg  # noqa: F401  (package init ordering)
from .....distributed import env as _env
from .....distributed import moe_comm as _moe_comm
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer", "ExpertFFN", "moe_fast_on", "moe_a2a_chunks"]


_constrain_value = _env.constrain_array


def moe_fast_on() -> bool:
    """PADDLE_TPU_MOE_FAST toggle, default ON. Read once per forward trace
    and captured into the traced program; =0 keeps the dense einsum path as
    the reference oracle for A/B and parity tests."""
    return os.environ.get("PADDLE_TPU_MOE_FAST", "1") != "0"


def moe_a2a_chunks() -> int:
    """PADDLE_TPU_MOE_A2A_CHUNKS (default 2, clamped to [1, 8]): row chunks
    the expert buffer is split into under expert parallelism so dispatch
    all-to-alls pipeline against expert GEMMs. 1 disables chunking (one
    exposed a2a each way, the A/B baseline)."""
    try:
        n = int(os.environ.get("PADDLE_TPU_MOE_A2A_CHUNKS", "2"))
    except ValueError:
        n = 2
    return max(1, min(n, 8))


class ExpertFFN(nn.Layer):
    """Stacked expert FFN: all experts' weights in one [E, ...] tensor so the
    expert dimension is a real mesh-shardable axis and the per-expert GEMMs
    batch onto the MXU (replaces the reference's per-expert Linear list +
    fused_moe cutlass grouped GEMM, fusion/cutlass/fused_moe_kernel.cu)."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu",
                 ep_axis=None):
        super().__init__()
        self.num_experts = num_experts
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden])
        self.b1 = self.create_parameter([num_experts, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model])
        self.b2 = self.create_parameter([num_experts, 1, d_model], is_bias=True)
        self.activation = activation
        if ep_axis:
            for p in (self.w1, self.b1, self.w2, self.b2):
                p.dist_attr = P(ep_axis, *([None] * (len(p.shape) - 1)))
                p.is_distributed = True

    def forward(self, xe):
        """xe: [E, C, M] -> [E, C, M]."""
        act = getattr(jax.nn, self.activation)

        def fn(x, w1, b1, w2, b2):
            h = jnp.einsum("ecm,emh->ech", x, w1) + b1
            h = act(h)
            return jnp.einsum("ech,ehm->ecm", h, w2) + b2

        return run_op("expert_ffn", fn, [xe, self.w1, self.b1, self.w2, self.b2])


class MoELayer(nn.Layer):
    """reference: moe_layer.py:261 — MoELayer(d_model, experts, gate, moe_group).

    `experts` is either an ExpertFFN (stacked fast path, expert-parallel
    capable) or a list of nn.Layer (reference-parity path; each expert applied
    to its [C, M] slice — replicated, eager/jit both fine).
    `gate` is a BaseGate instance or a config dict {"type": "gshard"|"switch"|
    "naive", "top_k": k} exactly like the reference's gate config.
    `ep_axis` names the mesh axis experts shard over (the analog of
    moe_group — the reference uses the data-parallel group; the planner's
    canonical axis is "ep", env.AXIS_ORDER)."""

    def __init__(self, d_model, experts, gate=None, moe_group=None, mp_group=None,
                 recompute_interval=0, ep_axis=None, name=None):
        super().__init__()
        self.d_model = d_model
        self.ep_axis = ep_axis
        if isinstance(experts, ExpertFFN):
            self.experts = experts
            self.num_expert = experts.num_experts
            self._stacked = True
        else:
            self.experts = nn.LayerList(experts)
            self.num_expert = len(experts)
            self._stacked = False

        if isinstance(gate, BaseGate):
            self.gate = gate
        else:
            cfg = dict(gate or {})
            gtype = cfg.pop("type", "gshard")
            topk = cfg.pop("top_k", 2)
            cls = {"gshard": GShardGate, "switch": SwitchGate, "naive": NaiveGate}[gtype]
            self.gate = cls(d_model, self.num_expert, topk=topk, **cfg)

    @property
    def l_aux(self):
        return self.gate.l_aux

    # ------------------------------------------------------------------ #
    # sorted fast path
    # ------------------------------------------------------------------ #

    def _ep_size(self):
        mesh = _env.get_global_mesh()
        if not self.ep_axis or mesh is None:
            return 1
        return int(mesh.shape.get(self.ep_axis, 1))

    def _fast_fn(self, cap, Rc, chunks, ep):
        """The whole fast layer as ONE pure fn of the raw arrays (a single
        dispatch-cache entry / trace). `cap`/`Rc`/`chunks`/`ep` are static
        (python ints captured per trace)."""
        gate = self.gate
        E = self.num_expert
        k = gate.top_k
        act = getattr(jax.nn, self.experts.activation)
        ep_axis = self.ep_axis
        mesh = _env.get_global_mesh()
        R = Rc * chunks

        # Pallas only on TPU or under the interpreter (the nn.functional
        # kernel-dispatch rule); the CPU fallback keeps the SAME sorted
        # layout and runs the groups as one batched einsum — dead rows are
        # zero by the scatter's construction, so values are identical
        from .....ops.pallas.grouped_gemm import grouped_matmul, kernel_usable
        use_kernel = kernel_usable()

        def gmm3(x3, w, sizes):
            """[E, Rc, K] @ [E, K, N] grouped — under shard_map over `ep`
            when expert-parallel (weights/rows/sizes all sharded on the
            expert dim; other mesh axes stay on GSPMD auto)."""

            def body(xl, wl, sl):
                El = xl.shape[0]
                if not use_kernel:
                    return jnp.einsum("erk,ekn->ern",
                                      xl.astype(wl.dtype), wl)
                out = grouped_matmul(xl.reshape(El * Rc, xl.shape[-1]),
                                     wl, sl)
                return out.reshape(El, Rc, out.shape[-1])

            if ep > 1:
                from .....parallel.shmap_compat import shard_map

                spec3 = P(ep_axis, None, None)
                return shard_map(
                    body, mesh=mesh, in_specs=(spec3, spec3, P(ep_axis)),
                    out_specs=spec3, axis_names={ep_axis},
                    check_vma=False)(x3, w, sizes)
            return body(x3, w, sizes)

        def fn(xv, gw, gb, w1, b1, w2, b2):
            S, M = xv.shape
            topi, topv, keep, l_aux = gate._route(xv, gw, gb)

            # flat (choice, token) arrays in choice-major order j*S+s — the
            # dense path's capacity priority (all 1st choices rank before
            # any 2nd choice)
            eid = topi.T.reshape(-1).astype(jnp.int32)       # [k*S]
            wts = topv.T.reshape(-1)
            valid = keep.T.reshape(-1)
            tok = jnp.tile(jnp.arange(S, dtype=jnp.int32), k)

            # rank within expert among valid entries, in flat order: stable
            # sort by expert (invalid entries sort to the E sentinel), then
            # position = index - run start. Identical to the dense path's
            # cumsum-over-one-hot slot assignment, at O(kS log kS).
            key = jnp.where(valid, eid, E)
            order = jnp.argsort(key, stable=True)
            counts = jax.ops.segment_sum(
                jnp.ones_like(key), key, num_segments=E + 1)[:E]
            start = jnp.cumsum(counts) - counts              # [E]
            srt = key[order]
            pos_sorted = (jnp.arange(k * S, dtype=jnp.int32)
                          - start[jnp.clip(srt, 0, E - 1)].astype(jnp.int32))
            pos = jnp.zeros((k * S,), jnp.int32).at[order].set(pos_sorted)

            # capacity overflow: a cheap drop mask, not one-hot pruning
            kept = valid & (pos < cap)
            slot = jnp.where(kept, eid * R + pos, E * R)     # E*R == drop
            xs = jnp.zeros((E * R, M), xv.dtype).at[slot].set(
                xv[tok], mode="drop")
            sizes = jnp.minimum(counts, cap).astype(jnp.int32)  # live rows/E

            xs3 = xs.reshape(E, R, M)
            spec3 = P(ep_axis, None, None) if ep > 1 else None
            g = jnp.zeros((k * S, M), xv.dtype)
            for c in range(chunks):
                xc = xs3[:, c * Rc:(c + 1) * Rc]
                if spec3 is not None:
                    # the dispatch all-to-all: token-sharded producer ->
                    # expert-sharded consumer, materialized by GSPMD per
                    # chunk so chunk c+1's exchange overlaps chunk c's GEMM
                    xc = _constrain_value(xc, spec3)
                sc = jnp.clip(sizes - c * Rc, 0, Rc)
                h = act(gmm3(xc, w1, sc) + b1)
                yc = gmm3(h, w2, sc) + b2                    # [E, Rc, M]
                # per-chunk combine gather (the reverse a2a, also chunked):
                # each (token, choice) lands in exactly one chunk, so the
                # running sum only ever adds zeros elsewhere
                local = pos - c * Rc
                in_c = kept & (local >= 0) & (local < Rc)
                slot_c = jnp.where(in_c, eid * Rc + local, E * Rc)
                g = g + jnp.take(yc.reshape(E * Rc, M), slot_c, axis=0,
                                 mode="fill", fill_value=0)
            out = (wts[:, None].astype(xv.dtype) * g).reshape(k, S, M).sum(0)
            return out, l_aux

        return fn

    def _forward_fast(self, x):
        S = int(x.shape[0])
        cap = self.gate.capacity(S)
        ep = self._ep_size()
        if ep > 1 and self.num_expert % ep:
            raise ValueError(
                f"expert count {self.num_expert} not divisible by the "
                f"'{self.ep_axis}' mesh axis size {ep}")
        chunks = moe_a2a_chunks() if ep > 1 else 1
        from .....ops.pallas.grouped_gemm import row_stride

        Rc = row_stride(int(math.ceil(cap / chunks)))
        fn = self._fast_fn(cap, Rc, chunks, ep)
        e = self.experts
        out, l_aux = run_op(
            "moe_fast", fn,
            [x, self.gate.gate.weight, self.gate.gate.bias,
             e.w1, e.b1, e.w2, e.b2], n_outputs=2)
        self.gate.set_loss(l_aux)
        if ep > 1:
            # per-step a2a volume for the host-side emission
            # (DistributedTrainStep._post_dispatch): analytic — bytes that
            # change shards when the routed rows reshard token->expert and
            # back. Registered once per trace, replayed per executed step.
            itemsize = np.dtype(str(x.dtype)).itemsize
            rows = min(self.gate.top_k * S, self.num_expert * cap)
            nbytes = int(2 * rows * self.d_model * itemsize * (ep - 1) / ep)
            _moe_comm.note_a2a(
                f"moe/a2a/{self.ep_axis}x{ep}", nbytes, calls=2 * chunks,
                overlapped=chunks > 1)
        return out

    def forward(self, inp):
        shape = inp.shape
        x = inp.reshape([-1, self.d_model])

        gate_cls = type(self.gate)
        fast_capable = (
            self._stacked and moe_fast_on()
            # gates must expose the shared router math (a custom BaseGate
            # subclass that only implements dense _routing stays dense),
            # and must NOT override the dense _routing itself — a custom
            # dispatch there would silently diverge from _route's routing
            and gate_cls._probs_and_keep is not BaseGate._probs_and_keep
            and gate_cls._routing is BaseGate._routing
            and getattr(self.gate, "gate", None) is not None)
        if fast_capable:
            out = self._forward_fast(x)
        else:
            out = self._forward_dense(x)
        return out.reshape(list(shape[:-1]) + [self.d_model])

    def _forward_dense(self, x):
        combine, dispatch, _l_aux = self.gate(x)

        ep = self._ep_size()
        spec_e = (P(self.ep_axis, None, None)
                  if self.ep_axis and ep > 1 else None)
        if spec_e is not None:
            # the oracle leg of the fast-vs-einsum A/B does REAL a2a too:
            # GSPMD reshards the full capacity-padded [E, C, M] buffer
            # (empty slots included — that's the dense formulation's wire
            # cost) each way, unchunked, so register it like the fast path
            # does or the baseline reads as comm-free
            S = int(x.shape[0])
            cap = self.gate.capacity(S)
            itemsize = np.dtype(str(x.dtype)).itemsize
            nbytes = int(2 * self.num_expert * cap * self.d_model
                         * itemsize * (ep - 1) / ep)
            _moe_comm.note_a2a(f"moe/a2a/{self.ep_axis}x{ep}", nbytes,
                               calls=2, overlapped=False)

        def dispatch_fn(d, xv):
            xe = jnp.einsum("tec,tm->ecm", d, xv)
            if spec_e is not None:
                xe = _constrain_value(xe, spec_e)
            return xe

        xe = run_op("moe_dispatch", dispatch_fn, [dispatch, x])

        if self._stacked:
            ye = self.experts(xe)
        else:
            outs = [self.experts[e](xe[e]) for e in range(self.num_expert)]
            ye = run_op("moe_stack", lambda *ys: jnp.stack(ys, 0), outs)

        def combine_fn(c, yv):
            if spec_e is not None:
                yv = _constrain_value(yv, spec_e)
            return jnp.einsum("tec,ecm->tm", c, yv)

        return run_op("moe_combine", combine_fn, [combine, ye])
