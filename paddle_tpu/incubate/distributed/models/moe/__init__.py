from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
from .moe_layer import (ExpertFFN, MoELayer, moe_a2a_chunks,  # noqa: F401
                        moe_fast_on)

__all__ = ["BaseGate", "GShardGate", "NaiveGate", "SwitchGate", "ExpertFFN",
           "MoELayer", "moe_fast_on", "moe_a2a_chunks"]
