from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
from .moe_layer import ExpertFFN, MoELayer  # noqa: F401

__all__ = ["BaseGate", "GShardGate", "NaiveGate", "SwitchGate", "ExpertFFN", "MoELayer"]
