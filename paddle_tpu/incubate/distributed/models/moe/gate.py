"""MoE gates.

Reference: python/paddle/incubate/distributed/models/moe/gate/
{base_gate,naive_gate,gshard_gate,switch_gate}.py — linear router producing
per-token expert scores, top-k selection, capacity enforcement and the GShard
load-balancing auxiliary loss.

TPU-native redesign: gates return dense dispatch/combine tensors
([S, E, C] einsum operands) instead of index lists — index-free routing keeps
everything static-shaped for XLA and feeds the MXU directly (this is the
original GShard-on-TPU formulation). The auxiliary loss is stored on the gate
(`gate.l_aux`) exactly like the reference's BaseGate.set_loss/get_loss.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn
from .....framework import random as rnd
from .....framework.core import run_op

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]


def _topk_route(probs, k, normalize_topk, choice_keep=None):
    """Raw top-k routing, shared by the dense einsum path and the sorted
    fast path so the two can never disagree on route choices or the aux
    loss.

    probs: [S, E] router probabilities. Returns (topi [S,k] int32 expert
    ids, topv [S,k] combine weights — zeroed for dropped choices, keep
    [S,k] bool, l_aux). The load-balancing aux loss (GShard eq.4) is
    computed from the PRE-DROP router stats — raw probs and the raw first
    choice — never from post-capacity (or post-random-routing) dispatch
    counts: stats taken after drops are biased TOWARD already-overflowed
    experts (their overflow is exactly what the drop removed), which
    inverts the loss's pressure. Pinned by
    tests/test_moe.py::TestGateAuxLoss."""
    S, E = probs.shape
    topv, topi = jax.lax.top_k(probs, k)  # [S, k]
    if normalize_topk:
        topv = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)

    # aux loss BEFORE any drop logic: E * sum_e mean_prob_e * frac_top1_e
    me = probs.mean(0)                                       # [E]
    ce = jax.nn.one_hot(topi[:, 0], E, dtype=probs.dtype).mean(0)
    l_aux = (me * ce).sum() * E

    if choice_keep is not None:
        keep = choice_keep
        topv = topv * keep.astype(topv.dtype)
    else:
        keep = jnp.ones(topi.shape, bool)
    return topi, topv, keep, l_aux


def _topk_dispatch(probs, k, capacity, normalize_topk, choice_keep=None):
    """Dense top-k routing with capacity.

    probs: [S, E] router probabilities. Returns (combine [S,E,C],
    dispatch [S,E,C] 0/1, l_aux scalar). Tokens overflowing an expert's
    capacity are dropped (zero rows — same semantics as the reference's
    capacity pruning in gshard_gate.py). `choice_keep` [S, k] bool drops
    individual (token, choice) routes (GShard random routing).
    """
    S, E = probs.shape
    topi, topv, keepc, l_aux = _topk_route(probs, k, normalize_topk,
                                           choice_keep)
    onehot = (jax.nn.one_hot(topi, E, dtype=probs.dtype)
              * keepc.astype(probs.dtype)[..., None])        # [S, k, E]

    # choice-major priority: all 1st choices rank before any 2nd choice
    m = jnp.transpose(onehot, (1, 0, 2)).reshape(k * S, E)
    pos_before = jnp.cumsum(m, axis=0) - m               # tokens ahead, [k*S, E]
    pos = (pos_before * m).sum(-1)                       # scalar slot per (choice, token)
    keep = (pos < capacity) & (m.sum(-1) > 0)
    slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=probs.dtype) \
        * keep[:, None].astype(probs.dtype)
    # dispatch_j[s, e, c] = m[j*S+s, e] * slot[j*S+s, c]
    disp = jnp.einsum("xe,xc->xec", m, slot).reshape(k, S, E, capacity)
    weights = jnp.transpose(topv, (1, 0))                # [k, S]
    combine = jnp.einsum("ks,ksec->sec", weights, disp)
    dispatch = disp.sum(0)                               # [S, E, C] (0/1 by construction)
    return combine, dispatch, l_aux


class BaseGate(nn.Layer):
    """reference: gate/base_gate.py — holds num_expert/world_size and the aux loss."""

    def __init__(self, num_expert, world_size=1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self.loss = None

    def set_loss(self, loss):
        self.loss = loss

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss

    @property
    def l_aux(self):
        return self.loss

    #: combine weights renormalized over the selected top-k (GShard style)
    _normalize_topk = True

    def capacity(self, num_tokens):
        raise NotImplementedError

    def _probs_and_keep(self, xv, w, b):
        """Pure fn -> (probs [S, E] f32, choice_keep [S, k] bool | None).
        The ONE place each gate's router math lives — both the dense
        einsum dispatch and the sorted fast path route through it."""
        raise NotImplementedError

    def _route(self, xv, w, b):
        """Raw routing for the sorted fast path: (topi [S,k], topv [S,k]
        in xv.dtype, keep [S,k] bool, l_aux). No dense [S,E,C] tensors are
        built — capacity enforcement is the caller's cheap positional drop
        mask, not one-hot pruning."""
        probs, keep = self._probs_and_keep(xv, w, b)
        topi, topv, keepc, l_aux = _topk_route(
            probs, self.top_k, self._normalize_topk, keep)
        return topi, topv.astype(xv.dtype), keepc, l_aux

    def _routing(self, xv, w, b):
        """Pure fn of raw arrays -> (combine, dispatch, l_aux)."""
        probs, keep = self._probs_and_keep(xv, w, b)
        cap = self.capacity(xv.shape[0])
        c, d, l = _topk_dispatch(probs, self.top_k, cap,
                                 self._normalize_topk, choice_keep=keep)
        return c.astype(xv.dtype), d.astype(xv.dtype), l

    def forward(self, x):
        out = run_op(self.__class__.__name__.lower(), self._routing,
                     [x, self.gate.weight, self.gate.bias])
        self.set_loss(out[2])
        return out  # (combine [S,E,C], dispatch [S,E,C], l_aux)


class NaiveGate(BaseGate):
    """Linear router + plain top-k, no capacity drop (reference: naive_gate.py).

    Dense form: capacity = S so no token is ever dropped."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(num_expert, world_size)
        self.top_k = topk
        self.gate = nn.Linear(d_model, self.tot_expert)

    def capacity(self, num_tokens):
        return int(num_tokens)

    def _probs_and_keep(self, xv, w, b):
        return jax.nn.softmax((xv @ w + b).astype(jnp.float32), axis=-1), None


class GShardGate(BaseGate):
    """Top-2 gate with capacity + load-balance loss (reference: gshard_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), random_routing=True, group=None):
        super().__init__(num_expert, world_size)
        assert topk == 2, "gshard gate is top-2"
        self.top_k = 2
        self.capacity_factor = capacity  # (train, eval) multipliers
        self.random_routing = random_routing
        self.gate = nn.Linear(d_model, self.tot_expert)

    def capacity(self, num_tokens):
        f = self.capacity_factor[0] if self.training else self.capacity_factor[1]
        return max(1, int(math.ceil(f * num_tokens / self.tot_expert)))

    def _probs_and_keep(self, xv, w, b):
        probs = jax.nn.softmax((xv @ w + b).astype(jnp.float32), axis=-1)
        choice_keep = None
        if self.random_routing and self.training:
            # GShard §3.2: the 2nd expert fires with probability ∝ its
            # weight — kept when 2*w2 > u ~ U(0,1)
            topv, _ = jax.lax.top_k(probs, 2)
            u = jax.random.uniform(rnd.next_key(), (xv.shape[0],), jnp.float32)
            keep2 = (2.0 * topv[:, 1]) > u
            choice_keep = jnp.stack(
                [jnp.ones_like(keep2), keep2], axis=-1)
        return probs, choice_keep


class SwitchGate(BaseGate):
    """Top-1 switch routing with jitter noise (reference: switch_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        super().__init__(num_expert, world_size)
        assert topk == 1, "switch gate is top-1"
        self.top_k = 1
        self.switch_eps = switch_eps
        self.capacity_factor = capacity
        self.gate = nn.Linear(d_model, self.tot_expert)

    def capacity(self, num_tokens):
        f = self.capacity_factor[0] if self.training else self.capacity_factor[1]
        return max(1, int(math.ceil(f * num_tokens / self.tot_expert)))

    _normalize_topk = False

    def _probs_and_keep(self, xv, w, b):
        logits = xv @ w + b
        if self.training and self.switch_eps > 0:
            noise = jax.random.uniform(rnd.next_key(), logits.shape, logits.dtype,
                                       1.0 - self.switch_eps, 1.0 + self.switch_eps)
            logits = logits * noise
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1), None
