"""Kernel-level op surface: one callable per ops.yaml name.

The reference exposes every phi kernel as ``paddle._C_ops.<op>`` (generated
pybind, paddle/fluid/pybind/eager_op_function.cc); user-facing Python APIs
are wrappers over these. This module is the same surface for the TPU
framework: each name maps to the real implementation — the public API
function where one exists, a direct jnp/run_op implementation where the op
is a kernel-level primitive without separate public API. Names that are
deliberately out of scope (PS-era CPU ops, stream/memcpy runtime internals,
DGC) are listed in DESIGN_DECISIONS.md §ops-audit rather than stubbed.

Ops are grouped below in the same buckets as the round-4 audit table.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .framework.core import Tensor, run_op, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _inplace(param, new_val):
    param._value = new_val
    return param


# --------------------------------------------------------------------------- #
# optimizer update kernels (reference: phi/kernels/gpu/{sgd,adam,...}_kernel.cu
# — the Python optimizer classes fuse these into their compiled steps; the
# functional forms here are the standalone kernel semantics)
# --------------------------------------------------------------------------- #

def sgd_(param, learning_rate, grad, master_param=None,
         multi_precision=False):
    lr = _val(learning_rate).reshape(())
    return _inplace(param, _val(param) - lr * _val(grad))


def momentum_(param, grad, velocity, learning_rate, master_param=None,
              mu=0.9, use_nesterov=False, *a, **kw):
    lr = _val(learning_rate).reshape(())
    v = mu * _val(velocity) + _val(grad)
    _inplace(velocity, v)
    if use_nesterov:
        step = _val(grad) + mu * v
    else:
        step = v
    return _inplace(param, _val(param) - lr * step)


def merged_momentum_(params, grads, velocities, learning_rate, *a, **kw):
    for p, g, v in zip(params, grads, velocities):
        momentum_(p, g, v, learning_rate, *a, **kw)
    return params


def adam_(param, grad, learning_rate, moment1, moment2, beta1_pow,
          beta2_pow, master_param=None, beta1=0.9, beta2=0.999,
          epsilon=1e-8, *a, **kw):
    lr = _val(learning_rate).reshape(())
    m1 = beta1 * _val(moment1) + (1 - beta1) * _val(grad)
    m2 = beta2 * _val(moment2) + (1 - beta2) * _val(grad) ** 2
    b1p = _val(beta1_pow) * beta1
    b2p = _val(beta2_pow) * beta2
    _inplace(moment1, m1)
    _inplace(moment2, m2)
    _inplace(beta1_pow, b1p)
    _inplace(beta2_pow, b2p)
    mh = m1 / (1 - b1p)
    vh = m2 / (1 - b2p)
    return _inplace(param, _val(param) - lr * mh / (jnp.sqrt(vh) + epsilon))


def merged_adam_(params, grads, learning_rate, moment1s, moment2s,
                 beta1_pows, beta2_pows, *a, **kw):
    for p, g, m1, m2, b1, b2 in zip(params, grads, moment1s, moment2s,
                                    beta1_pows, beta2_pows):
        adam_(p, g, learning_rate, m1, m2, b1, b2, *a, **kw)
    return params


def adamw_(param, grad, learning_rate, moment1, moment2, beta1_pow,
           beta2_pow, master_param=None, beta1=0.9, beta2=0.999,
           epsilon=1e-8, coeff=0.01, *a, **kw):
    lr = _val(learning_rate).reshape(())
    _inplace(param, _val(param) * (1 - lr * coeff))
    return adam_(param, grad, learning_rate, moment1, moment2, beta1_pow,
                 beta2_pow, None, beta1, beta2, epsilon)


def adamax_(param, grad, learning_rate, moment, inf_norm, beta1_pow,
            beta1=0.9, beta2=0.999, epsilon=1e-8, *a, **kw):
    lr = _val(learning_rate).reshape(())
    m = beta1 * _val(moment) + (1 - beta1) * _val(grad)
    u = jnp.maximum(beta2 * _val(inf_norm), jnp.abs(_val(grad)))
    _inplace(moment, m)
    _inplace(inf_norm, u)
    return _inplace(param, _val(param)
                    - lr / (1 - _val(beta1_pow)) * m / (u + epsilon))


def adagrad_(param, grad, moment, learning_rate, epsilon=1e-6, *a, **kw):
    lr = _val(learning_rate).reshape(())
    m = _val(moment) + _val(grad) ** 2
    _inplace(moment, m)
    return _inplace(param, _val(param)
                    - lr * _val(grad) / (jnp.sqrt(m) + epsilon))


def adadelta_(param, grad, avg_squared_grad, avg_squared_update,
              learning_rate=1.0, rho=0.95, epsilon=1e-6, *a, **kw):
    g = _val(grad)
    asg = rho * _val(avg_squared_grad) + (1 - rho) * g * g
    upd = (jnp.sqrt(_val(avg_squared_update) + epsilon)
           / jnp.sqrt(asg + epsilon)) * g
    asu = rho * _val(avg_squared_update) + (1 - rho) * upd * upd
    _inplace(avg_squared_grad, asg)
    _inplace(avg_squared_update, asu)
    lr = _val(learning_rate).reshape(()) if isinstance(
        learning_rate, Tensor) else learning_rate
    return _inplace(param, _val(param) - lr * upd)


def rmsprop_(param, mean_square, grad, moment, learning_rate,
             mean_grad=None, epsilon=1e-10, decay=0.9, momentum=0.0,
             centered=False, *a, **kw):
    lr = _val(learning_rate).reshape(())
    g = _val(grad)
    ms = decay * _val(mean_square) + (1 - decay) * g * g
    _inplace(mean_square, ms)
    denom = ms
    if centered:
        mg = decay * _val(mean_grad) + (1 - decay) * g
        _inplace(mean_grad, mg)
        denom = ms - mg * mg
    mom = momentum * _val(moment) + lr * g / jnp.sqrt(denom + epsilon)
    _inplace(moment, mom)
    return _inplace(param, _val(param) - mom)


def nadam_(param, grad, learning_rate, momentum_decay_pow, beta2_pow,
           mu_product, moment1, moment2, beta1=0.9, beta2=0.999,
           epsilon=1e-8, momentum_decay=0.004, *a, **kw):
    """NAdam (Dozat 2016): Nesterov lookahead with the mu_t schedule
    mu_t = beta1*(1 - 0.5*0.96^(t*psi)); mu_product accumulates mu_1..mu_t
    (reference nadam_kernel semantics)."""
    lr = _val(learning_rate).reshape(())
    g = _val(grad)
    # momentum_decay_pow carries 0.96^(t*psi); beta2_pow carries beta2^t
    mdp = _val(momentum_decay_pow).reshape(()) * (0.96 ** momentum_decay)
    b2p = _val(beta2_pow).reshape(()) * beta2
    _inplace(momentum_decay_pow, mdp)
    _inplace(beta2_pow, b2p)
    mu_t = beta1 * (1.0 - 0.5 * mdp)
    mu_t1 = beta1 * (1.0 - 0.5 * mdp * (0.96 ** momentum_decay))
    mp = _val(mu_product).reshape(()) * mu_t
    _inplace(mu_product, mp)
    m1 = beta1 * _val(moment1) + (1 - beta1) * g
    m2 = beta2 * _val(moment2) + (1 - beta2) * g * g
    _inplace(moment1, m1)
    _inplace(moment2, m2)
    m1_hat = (mu_t1 * m1 / (1 - mp * mu_t1)
              + (1 - mu_t) * g / (1 - mp))
    v_hat = m2 / (1 - b2p)
    return _inplace(param, _val(param)
                    - lr * m1_hat / (jnp.sqrt(v_hat) + epsilon))


def radam_(param, grad, learning_rate, beta1_pow, beta2_pow, rho,
           moment1, moment2, beta1=0.9, beta2=0.999, epsilon=1e-8,
           *a, **kw):
    """RAdam (Liu 2019): variance rectification — SGD-with-momentum while
    rho_t <= 4, rectified Adam after (reference radam_kernel semantics).
    `rho` carries the step counter t."""
    lr = _val(learning_rate).reshape(())
    g = _val(grad)
    b1p = _val(beta1_pow) * beta1
    b2p = _val(beta2_pow) * beta2
    _inplace(beta1_pow, b1p)
    _inplace(beta2_pow, b2p)
    t = _val(rho).reshape(()) + 1
    _inplace(rho, t)
    m1 = beta1 * _val(moment1) + (1 - beta1) * g
    m2 = beta2 * _val(moment2) + (1 - beta2) * g * g
    _inplace(moment1, m1)
    _inplace(moment2, m2)
    rho_inf = 2.0 / (1.0 - beta2) - 1.0
    rho_t = rho_inf - 2.0 * t * b2p / (1.0 - b2p)
    m1_hat = m1 / (1 - b1p)
    rect = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                    / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t,
                                  1e-12))
    adaptive = rect * m1_hat / (jnp.sqrt(m2 / (1 - b2p)) + epsilon)
    plain = m1_hat
    step = jnp.where(rho_t > 4.0, adaptive, plain)
    return _inplace(param, _val(param) - lr * step)


def rprop_(param, grad, prev, learning_rate, master_param=None,
           learning_rate_range=(1e-5, 50.0), etas=(0.5, 1.2), *a, **kw):
    g = _val(grad)
    sign = jnp.sign(g * _val(prev))
    lr = _val(learning_rate)
    lr = jnp.clip(jnp.where(sign > 0, lr * etas[1],
                            jnp.where(sign < 0, lr * etas[0], lr)),
                  learning_rate_range[0], learning_rate_range[1])
    _inplace(learning_rate, lr)
    g_eff = jnp.where(sign < 0, 0.0, g)
    _inplace(prev, g_eff)
    return _inplace(param, _val(param) - lr * jnp.sign(g_eff))


def asgd_(param, grad, learning_rate, d, y, n, master_param=None, *a, **kw):
    lr = _val(learning_rate).reshape(())
    g = _val(grad)
    dv = _val(d) - _val(y) + g
    _inplace(d, dv)
    _inplace(y, g)
    return _inplace(param, _val(param) - lr / jnp.maximum(
        _val(n).reshape(()), 1.0) * dv)


def lamb_(param, grad, learning_rate, moment1, moment2, beta1_pow,
          beta2_pow, master_param=None, weight_decay=0.01, beta1=0.9,
          beta2=0.999, epsilon=1e-6, *a, **kw):
    lr = _val(learning_rate).reshape(())
    g = _val(grad)
    m1 = beta1 * _val(moment1) + (1 - beta1) * g
    m2 = beta2 * _val(moment2) + (1 - beta2) * g * g
    _inplace(moment1, m1)
    _inplace(moment2, m2)
    b1p = _val(beta1_pow) * beta1
    b2p = _val(beta2_pow) * beta2
    _inplace(beta1_pow, b1p)
    _inplace(beta2_pow, b2p)
    r = m1 / (1 - b1p) / (jnp.sqrt(m2 / (1 - b2p)) + epsilon) \
        + weight_decay * _val(param)
    w_norm = jnp.linalg.norm(_val(param))
    r_norm = jnp.linalg.norm(r)
    trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    return _inplace(param, _val(param) - lr * trust * r)


def decayed_adagrad(param, grad, moment, learning_rate, decay=0.95,
                    epsilon=1e-6, *a, **kw):
    lr = _val(learning_rate).reshape(())
    m = decay * _val(moment) + (1 - decay) * _val(grad) ** 2
    _inplace(moment, m)
    return _inplace(param, _val(param)
                    - lr * _val(grad) / (jnp.sqrt(m) + epsilon))


def dpsgd(param, grad, learning_rate, clip=10.0, batch_size=16.0,
          sigma=1.0, seed=0, *a, **kw):
    from .framework import random as rnd

    lr = _val(learning_rate).reshape(())
    g = _val(grad)
    gn = jnp.linalg.norm(g)
    g = g / jnp.maximum(1.0, gn / clip)
    noise = sigma * clip / batch_size * jax.random.normal(
        rnd.next_key(), g.shape, g.dtype)
    return _inplace(param, _val(param) - lr * (g + noise))


def ftrl(param, squared_accumulator, linear_accumulator, grad,
         learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, *a, **kw):
    lr = _val(learning_rate).reshape(())
    g = _val(grad)
    sq = _val(squared_accumulator)
    new_sq = sq + g * g
    sigma = (new_sq ** -lr_power - sq ** -lr_power) / lr
    lin = _val(linear_accumulator) + g - sigma * _val(param)
    _inplace(squared_accumulator, new_sq)
    _inplace(linear_accumulator, lin)
    x = jnp.sign(lin) * l1 - lin
    y = new_sq ** -lr_power / lr + 2 * l2
    return _inplace(param, jnp.where(jnp.abs(lin) > l1, x / y, 0.0))


def average_accumulates_(param, in_sum_1, in_sum_2, in_sum_3,
                         in_num_accumulates, in_old_num_accumulates,
                         in_num_updates, average_window=10000,
                         max_average_window=10000,
                         min_average_window=10000, *a, **kw):
    _inplace(in_sum_1, _val(in_sum_1) + _val(param))
    _inplace(in_num_accumulates,
             _val(in_num_accumulates) + jnp.ones((), jnp.int64))
    return in_sum_1


# --------------------------------------------------------------------------- #
# losses / activations with yaml-only names
# --------------------------------------------------------------------------- #

def bce_loss(input, label):  # noqa: A002
    from .nn import functional as F

    return F.binary_cross_entropy(input, label, reduction="none")


def kldiv_loss(x, label, reduction="mean", log_target=False):
    from .nn import functional as F

    return F.kl_div(x, label, reduction=reduction)


def huber_loss(input, label, delta=1.0):  # noqa: A002
    from .nn import functional as F

    return F.smooth_l1_loss(input, label, reduction="none", delta=delta)


def hinge_loss(logits, labels):
    """max(0, 1 - label*logit) (reference hinge_loss op)."""
    return run_op("hinge_loss",
                  lambda lg, lb: jnp.maximum(0.0, 1.0 - lb * lg),
                  [logits, labels])


def sigmoid_cross_entropy_with_logits(x, label, normalize=False,
                                      ignore_index=-100):
    from .nn import functional as F

    return F.binary_cross_entropy_with_logits(x, label, reduction="none")


def cross_entropy_with_softmax(logits, label, soft_label=False,
                               use_softmax=True, numeric_stable_mode=True,
                               ignore_index=-100, axis=-1):
    from .nn import functional as F

    return F.softmax_with_cross_entropy(logits, label,
                                        soft_label=soft_label, axis=axis)


def warpctc(logits, label, logits_length=None, labels_length=None,
            blank=0, norm_by_times=False):
    from .nn import functional as F

    return F.ctc_loss(logits, label, logits_length, labels_length,
                      blank=blank, reduction="none")


def warprnnt(input, label, input_lengths, label_lengths, blank=0,  # noqa: A002
             fastemit_lambda=0.0):
    """RNN-T loss via the log-sum-exp lattice recursion (reference
    warprnnt op; Graves 2012). input: [B, T, U+1, V] log-probable logits."""
    def fn(lg, lb, il, ul):
        logp = jax.nn.log_softmax(lg, axis=-1)
        B, T, U1, V = logp.shape

        def one(lp, y, t_len, u_len):
            # alpha over the (T, U+1) lattice with lax scans
            blank_lp = lp[:, :, blank]                      # [T, U+1]
            y_lp = jnp.take_along_axis(
                lp[:, :-1, :], y[None, :, None], axis=2)[:, :, 0]  # [T, U]

            def row(alpha_prev, t):
                # alpha[t, u] = logsumexp(alpha[t-1, u] + blank,
                #                         alpha[t, u-1] + y)
                def col(carry, u):
                    a_diag = alpha_prev[u] + blank_lp[t - 1, u]
                    a_left = jnp.where(u > 0, carry + y_lp[t, u - 1],
                                       -jnp.inf)
                    a = jnp.where(t > 0,
                                  jnp.logaddexp(a_diag, a_left),
                                  a_left)
                    a = jnp.where((t == 0) & (u == 0), 0.0, a)
                    return a, a

                _, alpha_t = jax.lax.scan(col, -jnp.inf, jnp.arange(U1))
                return alpha_t, alpha_t

            _, alphas = jax.lax.scan(row, jnp.full((U1,), -jnp.inf),
                                     jnp.arange(T))
            final = alphas[t_len - 1, u_len] + blank_lp[t_len - 1, u_len]
            return -final

        return jax.vmap(one)(logp, lb, il, ul)

    return run_op("warprnnt", fn,
                  [input, label, input_lengths, label_lengths])


def logsigmoid(x):
    from .nn import functional as F

    return F.log_sigmoid(x)


def tanh_shrink(x):
    from .nn import functional as F

    return F.tanhshrink(x)


def identity_loss(x, reduction="none"):
    red = {0: "sum", 1: "mean", 2: "none", "sum": "sum", "mean": "mean",
           "none": "none"}[reduction]
    if red == "sum":
        return _t(x).sum()
    if red == "mean":
        return _t(x).mean()
    return _t(x)


# --------------------------------------------------------------------------- #
# norms / reductions with yaml-only names
# --------------------------------------------------------------------------- #

def frobenius_norm(x, axis=None, keepdim=False):
    return run_op("frobenius_norm",
                  lambda a: jnp.sqrt(jnp.sum(
                      a * a, axis=tuple(axis) if axis else None,
                      keepdims=keepdim)), [x])


def p_norm(x, porder=2.0, axis=-1, epsilon=1e-12, keepdim=False,
           asvector=False):
    def fn(a):
        if asvector:
            a = a.reshape(-1)
        return jnp.linalg.norm(a, ord=porder,
                               axis=None if asvector else axis,
                               keepdims=keepdim and not asvector)

    return run_op("p_norm", fn, [x])


def l1_norm(x):
    return run_op("l1_norm", lambda a: jnp.sum(jnp.abs(a)), [x])


def squared_l2_norm(x):
    return run_op("squared_l2_norm", lambda a: jnp.sum(a * a).reshape(1),
                  [x])


def mean_all(x):
    return _t(x).mean()


def matrix_rank_tol(x, tol_tensor, use_default_tol=True, hermitian=False):
    from .tensor import linalg as L

    return L.matrix_rank(x, tol=tol_tensor, hermitian=hermitian)


def matrix_rank_atol_rtol(x, atol=None, rtol=None, hermitian=False):
    """rank = #{sv > max(atol, rtol * sv_max)} (reference
    matrix_rank_atol_rtol kernel)."""
    a = float(np.asarray(_val(atol)).reshape(())) if atol is not None \
        else 0.0
    r = float(np.asarray(_val(rtol)).reshape(())) if rtol is not None \
        else None

    def fn(xv):
        if hermitian:
            sv = jnp.abs(jnp.linalg.eigvalsh(xv))
        else:
            sv = jnp.linalg.svd(xv, compute_uv=False)
        if r is None:
            eps = jnp.finfo(xv.dtype).eps
            rr = max(xv.shape[-2], xv.shape[-1]) * eps
        else:
            rr = r
        thresh = jnp.maximum(a, rr * sv.max(axis=-1, keepdims=True))
        return (sv > thresh).sum(axis=-1)

    return run_op("matrix_rank_atol_rtol", fn, [x])


# --------------------------------------------------------------------------- #
# interpolation / conv / pooling aliases
# --------------------------------------------------------------------------- #

def _interp(mode):
    def f(x, size=None, scale_factor=None, align_corners=False, **kw):
        from .nn import functional as F

        return F.interpolate(x, size=size, scale_factor=scale_factor,
                             mode=mode, align_corners=align_corners)

    f.__name__ = mode + "_interp"
    return f


bilinear_interp = _interp("bilinear")
bicubic_interp = _interp("bicubic")
trilinear_interp = _interp("trilinear")
nearest_interp = _interp("nearest")
linear_interp = _interp("linear")


def depthwise_conv2d(x, weight, stride=1, padding=0, dilation=1, **kw):
    from .nn import functional as F

    return F.conv2d(x, weight, stride=stride, padding=padding,
                    dilation=dilation, groups=int(x.shape[1]))


def depthwise_conv2d_transpose(x, weight, stride=1, padding=0, **kw):
    from .nn import functional as F

    return F.conv2d_transpose(x, weight, stride=stride, padding=padding,
                              groups=int(x.shape[1]))


def conv2d_transpose_bias(x, weight, bias=None, stride=1, padding=0, **kw):
    from .nn import functional as F

    return F.conv2d_transpose(x, weight, bias=bias, stride=stride,
                              padding=padding)


def pool2d(x, kernel_size, stride=None, padding=0, pooling_type="max",
           **kw):
    from .nn import functional as F

    f = F.max_pool2d if pooling_type == "max" else F.avg_pool2d
    return f(x, kernel_size, stride, padding)


def pool3d(x, kernel_size, stride=None, padding=0, pooling_type="max",
           **kw):
    from .nn import functional as F

    f = F.max_pool3d if pooling_type == "max" else F.avg_pool3d
    return f(x, kernel_size, stride, padding)


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0, **kw):
    from .nn import functional as F

    return F.max_pool2d(x, kernel_size, stride, padding, return_mask=True)


def max_pool3d_with_index(x, kernel_size, stride=None, padding=0, **kw):
    from .nn import functional as F

    return F.max_pool3d(x, kernel_size, stride, padding, return_mask=True)


def unpool(x, indices, kernel_size, stride=None, padding=0,
           output_size=None, **kw):
    from .nn import functional as F

    return F.max_unpool2d(x, indices, kernel_size, stride, padding,
                          output_size=output_size)


def unpool3d(x, indices, kernel_size, stride=None, padding=0,
             output_size=None, **kw):
    from .nn import functional as F

    return F.max_unpool3d(x, indices, kernel_size, stride, padding,
                          output_size=output_size)


def pad3d(x, paddings, mode="constant", value=0.0, data_format="NCDHW"):
    from .nn import functional as F

    return F.pad(x, paddings, mode=mode, value=value,
                 data_format=data_format)


def shuffle_channel(x, group=1):
    from .nn import functional as F

    return F.channel_shuffle(x, group)


def deformable_conv(x, offset, filter, mask=None, strides=1,  # noqa: A002
                    paddings=0, dilations=1, deformable_groups=1,
                    groups=1, im2col_step=64):
    from .vision.ops import deform_conv2d

    return deform_conv2d(x, offset, filter, None, strides, paddings,
                         dilations, deformable_groups, groups, mask)


# --------------------------------------------------------------------------- #
# sequence / recurrent kernel names (the RNN family is nn.layer.rnn)
# --------------------------------------------------------------------------- #

def lstm(x, h0, c0, weight_ih, weight_hh, bias_ih, bias_hh):
    """Single-layer LSTM over [B, T, I] (kernel rnn_kernel.cu.cc)."""
    from . import nn as pnn

    cell = pnn.LSTMCell(int(x.shape[-1]), int(h0.shape[-1]))
    with jax.disable_jit(False):
        cell.weight_ih._value = _val(weight_ih)
        cell.weight_hh._value = _val(weight_hh)
        cell.bias_ih._value = _val(bias_ih)
        cell.bias_hh._value = _val(bias_hh)
    from .nn.layer.rnn import rnn as _rnn

    return _rnn(cell, x, (h0, c0))


def gru(x, h0, weight_ih, weight_hh, bias_ih, bias_hh):
    from . import nn as pnn

    cell = pnn.GRUCell(int(x.shape[-1]), int(h0.shape[-1]))
    cell.weight_ih._value = _val(weight_ih)
    cell.weight_hh._value = _val(weight_hh)
    cell.bias_ih._value = _val(bias_ih)
    cell.bias_hh._value = _val(bias_hh)
    from .nn.layer.rnn import rnn as _rnn

    return _rnn(cell, x, h0)


cudnn_lstm = lstm


def gru_unit(x, h_prev, weight_ih, weight_hh, bias_ih, bias_hh):
    from . import nn as pnn

    cell = pnn.GRUCell(int(x.shape[-1]), int(h_prev.shape[-1]))
    cell.weight_ih._value = _val(weight_ih)
    cell.weight_hh._value = _val(weight_hh)
    cell.bias_ih._value = _val(bias_ih)
    cell.bias_hh._value = _val(bias_hh)
    return cell(x, h_prev)


def attention_lstm(x, h0, c0, attn_w, lstm_w_ih, lstm_w_hh, b_ih, b_hh):
    """Attention-weighted LSTM step sequence (legacy fusion op): softmax
    attention over time then an LSTM pass."""
    from .nn import functional as F

    scores = run_op("attn_scores",
                    lambda a, w: jax.nn.softmax(
                        jnp.einsum("bti,ij->btj", a, w).squeeze(-1),
                        axis=-1),
                    [x, attn_w])
    weighted = run_op("attn_apply",
                      lambda a, s: a * s[..., None], [x, scores])
    return lstm(weighted, h0, c0, lstm_w_ih, lstm_w_hh, b_ih, b_hh)


def sequence_conv(x, weight, context_length=3, context_start=None,
                  padding_data=None):
    """Dense analog of the LoD sequence_conv: 1-D context-window conv.
    x: [B, T, D]; weight: paddle layout [context_length*D, out]."""
    from .nn import functional as F

    D = int(x.shape[-1])
    out_c = int(weight.shape[-1])
    # [ctx*D, out] -> [out, D, ctx] (conv1d weight layout)
    w = _t(weight).reshape([context_length, D, out_c]) \
        .transpose([2, 1, 0])
    y = F.conv1d(_t(x).transpose([0, 2, 1]), w,
                 padding=(context_length - 1) // 2)  # [B, out, T]
    return y.transpose([0, 2, 1])


def sequence_pool(x, pool_type="SUM"):
    red = {"SUM": "sum", "AVERAGE": "mean", "MAX": "max"}[pool_type.upper()]
    return getattr(_t(x), red)(axis=1)


def im2sequence(x, kernels, strides=(1, 1), paddings=(0, 0)):
    from .nn import functional as F

    return F.unfold(x, kernels, strides=list(strides),
                    paddings=list(paddings))


def add_position_encoding(x, alpha=1.0, beta=1.0):
    """x*alpha + beta*sinusoid (legacy add_position_encoding op)."""
    def fn(a):
        B, T, D = a.shape
        pos = jnp.arange(T, dtype=a.dtype)[:, None]
        i = jnp.arange(D // 2, dtype=a.dtype)[None, :]
        ang = pos / jnp.power(10000.0, 2 * i / D)
        pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
        return alpha * a + beta * pe[None]

    return run_op("add_position_encoding", fn, [x])


# --------------------------------------------------------------------------- #
# detection tail
# --------------------------------------------------------------------------- #

def box_clip(input, im_info):  # noqa: A002
    def fn(b, info):
        h, w = info[0], info[1]
        return jnp.stack([jnp.clip(b[..., 0], 0, w - 1),
                          jnp.clip(b[..., 1], 0, h - 1),
                          jnp.clip(b[..., 2], 0, w - 1),
                          jnp.clip(b[..., 3], 0, h - 1)], axis=-1)

    return run_op("box_clip", fn, [input, im_info])


def bipartite_match(dist_mat, match_type="bipartite", dist_threshold=0.5):
    """Greedy bipartite matching (reference bipartite_match op). Host-side
    (sequential argmax elimination)."""
    d = np.asarray(_val(dist_mat)).copy()
    rows, cols = d.shape
    match_idx = np.full(cols, -1, np.int64)
    match_dist = np.zeros(cols, np.float32)
    import builtins
    used_r, used_c = builtins.set(), builtins.set()
    while len(used_r) < rows and len(used_c) < cols:
        flat = np.argmax(d)
        r, c = divmod(int(flat), cols)
        if d[r, c] <= 0:
            break
        match_idx[c] = r
        match_dist[c] = d[r, c]
        d[r, :] = -1
        d[:, c] = -1
        used_r.add(r)
        used_c.add(c)
    if match_type == "per_prediction":
        dd = np.asarray(_val(dist_mat))
        for c in range(cols):
            if match_idx[c] == -1:
                r = int(np.argmax(dd[:, c]))
                if dd[r, c] >= dist_threshold:
                    match_idx[c] = r
                    match_dist[c] = dd[r, c]
    return to_tensor(match_idx.reshape(1, -1)), \
        to_tensor(match_dist.reshape(1, -1))


def multiclass_nms3(bboxes, scores, rois_num=None, score_threshold=0.05,
                    nms_top_k=1000, keep_top_k=100, nms_threshold=0.3,
                    normalized=True, nms_eta=1.0, background_label=-1):
    """Per-class hard NMS + global top-k (reference multiclass_nms3)."""
    from .vision.ops import _nms_np

    bb = np.asarray(_val(bboxes))   # [B, M, 4]
    sc = np.asarray(_val(scores))   # [B, C, M]
    B, C, M = sc.shape
    outs, nums, idxs = [], [], []
    for bi in range(B):
        dets, det_idx = [], []
        for c in range(C):
            if c == background_label:
                continue
            s = sc[bi, c]
            sel = np.nonzero(s > score_threshold)[0]
            if not sel.size:
                continue
            sel = sel[np.argsort(-s[sel])][:nms_top_k]
            keep = _nms_np(bb[bi, sel].astype(np.float64), s[sel],
                           nms_threshold)
            for k in sel[keep]:
                dets.append([c, s[k], *bb[bi, k]])
                det_idx.append(bi * M + k)
        dets = np.asarray(dets, np.float32).reshape(-1, 6)
        det_idx = np.asarray(det_idx, np.int64)
        order = np.argsort(-dets[:, 1])[:keep_top_k] if len(dets) else []
        outs.append(dets[order])
        idxs.append(det_idx[order])
        nums.append(len(order))
    out = np.concatenate(outs) if outs else np.zeros((0, 6), np.float32)
    index = np.concatenate(idxs) if idxs else np.empty(0, np.int64)
    return (to_tensor(out), to_tensor(index),
            to_tensor(np.asarray(nums, np.int32)))


def collect_fpn_proposals(multi_rois, multi_scores, post_nms_top_n=1000,
                          rois_num_per_level=None):
    rois = np.concatenate([np.asarray(_val(r)) for r in multi_rois])
    scores = np.concatenate([np.asarray(_val(s)).reshape(-1)
                             for s in multi_scores])
    order = np.argsort(-scores)[:post_nms_top_n]
    return to_tensor(rois[order]), to_tensor(scores[order])


def yolo_box_head(x, anchors, class_num):
    """Raw head decode without img rescale (yolo_box_head op)."""
    from .vision.ops import yolo_box

    B = int(x.shape[0])
    H = int(x.shape[2])
    img = to_tensor(np.full((B, 2), H * 32, np.int32))
    return yolo_box(x, img, anchors, class_num, 0.0, 32, clip_bbox=False)


def yolo_box_post(boxes, scores, nms_threshold=0.45,
                  score_threshold=0.25, keep_top_k=100):
    from .vision.ops import _nms_np

    b = np.asarray(_val(boxes)).reshape(-1, 4)
    s = np.asarray(_val(scores)).reshape(len(b), -1)
    cls = s.argmax(-1)
    conf = s.max(-1)
    ok = conf > score_threshold
    b, conf, cls = b[ok], conf[ok], cls[ok]
    keep = _nms_np(b.astype(np.float64), conf, nms_threshold)[:keep_top_k]
    out = np.concatenate([cls[keep, None], conf[keep, None], b[keep]], 1)
    return to_tensor(out.astype(np.float32))


def ctc_align(input, input_length=None, blank=0, merge_repeated=True):  # noqa: A002
    """Collapse repeats + drop blanks (ctc_align op). Host-side ragged."""
    a = np.asarray(_val(input))
    outs = []
    for row in a:
        prev = None
        seq = []
        for t in row.tolist():
            if merge_repeated and t == prev:
                prev = t
                continue
            prev = t
            if t != blank:
                seq.append(t)
        outs.append(seq)
    L = max((len(s) for s in outs), default=0)
    out = np.zeros((len(outs), max(L, 1)), a.dtype)
    for i, s in enumerate(outs):
        out[i, :len(s)] = s
    return to_tensor(out)


def crf_decoding(emission, transition, label=None, length=None):
    from .text import ViterbiDecoder

    trans = _t(transition)
    # paddle layout: rows 0/1 are start/stop, remainder the transition matrix
    dec = ViterbiDecoder(trans[2:], include_bos_eos_tag=False)
    if length is None:
        length = to_tensor(np.full((int(emission.shape[0]),),
                                   int(emission.shape[1]), np.int64))
    scores, path = dec(emission, length)
    return path


def chunk_eval(inference, label, chunk_scheme="IOB", num_chunk_types=1,
               excluded_chunk_types=None, seq_length=None):
    """Precision/recall/F1 over IOB chunks (chunk_eval op). Host-side."""
    inf = np.asarray(_val(inference)).reshape(-1)
    lab = np.asarray(_val(label)).reshape(-1)

    outside = num_chunk_types * 2  # the O tag in IOB encoding

    def chunks(seq):
        """IOB spans as (start, end, type): B-<t> = 2t, I-<t> = 2t+1,
        O = num_chunk_types*2."""
        import builtins

        out = builtins.set()
        start = ctype = None
        for i, t in enumerate(seq.tolist()):
            if t >= outside or t < 0:  # O (or padding): close any open chunk
                if start is not None:
                    out.add((start, i, ctype))
                start = ctype = None
            elif t % 2 == 0:  # B- tag: close previous, open new
                if start is not None:
                    out.add((start, i, ctype))
                start, ctype = i, t // 2
            else:  # I- tag: continues only a matching open chunk
                if start is None or ctype != t // 2:
                    if start is not None:
                        out.add((start, i, ctype))
                    start, ctype = i, t // 2  # IOB2-lenient: treat as start
        if start is not None:
            out.add((start, len(seq.tolist()), ctype))
        return out

    ci, cl = chunks(inf), chunks(lab)
    correct = len(ci & cl)
    p = correct / max(len(ci), 1)
    r = correct / max(len(cl), 1)
    f1 = 2 * p * r / max(p + r, 1e-10)
    return (to_tensor(np.float32(p)), to_tensor(np.float32(r)),
            to_tensor(np.float32(f1)),
            to_tensor(np.int64(len(ci))), to_tensor(np.int64(len(cl))),
            to_tensor(np.int64(correct)))


# --------------------------------------------------------------------------- #
# quantization fake-quant family (reference fake_quantize_*.cu; the PTQ/QAT
# passes in paddle_tpu.quantization use these)
# --------------------------------------------------------------------------- #

def _absmax_scale(a, axis=None):
    if axis is None:
        return jnp.max(jnp.abs(a))
    axes = tuple(i for i in range(a.ndim) if i != axis)
    return jnp.max(jnp.abs(a), axis=axes)


def fake_quantize_abs_max(x, bit_length=8):
    def fn(a):
        bound = 2.0 ** (bit_length - 1) - 1
        scale = _absmax_scale(a)
        q = jnp.round(a / jnp.maximum(scale, 1e-8) * bound)
        return q, scale.reshape(1)

    return run_op("fake_quantize_abs_max", fn, [x])


def fake_quantize_dequantize_abs_max(x, bit_length=8):
    def fn(a):
        bound = 2.0 ** (bit_length - 1) - 1
        scale = jnp.maximum(_absmax_scale(a), 1e-8)
        q = jnp.round(a / scale * bound)
        return q / bound * scale, scale.reshape(1)

    return run_op("fake_qdq_abs_max", fn, [x])


def fake_channel_wise_quantize_abs_max(x, bit_length=8, quant_axis=0):
    def fn(a):
        bound = 2.0 ** (bit_length - 1) - 1
        scale = _absmax_scale(a, quant_axis)
        shape = [1] * a.ndim
        shape[quant_axis] = -1
        q = jnp.round(a / jnp.maximum(scale.reshape(shape), 1e-8) * bound)
        return q, scale

    return run_op("fake_cw_q_abs_max", fn, [x])


def fake_channel_wise_quantize_dequantize_abs_max(x, bit_length=8,
                                                  quant_axis=0):
    def fn(a):
        bound = 2.0 ** (bit_length - 1) - 1
        scale = jnp.maximum(_absmax_scale(a, quant_axis), 1e-8)
        shape = [1] * a.ndim
        shape[quant_axis] = -1
        s = scale.reshape(shape)
        q = jnp.round(a / s * bound)
        return q / bound * s, scale

    return run_op("fake_cw_qdq_abs_max", fn, [x])


def fake_channel_wise_dequantize_max_abs(x, scales, quant_bits=(8,),
                                         quant_axis=0, x_num_col_dims=1):
    def fn(a, s):
        bound = 2.0 ** (quant_bits[0] - 1) - 1
        shape = [1] * a.ndim
        shape[quant_axis] = -1
        return a * s.reshape(shape) / bound

    return run_op("fake_cw_dq_max_abs", fn, [x, scales])


def fake_dequantize_max_abs(x, scale, max_range):
    return run_op("fake_dq_max_abs",
                  lambda a, s: a * s.reshape(()) / max_range, [x, scale])


def fake_quantize_moving_average_abs_max(x, in_scale, moving_rate=0.9,
                                         bit_length=8):
    def fn(a, s):
        bound = 2.0 ** (bit_length - 1) - 1
        cur = jnp.max(jnp.abs(a))
        new_s = moving_rate * s.reshape(()) + (1 - moving_rate) * cur
        q = jnp.round(a / jnp.maximum(new_s, 1e-8) * bound)
        return q, new_s.reshape(1)

    return run_op("fake_q_ma_abs_max", fn, [x, in_scale])


def fake_quantize_dequantize_moving_average_abs_max(x, in_scale,
                                                    moving_rate=0.9,
                                                    bit_length=8):
    def fn(a, s):
        bound = 2.0 ** (bit_length - 1) - 1
        cur = jnp.max(jnp.abs(a))
        new_s = jnp.maximum(
            moving_rate * s.reshape(()) + (1 - moving_rate) * cur, 1e-8)
        q = jnp.round(a / new_s * bound)
        return q / bound * new_s, new_s.reshape(1)

    return run_op("fake_qdq_ma_abs_max", fn, [x, in_scale])


def fake_quantize_range_abs_max(x, in_scale, window_size=10000,
                                bit_length=8):
    return fake_quantize_moving_average_abs_max(x, in_scale, 0.9,
                                                bit_length)


def dequantize_abs_max(x, scale, max_range):
    return fake_dequantize_max_abs(x, scale, max_range)


def dequantize_log(x, dict):  # noqa: A002
    """Codes 0..127 decode +2^d[code]; the upper half of the code space
    (int8 negatives / uint8 128..255) decodes -2^d[code&127]."""
    def fn(a, d):
        neg = (a < 0) if jnp.issubdtype(a.dtype, jnp.signedinteger) \
            else (a >= 128)
        idx = jnp.asarray(a).astype(jnp.int32) & 127
        mag = jnp.power(2.0, d[idx])
        return jnp.where(neg, -mag, mag)

    return run_op("dequantize_log", fn, [x, dict])


def apply_per_channel_scale(x, scales):
    return run_op("apply_per_channel_scale",
                  lambda a, s: a * s, [x, scales])


def lookup_table_dequant(w, ids, scale=None):
    def fn(wv, iv):
        return wv[iv.astype(jnp.int32)]

    return run_op("lookup_table_dequant", fn, [w, ids])


def embedding_with_scaled_gradient(x, weight, padding_idx=-1):
    from .nn import functional as F

    return F.embedding(x, weight,
                       padding_idx=None if padding_idx == -1
                       else padding_idx)


# --------------------------------------------------------------------------- #
# AMP internals (the GradScaler uses these semantics; functional forms)
# --------------------------------------------------------------------------- #

def check_finite_and_unscale_(xs, scale):
    """Unscale grads by 1/scale; found_inf=True if any non-finite
    (reference check_finite_and_unscale op)."""
    inv = 1.0 / float(np.asarray(_val(scale)).reshape(()))
    found = False
    for t in xs:
        v = _val(t) * inv
        t._value = v
        if not bool(jnp.all(jnp.isfinite(v))):
            found = True
    return xs, to_tensor(np.asarray([found]))


def update_loss_scaling_(xs, found_inf, prev_loss_scaling, in_good_steps,
                         in_bad_steps, incr_every_n_steps=2000,
                         decr_every_n_nan_or_inf=2, incr_ratio=2.0,
                         decr_ratio=0.5, stop_update=False):
    scale = float(np.asarray(_val(prev_loss_scaling)).reshape(()))
    good = int(np.asarray(_val(in_good_steps)).reshape(()))
    bad = int(np.asarray(_val(in_bad_steps)).reshape(()))
    if bool(np.asarray(_val(found_inf)).reshape(())):
        # reference contract (update_loss_scaling_kernel): on overflow the
        # grads are ZEROED so a subsequent optimizer step is a no-op
        for t in xs:
            t._value = jnp.zeros_like(_val(t))
        bad += 1
        good = 0
        if bad >= decr_every_n_nan_or_inf:
            scale *= decr_ratio
            bad = 0
    else:
        good += 1
        bad = 0
        if good >= incr_every_n_steps:
            scale *= incr_ratio
            good = 0
    prev_loss_scaling._value = jnp.asarray(scale, jnp.float32)
    in_good_steps._value = jnp.asarray(good, jnp.int32)
    in_bad_steps._value = jnp.asarray(bad, jnp.int32)
    return prev_loss_scaling


def check_numerics(x, op_type="", var_name="", stack_height_limit=-1,
                   debug_mode=0):
    from .amp import debugging as dbg

    v = _val(x)
    n_nan = int(jnp.sum(jnp.isnan(v)))
    n_inf = int(jnp.sum(jnp.isinf(v)))
    return to_tensor(np.asarray([n_nan, n_inf], np.int64))


def enable_check_model_nan_inf(flag=True):
    from .amp import debugging as dbg

    dbg.enable_operator_stats_collection() if False else None
    from .framework import flags

    flags.set_flags({"FLAGS_check_nan_inf": bool(flag)})


def disable_check_model_nan_inf():
    enable_check_model_nan_inf(False)


def accuracy_check(x, y, fn_name="allclose", rtol=1e-5, atol=1e-8,
                   equal_nan=False):
    ok = bool(np.allclose(np.asarray(_val(x)), np.asarray(_val(y)),
                          rtol=rtol, atol=atol, equal_nan=equal_nan))
    if not ok:
        raise AssertionError(f"accuracy_check failed ({fn_name})")
    return to_tensor(np.asarray([ok]))


def auc(predict, label, stat_pos, stat_neg, curve="ROC",
        num_thresholds=4095, slide_steps=1):
    from .metric import Auc

    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(np.asarray(_val(predict)), np.asarray(_val(label)))
    return to_tensor(np.float32(m.accumulate()))


# --------------------------------------------------------------------------- #
# MoE routing kernels (incubate.distributed.models.moe uses the compiled
# equivalents; these are the standalone forms)
# --------------------------------------------------------------------------- #

def number_count(numbers, upper_range):
    return run_op("number_count",
                  lambda a: jnp.bincount(
                      jnp.clip(a.reshape(-1).astype(jnp.int32), 0,
                               upper_range - 1), length=upper_range),
                  [numbers])


def assign_pos(x, cum_count, eff_num_len=None):
    """Positions that sort tokens by expert (assign_pos op)."""
    xv = np.asarray(_val(x)).reshape(-1)
    order = np.argsort(xv, kind="stable")
    return to_tensor(order.astype(np.int64))


def limit_by_capacity(expert_count, capacity, n_worker=1):
    return run_op("limit_by_capacity",
                  lambda ec, c: jnp.minimum(ec, c),
                  [expert_count, capacity])


def prune_gate_by_capacity(gate_idx, expert_count, n_expert=None,
                           n_worker=1):
    gi = np.asarray(_val(gate_idx)).reshape(-1).copy()
    ec = np.asarray(_val(expert_count)).reshape(-1).copy()
    seen = np.zeros_like(ec)
    for i, e in enumerate(gi.tolist()):
        if seen[e] >= ec[e]:
            gi[i] = -1
        else:
            seen[e] += 1
    return to_tensor(gi)


def random_routing(topk_idx, topk_value, prob):
    def fn(idx, val, p):
        # tokens whose 2nd-expert prob is too low route to expert -1
        keep = p.reshape(-1) < (2.0 * val[:, 1])
        new1 = jnp.where(keep, idx[:, 1], -1)
        return jnp.stack([idx[:, 0], new1], axis=1)

    return run_op("random_routing", fn, [topk_idx, topk_value, prob])


# --------------------------------------------------------------------------- #
# graph sampling extras
# --------------------------------------------------------------------------- #

def graph_sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                           **kw):
    from .geometric import sample_neighbors

    return sample_neighbors(row, colptr, input_nodes, sample_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       return_eids=False):
    """Multi-hop neighbor sampling (graph_khop_sampler op): the frontier is
    DEDUPLICATED between hops and the result is the union of all hops'
    sampled edges (neighbors + per-source counts concatenated hop-major)."""
    from .geometric import sample_neighbors

    cur = _t(input_nodes)
    all_nb, all_cnt = [], []
    for k in sample_sizes:
        nb, cnt = sample_neighbors(row, colptr, cur, k)
        all_nb.append(np.asarray(_val(nb)))
        all_cnt.append(np.asarray(_val(cnt)))
        cur = to_tensor(np.unique(np.asarray(_val(nb))))
    return (to_tensor(np.concatenate(all_nb) if all_nb
                      else np.empty(0, np.int64)),
            to_tensor(np.concatenate(all_cnt) if all_cnt
                      else np.empty(0, np.int32)))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, return_eids=False):
    r = np.asarray(_val(row)).astype(np.int64)
    cp = np.asarray(_val(colptr)).astype(np.int64)
    w = np.asarray(_val(edge_weight)).astype(np.float64)
    nodes = np.asarray(_val(input_nodes)).astype(np.int64)
    rng = np.random.default_rng()
    out_nb, out_cnt = [], []
    for nd in nodes.tolist():
        beg, end = int(cp[nd]), int(cp[nd + 1])
        neigh = r[beg:end]
        ww = w[beg:end]
        if 0 <= sample_size < len(neigh):
            pp = ww / ww.sum() if ww.sum() > 0 else None
            sel = rng.choice(len(neigh), size=sample_size, replace=False,
                             p=pp)
            neigh = neigh[sel]
        out_nb.append(neigh)
        out_cnt.append(len(neigh))
    return (to_tensor(np.concatenate(out_nb)
                      if out_nb else np.empty(0, np.int64)),
            to_tensor(np.asarray(out_cnt, np.int32)))


def segment_pool(x, segment_ids, pool_type="SUM"):
    from . import geometric as G

    f = {"SUM": G.segment_sum, "MEAN": G.segment_mean,
         "MAX": G.segment_max, "MIN": G.segment_min}[pool_type.upper()]
    return f(x, segment_ids)


# --------------------------------------------------------------------------- #
# fused misc
# --------------------------------------------------------------------------- #

def fused_batch_norm_act(x, scale, bias, mean, variance, momentum=0.9,
                         epsilon=1e-5, act_type="relu"):
    from .nn import functional as F

    out = F.batch_norm(x, mean, variance, scale, bias, training=False,
                       epsilon=epsilon)
    return getattr(F, act_type)(out)


def fused_bn_add_activation(x, z, scale, bias, mean, variance,
                            momentum=0.9, epsilon=1e-5, act_type="relu"):
    from .nn import functional as F

    out = F.batch_norm(x, mean, variance, scale, bias, training=False,
                       epsilon=epsilon) + z
    return getattr(F, act_type)(out)


def fused_softmax_mask(x, mask):
    from .incubate import softmax_mask_fuse

    return softmax_mask_fuse(x, mask)


def fused_softmax_mask_upper_triangle(x):
    from .incubate import softmax_mask_fuse_upper_triangle

    return softmax_mask_fuse_upper_triangle(x)


def flash_attn(q, k, v, dropout=0.0, causal=False, return_softmax=False):
    from .nn import functional as F

    return F.flash_attention(q, k, v, dropout=dropout, causal=causal)


def memory_efficient_attention(q, k, v, bias=None, p=0.0, scale=None,
                               training=True):
    from .nn import functional as F

    return F.scaled_dot_product_attention(q, k, v, attn_mask=bias,
                                          dropout_p=p,
                                          training=training)


def sparse_attention(q, k, v, offset, columns, key_padding_mask=None,
                     attn_mask=None):
    """Block-sparse attention via the dense mask path (the reference's CUDA
    sparse kernel's semantics; TPU flashmask covers the perf case)."""
    from .nn import functional as F

    return F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask)


def calc_reduced_attn_scores(q, k, softmax_lse):
    def fn(qv, kv, lse):
        s = jnp.einsum("bhqd,bhkd->bhqk", qv, kv) / np.sqrt(qv.shape[-1])
        p = jnp.exp(s - lse[..., None])
        return p.sum(axis=2)

    return run_op("calc_reduced_attn_scores", fn, [q, k, softmax_lse])


def correlation(x, y, pad_size=0, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, corr_type_multiply=1):
    """Optical-flow cost volume (correlation op): dot products of x patches
    against displaced y patches."""
    def fn(a, b):
        d = max_displacement
        B, C, H, W = a.shape
        bp = jnp.pad(b, ((0, 0), (0, 0), (d, d), (d, d)))
        outs = []
        for dy in range(-d, d + 1, stride2):
            for dx in range(-d, d + 1, stride2):
                shifted = jax.lax.dynamic_slice(
                    bp, (0, 0, d + dy, d + dx), (B, C, H, W))
                outs.append((a * shifted).mean(axis=1))
        return jnp.stack(outs, axis=1)

    return run_op("correlation", fn, [x, y])


def affine_channel(x, scale, bias, data_format="NCHW"):
    def fn(a, s, b):
        shape = [1, -1] + [1] * (a.ndim - 2) if data_format == "NCHW" \
            else [1] * (a.ndim - 1) + [-1]
        return a * s.reshape(shape) + b.reshape(shape)

    return run_op("affine_channel", fn, [x, scale, bias])


def sync_batch_norm_(x, scale, bias, mean, variance, momentum=0.9,
                     epsilon=1e-5, data_format="NCHW"):
    """In compiled SPMD steps batch stats reduce over the mesh
    automatically (GSPMD); eager per-process form = plain batch_norm."""
    from .nn import functional as F

    return F.batch_norm(x, mean, variance, scale, bias, training=True,
                        momentum=momentum, epsilon=epsilon,
                        data_format=data_format)


# --------------------------------------------------------------------------- #
# FFT kernel names
# --------------------------------------------------------------------------- #

def fft_c2c(x, axes, normalization="backward", forward=True):
    from . import fft as _fft

    f = _fft.fftn if forward else _fft.ifftn
    return f(x, axes=axes, norm=normalization)


def fft_r2c(x, axes, normalization="backward", forward=True,
            onesided=True):
    from . import fft as _fft

    return _fft.rfftn(x, axes=axes, norm=normalization)


def fft_c2r(x, axes, normalization="backward", forward=False,
            last_dim_size=0):
    from . import fft as _fft

    return _fft.irfftn(x, axes=axes, norm=normalization)


# --------------------------------------------------------------------------- #
# creation / view / assignment internals
# --------------------------------------------------------------------------- #

def fill(x, value):
    x._value = jnp.full_like(x._value, value)
    return x


def full_int_array(shape, dtype="int64"):
    from .framework.dtype import convert_dtype

    return to_tensor(np.asarray(shape, convert_dtype(dtype)))


def full_with_tensor(value, shape, dtype=None):
    def fn(v):
        return jnp.full([int(s) for s in np.asarray(_val(shape))],
                        v.reshape(()))

    return run_op("full_with_tensor", fn, [value])


def full_batch_size_like(input, shape, value, input_dim_idx=0,  # noqa: A002
                         output_dim_idx=0, dtype="float32"):
    from .framework.dtype import convert_dtype

    shp = list(shape)
    shp[output_dim_idx] = int(input.shape[input_dim_idx])
    return to_tensor(np.full(shp, value, convert_dtype(dtype)))


def uniform_random_batch_size_like(input, shape, min=-1.0, max=1.0,  # noqa: A002
                                   input_dim_idx=0, output_dim_idx=0,
                                   dtype="float32"):
    from . import tensor as T

    shp = list(shape)
    shp[output_dim_idx] = int(input.shape[input_dim_idx])
    return T.uniform(shp, min=min, max=max, dtype=dtype)


def gaussian_inplace(x, mean=0.0, std=1.0, seed=0):
    from .framework import random as rnd

    x._value = mean + std * jax.random.normal(rnd.next_key(),
                                              x._value.shape,
                                              x._value.dtype)
    return x


def uniform_inplace(x, min=-1.0, max=1.0, seed=0, diag_num=0,  # noqa: A002
                    diag_step=0, diag_val=1.0):
    from .framework import random as rnd

    x._value = jax.random.uniform(rnd.next_key(), x._value.shape,
                                  x._value.dtype, min, max)
    return x


def truncated_gaussian_random(shape, mean=0.0, std=1.0, a=-2.0, b=2.0,
                              dtype="float32", seed=0):
    from .framework import random as rnd
    from .framework.dtype import convert_dtype

    v = jax.random.truncated_normal(
        rnd.next_key(), a, b, tuple(shape),
        jnp.dtype(convert_dtype(dtype))) * std + mean
    return to_tensor(v)


def dirichlet(alpha):
    from .framework import random as rnd

    def fn(a, key):
        return jax.random.dirichlet(key, a)

    from .framework.random import rng_tensor

    return run_op("dirichlet", fn, [alpha, rng_tensor()])


def assign_value_(x, values):
    x._value = jnp.asarray(np.asarray(_val(values)), x._value.dtype) \
        .reshape(x._value.shape)
    return x


def assign_out_(x, out):
    out._value = _val(x)
    return out


def set_value_with_tensor(x, value, starts, ends, steps, axes, **kw):
    def fn(a, v):
        idx = tuple(slice(int(s), int(e), int(st))
                    for s, e, st in zip(starts, ends, steps))
        full = [slice(None)] * a.ndim
        for ax, sl in zip(axes, idx):
            full[ax] = sl
        return a.at[tuple(full)].set(v)

    return run_op("set_value_with_tensor", fn, [x, value])


def set(x, source):  # noqa: A001
    x._value = _val(source)
    return x


def share_data(x):
    return _t(x).detach()


def view_shape(x, shape):
    return _t(x).reshape(list(shape))


def view_dtype(x, dtype):
    from .framework.dtype import convert_dtype

    return run_op("view_dtype",
                  lambda a: a.view(jnp.dtype(convert_dtype(dtype))), [x])


def view_slice(x, begin_idx, end_idx):
    return _t(x)[int(begin_idx):int(end_idx)]


def index_select_strided(x, index, axis=0):
    from . import tensor as T

    return T.index_select(x, to_tensor(np.asarray([index], np.int64)),
                          axis=axis).squeeze(axis)


def repeat_interleave_with_tensor_index(x, repeats, axis=None):
    from . import tensor as T

    return T.repeat_interleave(x, repeats, axis=axis)


def split_with_num(x, num, axis=0):
    from . import tensor as T

    return T.split(x, num, axis=axis)


def shape64(x):
    return to_tensor(np.asarray([int(s) for s in x.shape], np.int64))


def merge_selected_rows(x):
    return _t(x)  # SelectedRows absorbed: grads are dense (see DESIGN_DECISIONS)


def npu_identity(x, format=-1):  # noqa: A002
    return _t(x)


def copy_to(x, place, blocking=True):
    return _t(x).detach()


def beam_search(pre_ids, pre_scores, ids, scores, beam_size=4, end_id=0,
                level=0, is_accumulated=True):
    """One beam-search expansion step (legacy beam_search op): top-k over
    accumulated scores."""
    def fn(ps, sc):
        acc = sc if is_accumulated else ps[..., None] + jnp.log(sc)
        flat = acc.reshape(acc.shape[0], -1)
        top_v, top_i = jax.lax.top_k(flat, beam_size)
        return top_i.astype(jnp.int64), top_v

    return run_op("beam_search", fn, [pre_scores, scores])


# collectives in op form (compiled collectives are the primary surface;
# these eager forms delegate to paddle.distributed)

def _dist():
    from . import distributed as D

    return D


def all_to_all(x, out=None, group=None):
    D = _dist()
    outs = []
    D.alltoall(outs, list(x) if isinstance(x, (list, tuple)) else [x],
               group=group)
    return outs


def c_allreduce_sum(x, ring_id=0, use_calc_stream=True):
    D = _dist()
    D.all_reduce(x)
    return x


def mp_allreduce_sum(x, ring_id=0):
    return c_allreduce_sum(x, ring_id)


def c_identity(x, ring_id=0):
    return _t(x)


def c_concat(x, rank=0, nranks=1, ring_id=0):
    D = _dist()
    parts = []
    D.all_gather(parts, x)
    from . import tensor as T

    return T.concat(parts, axis=-1)


def c_split(x, rank=0, nranks=1, ring_id=0):
    from . import tensor as T

    return T.split(x, nranks, axis=-1)[rank]


def c_scatter(x, src=0, group=None):
    D = _dist()
    out = _t(x)
    D.broadcast(out, src, group=group)
    return out


def partial_allgather(x, nranks=1, rank=0):
    D = _dist()
    parts = []
    D.all_gather(parts, x)
    from . import tensor as T

    return T.concat(parts, axis=0)


def partial_concat(xs, start_index=0, length=-1):
    from . import tensor as T

    parts = []
    for x in xs:
        flat = _t(x).reshape([x.shape[0], -1])
        end = flat.shape[1] if length < 0 else start_index + length
        parts.append(flat[:, start_index:end])
    return T.concat(parts, axis=1)


def partial_sum(xs, start_index=0, length=-1):
    parts = partial_concat(xs, start_index, length)
    n = len(xs)
    per = parts.shape[1] // n
    return sum(parts[:, i * per:(i + 1) * per] for i in range(n))


def global_gather(x, local_count, global_count, ring_id=0):
    from .distributed.utils import moe_utils

    return moe_utils.global_gather(x, local_count, global_count)


def global_scatter(x, local_count, global_count, ring_id=0):
    from .distributed.utils import moe_utils

    return moe_utils.global_scatter(x, local_count, global_count)


def fill_diagonal(x, value=0.0, offset=0, wrap=False):
    from .tensor.tail import fill_diagonal_

    return fill_diagonal_(_t(x), value, offset, wrap)


def trans_layout(x, perm):
    return _t(x).transpose(list(perm))


def coalesce_tensor(input, dtype=None, copy_data=True, **kw):  # noqa: A002
    """Pack a list of tensors into one contiguous buffer (reference
    coalesce_tensor op — the flat-param trick cudnn RNN / DDP buckets use).
    Returns (tensors_viewing_the_buffer, fused_buffer)."""
    from .nn.utils import parameters_to_vector

    fused = parameters_to_vector(list(input))
    return list(input), fused


def depend(x, dep):
    """Scheduling edge: value passthrough (reference depend op). XLA's
    dataflow ordering makes the explicit edge a no-op here."""
    return _t(x)


def memcpy_d2h(x, dst_place_type=0):
    return to_tensor(np.asarray(_val(x)))


def memcpy_h2d(x, dst_place_type=1):
    import jax as _jax

    return to_tensor(_jax.device_put(_val(x)))


def sync_calc_stream(x):
    v = _val(x)
    if hasattr(v, "block_until_ready"):
        v.block_until_ready()
    return _t(x)


__all__ = sorted(
    n for n, v in list(globals().items())
    if not n.startswith("_") and callable(v)
    and getattr(v, "__module__", None) == __name__)
