"""ONNX export surface (reference: python/paddle/onnx/export.py — export()
delegating to paddle2onnx).

TPU formulation: the portable serialized graph on this stack is StableHLO
(the jit.save artifact), which is what XLA-family runtimes consume — it
plays the role ONNX plays in the reference's deployment story. export()
therefore emits the StableHLO bundle at `path`; when the `onnx` package is
installed (not in this image) a real ONNX conversion could be layered on
top, so its absence raises only if `format='onnx'` is forced."""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, format=None,
           **configs):
    """reference: paddle.onnx.export (export.py). Saves the traced program
    as a StableHLO bundle via jit.save; `format='onnx'` requires the onnx
    package."""
    if format == "onnx":
        try:
            import onnx  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "the `onnx` package is not available in this environment; "
                "export() emits a StableHLO bundle instead (omit "
                "format='onnx')") from e
        raise NotImplementedError(
            "direct ONNX serialization is not implemented; use the "
            "StableHLO bundle (default format) with an XLA-family runtime")
    from ..jit import save as jit_save

    jit_save(layer, path, input_spec=input_spec)
    return path
