"""Signal processing (reference: python/paddle/signal.py — stft :123,
istft :327; kernels frame/overlap_add in paddle/phi/kernels/).

TPU formulation: framing is a strided gather, the transform is XLA's FftOp,
and istft's overlap-add is a scatter-add — all differentiable run_ops."""

from __future__ import annotations

import jax.numpy as jnp

from .framework.core import Tensor, run_op, to_tensor

__all__ = ["stft", "istft", "frame", "overlap_add"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """reference signal.py frame — [..., T] -> [..., frame_length, n_frames]
    (frame dim before frames, matching the reference layout)."""
    t = _t(x)
    if axis not in (-1, t.ndim - 1):
        raise NotImplementedError("frame: last-axis only")
    if t.shape[-1] < frame_length:
        raise ValueError(
            f"frame: input length {t.shape[-1]} < frame_length "
            f"{frame_length}")

    def fn(v):
        n = (v.shape[-1] - frame_length) // hop_length + 1
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(frame_length)[None, :])  # [n, frame_length]
        return jnp.swapaxes(v[..., idx], -1, -2)

    return run_op("frame", fn, [t])


def overlap_add(x, hop_length, axis=-1, name=None):
    """reference signal.py overlap_add — [..., frame_length, n_frames] ->
    [..., T]."""
    t = _t(x)
    if axis not in (-1, t.ndim - 1):
        raise NotImplementedError("overlap_add: last-axis only")

    def fn(v):
        fl, n = v.shape[-2], v.shape[-1]
        T = (n - 1) * hop_length + fl
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(fl)[None, :])           # [n, fl]
        frames = jnp.swapaxes(v, -1, -2)            # [..., n, fl]
        lead = frames.shape[:-2]
        out = jnp.zeros(lead + (T,), v.dtype)
        return out.at[..., idx].add(frames)

    return run_op("overlap_add", fn, [t])


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """reference signal.py:123 — returns [..., n_fft//2+1 | n_fft, frames]
    complex."""
    t = _t(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    T_in = t.shape[-1]
    if not center and T_in < n_fft:
        raise ValueError(
            f"stft: input length {T_in} < n_fft {n_fft} with center=False — "
            f"would produce zero frames")
    if center and pad_mode in ("reflect", "symmetric") and T_in <= n_fft // 2:
        raise ValueError(
            f"stft: input length {T_in} too short to {pad_mode}-pad by "
            f"n_fft//2 = {n_fft // 2} (center=True)")
    has_win = window is not None
    ins = [t] + ([_t(window)] if has_win else [])

    def fn(v, *rest):
        w = rest[0] if has_win else jnp.ones(win_length, v.dtype)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            w = jnp.pad(w, (lp, n_fft - win_length - lp))
        if center:
            pad = [(0, 0)] * (v.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            v = jnp.pad(v, pad, mode=pad_mode)
        n = (v.shape[-1] - n_fft) // hop_length + 1
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :])
        frames = v[..., idx] * w                      # [..., n, n_fft]
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        return jnp.swapaxes(spec, -1, -2)             # [..., freq, frames]

    return run_op("stft", fn, ins)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """reference signal.py:327 — inverse with window-square overlap-add
    normalization."""
    t = _t(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    has_win = window is not None
    ins = [t] + ([_t(window)] if has_win else [])

    if return_complex and onesided:
        raise ValueError("return_complex=True requires onesided=False")

    def fn(v, *rest):
        w = rest[0] if has_win else jnp.ones(win_length, jnp.float32)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            w = jnp.pad(w, (lp, n_fft - win_length - lp))
        spec = jnp.swapaxes(v, -1, -2)               # [..., frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * w                           # [..., n, n_fft]
        n = frames.shape[-2]
        T = (n - 1) * hop_length + n_fft
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :])
        lead = frames.shape[:-2]
        out = jnp.zeros(lead + (T,), frames.dtype).at[..., idx].add(frames)
        # window-square normalization (COLA)
        wsq = jnp.zeros(T, frames.dtype).at[idx.reshape(-1)].add(
            jnp.tile(w * w, n))
        out = out / jnp.maximum(wsq, 1e-10)
        if center:
            out = out[..., n_fft // 2:T - n_fft // 2]
        if length is not None:
            if out.shape[-1] < length:  # trailing partial frame was dropped
                out = jnp.pad(out, [(0, 0)] * (out.ndim - 1)
                              + [(0, length - out.shape[-1])])
            out = out[..., :length]
        return out

    return run_op("istft", fn, ins)
