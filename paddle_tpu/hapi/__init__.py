from . import callbacks
from .callbacks import Callback, EarlyStopping, ModelCheckpoint, ProgBarLogger
from .model import Model, summary

__all__ = ["Model", "summary", "callbacks", "Callback", "EarlyStopping", "ModelCheckpoint", "ProgBarLogger"]
