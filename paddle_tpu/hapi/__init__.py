from . import callbacks
from .callbacks import (Callback, EarlyStopping, ModelCheckpoint,
                        ProgBarLogger, ReduceLROnPlateau, VisualDL)
from .dynamic_flops import flops
from .model import Model, summary

__all__ = ["Model", "summary", "flops", "callbacks", "Callback", "EarlyStopping", "ModelCheckpoint", "ProgBarLogger", "ReduceLROnPlateau", "VisualDL"]
