"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping", "LRScheduler", "CallbackList"]


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks)
        for c in self.callbacks:
            if model is not None:
                c.set_model(model)
            c.set_params(params or {})

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, (int, float, np.floating)) else f"{k}: {v}"
                for k, v in (logs or {}).items()
            )
            total = f"/{self.steps}" if self.steps else ""
            print(f"Epoch {self.epoch}: step {step}{total} - {items}", flush=True)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, (int, float, np.floating)) else f"{k}: {v}"
                for k, v in (logs or {}).items()
            )
            print(f"Epoch {epoch} done in {dt:.1f}s - {items}", flush=True)

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = " - ".join(
                f"{k}: {v}" for k, v in (logs or {}).items()
            )
            print(f"Eval - {items}", flush=True)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"{epoch}")
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        improved = (
            self.best is None
            or (self.mode == "min" and cur < self.best - self.min_delta)
            or (self.mode == "max" and cur > self.best + self.min_delta)
        )
        if improved:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_lr", None) if opt else None
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()
