"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import json
import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping", "LRScheduler", "CallbackList"]


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks)
        for c in self.callbacks:
            if model is not None:
                c.set_model(model)
            c.set_params(params or {})

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose
        # registry cursor for throughput: (count, sum) of the step-time
        # histogram at the last log line
        self._tp_cursor = (0, 0.0)

    def on_train_begin(self, logs=None):
        # seed from the CURRENT registry state: the histogram is process-
        # wide, so a second fit() in the same process must not fold the
        # first fit's steps into its opening ips line
        from ..observability.metrics import default_registry

        hist = default_registry().get("hapi_train_step_seconds")
        self._tp_cursor = (hist.count(), hist.sum()) if hist is not None \
            else (0, 0.0)

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()

    def _throughput(self):
        """steps/s since the last log line, read from the metrics registry
        (hapi_train_step_seconds, written by Model.fit) — the same series
        telemetry exports, so the progress bar and the step-timeline JSONL
        cannot disagree. Returns None before fit has recorded a step."""
        from ..observability.metrics import default_registry

        hist = default_registry().get("hapi_train_step_seconds")
        if hist is None:
            return None
        count, total = hist.count(), hist.sum()
        c0, s0 = self._tp_cursor
        self._tp_cursor = (count, total)
        dc, ds = count - c0, total - s0
        if dc <= 0 or ds <= 0:
            return None
        return dc / ds

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, (int, float, np.floating)) else f"{k}: {v}"
                for k, v in (logs or {}).items()
            )
            ips = self._throughput()
            if ips is not None:
                items += f" - ips: {ips:.3f} steps/s"
            total = f"/{self.steps}" if self.steps else ""
            print(f"Epoch {self.epoch}: step {step}{total} - {items}", flush=True)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, (int, float, np.floating)) else f"{k}: {v}"
                for k, v in (logs or {}).items()
            )
            print(f"Epoch {epoch} done in {dt:.1f}s - {items}", flush=True)

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = " - ".join(
                f"{k}: {v}" for k, v in (logs or {}).items()
            )
            print(f"Eval - {items}", flush=True)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"{epoch}")
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        improved = (
            self.best is None
            or (self.mode == "min" and cur < self.best - self.min_delta)
            or (self.mode == "max" and cur > self.best + self.min_delta)
        )
        if improved:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_lr", None) if opt else None
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class ReduceLROnPlateau(Callback):
    """Reduce the optimizer lr when the monitored metric plateaus
    (reference: hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0
        self.epoch = 0
        self._last_epoch_stepped = None
        self._pending = None

    def _improved(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def _step(self, logs):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        # fit() can surface the monitored key in BOTH the epoch logs and the
        # eval logs — only ONE observation per epoch may advance the plateau
        # counter, or patience halves and the factor applies twice
        if self._last_epoch_stepped == self.epoch:
            return
        self._last_epoch_stepped = self.epoch
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._improved(cur):
            self.best = cur
            self.wait = 0
            return
        if self.cooldown_counter > 0:
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is None:
                return
            from ..optimizer.lr import LRScheduler as Sched

            if isinstance(opt._lr, Sched):
                # scale the schedule's BASE lr — writing last_lr*factor into
                # base_lr would re-apply the schedule multiplier on top of
                # the already-scaled value
                old = float(opt._lr.base_lr)
                new = max(old * self.factor, self.min_lr)
                opt._lr.base_lr = new
            else:
                old = float(opt.get_lr())
                new = max(old * self.factor, self.min_lr)
                opt.set_lr(new)
            if self.verbose:
                print(f"Epoch {self.epoch}: ReduceLROnPlateau reducing "
                      f"learning rate from {old} to {new}.")
            self.cooldown_counter = self.cooldown
            self.wait = 0

    def on_epoch_begin(self, epoch, logs=None):
        # no eval followed the previous epoch: its train observation counts
        self._flush_pending()

    def on_epoch_end(self, epoch, logs=None):
        # DEFER the train-log observation: fit() fires on_eval_end after
        # on_epoch_end, and eval metrics must win over same-named train
        # metrics (reference semantics reduce on the eval metric)
        self.epoch = epoch
        self._pending = (epoch, dict(logs or {}))

    def on_eval_end(self, logs=None):
        self._pending = None
        self._step(logs)

    def on_train_end(self, logs=None):
        self._flush_pending()

    def _flush_pending(self):
        if self._pending is None:
            return
        epoch, logs = self._pending
        self._pending = None
        self.epoch = epoch
        self._step(logs)


class VisualDL(Callback):
    """Scalar logging callback (reference: hapi/callbacks.py VisualDL).

    The VisualDL package is not available on this stack; scalars are
    appended as JSON lines under log_dir (one file per phase) — readable by
    any dashboard and by tests. If the visualdl package IS importable, its
    LogWriter is used instead.
    """

    def __init__(self, log_dir):
        self.log_dir = log_dir
        self.epochs = None
        self.steps = None
        self.epoch = 0
        self._writer = None
        self._jsonl = None
        try:  # pragma: no cover - visualdl not in this image
            from visualdl import LogWriter

            self._writer = LogWriter(log_dir)
        except Exception:
            os.makedirs(log_dir, exist_ok=True)

    def _record(self, phase, logs, step):
        logs = logs or {}
        lines = []
        for k, v in logs.items():
            if isinstance(v, (list, tuple)):
                v = v[0] if v else None
            if not isinstance(v, (int, float, np.floating, np.integer)):
                # the fit loop hands out the 0-d device loss between log
                # points (no free per-step sync); a recorder callback is an
                # explicit opt-in to per-step values, so it pays the read
                if np.ndim(v) == 0 and hasattr(v, "__float__"):
                    v = float(v)
                else:
                    continue
            if self._writer is not None:  # pragma: no cover
                self._writer.add_scalar(f"{phase}/{k}", float(v), step)
            else:
                lines.append(json.dumps({"tag": k, "step": int(step),
                                         "value": float(v)}))
        if lines:
            path = os.path.join(self.log_dir, f"{phase}.jsonl")
            with open(path, "a") as f:
                f.write("\n".join(lines) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._record("train", logs, step)

    def on_epoch_end(self, epoch, logs=None):
        self.epoch = epoch
        self._record("train_epoch", logs, epoch)

    def on_eval_end(self, logs=None):
        self._record("eval", logs, self.epoch)

    def on_train_end(self, logs=None):
        if self._writer is not None:  # pragma: no cover
            self._writer.close()


__all__ += ["ReduceLROnPlateau", "VisualDL"]
