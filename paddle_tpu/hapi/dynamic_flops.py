"""Dynamic FLOP counter (reference: python/paddle/hapi/dynamic_flops.py —
paddle.flops(net, input_size) walking leaf layers with per-type counting
hooks)."""

from __future__ import annotations

import numpy as np

import paddle_tpu.nn as nn

__all__ = ["flops"]


def _count_linear(layer, x, y):
    in_f = int(np.prod(x.shape)) // x.shape[-1] if x.ndim else 1
    return 2 * in_f * layer.weight.shape[0] * layer.weight.shape[1]


def _count_conv(layer, x, y):
    # 2 * out_elems * (Cin/groups * kh * kw)
    out_elems = int(np.prod(y.shape))
    w = layer.weight
    per_out = 2 * int(np.prod(w.shape[1:]))
    return out_elems * per_out


def _count_norm(layer, x, y):
    return 2 * int(np.prod(x.shape))


def _count_act(layer, x, y):
    return int(np.prod(y.shape))


def _count_pool(layer, x, y):
    return int(np.prod(y.shape))


def _count_embedding(layer, x, y):
    return 0


_COUNTERS = []


def _build_counters():
    if _COUNTERS:
        return _COUNTERS
    table = [
        ((nn.Linear,), _count_linear),
        ((nn.Conv1D, nn.Conv2D, nn.Conv3D) if hasattr(nn, "Conv1D")
         else (nn.Conv2D,), _count_conv),
        ((nn.LayerNorm, nn.BatchNorm2D, nn.BatchNorm, nn.GroupNorm),
         _count_norm),
        ((nn.ReLU, nn.GELU, nn.Silu, nn.Sigmoid, nn.Tanh, nn.Hardswish,
          nn.ReLU6), _count_act),
        ((nn.MaxPool2D, nn.AvgPool2D, nn.AdaptiveAvgPool2D)
         if hasattr(nn, "MaxPool2D") else (), _count_pool),
        ((nn.Embedding,), _count_embedding),
    ]
    for types, fn in table:
        if types:
            _COUNTERS.append((types, fn))
    return _COUNTERS


def flops(net, input_size, custom_ops=None, print_detail=False):
    """reference: paddle.flops (hapi/dynamic_flops.py flops). Runs a zeros
    forward with counting hooks on leaf layers; returns total FLOPs."""
    import paddle_tpu as paddle

    counters = _build_counters()
    custom_ops = custom_ops or {}
    records = []
    handles = []

    def make_hook(layer, count_fn):
        def hook(lyr, inputs, output):
            try:
                x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
                n = int(count_fn(lyr, x, output))
            except Exception:
                n = 0
            records.append((type(lyr).__name__, n))
            return output

        return hook

    import warnings

    seen = set()
    uncovered = set()
    for _, sub in net.named_sublayers():
        if sub._sub_layers:  # leaves only (O(1) check)
            continue
        if id(sub) in seen:  # shared layer: one hook, no double count
            continue
        seen.add(id(sub))
        count_fn = custom_ops.get(type(sub))
        if count_fn is None:
            for types, fn in counters:
                if isinstance(sub, types):
                    count_fn = fn
                    break
        if count_fn is not None:
            handles.append(sub.register_forward_post_hook(
                make_hook(sub, count_fn)))
        else:
            uncovered.add(type(sub).__name__)
    if uncovered:
        warnings.warn(
            f"paddle.flops: no count function for layer type(s) "
            f"{sorted(uncovered)} — totals exclude them")

    # restore per-sublayer modes: a blanket net.train() would un-freeze
    # sublayers deliberately held in eval
    modes = [(net, net.training)] + [(s_, s_.training)
                                     for _, s_ in net.named_sublayers()]
    net.eval()
    try:
        x = paddle.zeros(list(input_size), dtype="float32")
        net(x)
    finally:
        for h in handles:
            try:
                h.remove()
            except Exception:
                pass
        for lyr, mode in modes:
            lyr.training = mode

    total = sum(n for _, n in records)
    if print_detail:
        for name, n in records:
            print(f"  {name:<24} {n:,}")
    print(f"Total Flops: {total:,}     Total Params: "
          f"{sum(int(np.prod(p.shape)) for _, p in net.named_parameters()):,}")
    return total
