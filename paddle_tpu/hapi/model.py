"""paddle.Model high-level API (reference: python/paddle/hapi/model.py:1472 —
fit :1472ff, evaluate :2200, predict, save/load, summary).

The training engine is jit-first: fit() drives a TrainStep (one compiled XLA
program per step) instead of the reference's per-op dygraph loop.
"""

from __future__ import annotations

import os

import numpy as np

from ..framework.core import Tensor, no_grad, to_tensor
from ..io import DataLoader, Dataset
from ..jit import TrainStep, functional_call
from ..metric import Metric
from .callbacks import Callback, CallbackList, LRScheduler, ProgBarLogger

__all__ = ["Model", "summary"]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self.stop_training = False

    # ------------------------------------------------------------------ #

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        amp_level = None
        if amp_configs:
            if isinstance(amp_configs, str):
                amp_level = amp_configs
            else:
                amp_level = amp_configs.get("level", "O1")
        self._amp_level = amp_level

    def _get_train_step(self):
        if self._train_step is None:
            self._train_step = TrainStep(
                self.network, self._loss, self._optimizer,
                amp_level=getattr(self, "_amp_level", None),
            )
        return self._train_step

    # ------------------------------------------------------------------ #

    def train_batch(self, inputs, labels=None, update=True):
        step = self._get_train_step()
        loss = step(inputs, labels)
        return [float(loss)]

    def _train_batch_async(self, inputs, labels=None):
        """One train step returning the DEVICE-side loss Tensor — no host
        sync. The public train_batch() float()s the loss, which blocks the
        host on every step and stalls XLA's async dispatch pipeline; the fit
        loop uses this variant and only syncs at log boundaries (the GL001
        hot-path audit — see docs/LINTING.md)."""
        if type(self).train_batch is not Model.train_batch:
            # subclass customized the step (the reference paddle.Model
            # extension point): honor it — correctness over async dispatch
            return self.train_batch(inputs, labels)[0]
        return self._get_train_step()(inputs, labels)

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        step = self._get_train_step()
        loss = step.evaluate(inputs, labels)
        return [float(loss)]

    @no_grad()
    def predict_batch(self, inputs):
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self.network.eval()
        if self._train_step is not None:
            self._train_step.sync_weights()
        outs = self.network(*[i if isinstance(i, Tensor) else to_tensor(np.asarray(i)) for i in inputs])
        self.network.train()
        return [o.numpy() for o in (outs if isinstance(outs, (list, tuple)) else [outs])]

    # ------------------------------------------------------------------ #

    def _to_loader(self, data, batch_size, shuffle, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle, num_workers=num_workers)
        return data  # assume iterable of batches

    @staticmethod
    def _split_batch(batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return list(batch[:-1]), [batch[-1]]
            return [batch[0]], []
        return [batch], []

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        import time

        from ..observability import metrics as _obs_metrics
        from ..observability import spans as _obs_spans

        loader = self._to_loader(train_data, batch_size, shuffle, num_workers)
        eval_loader = self._to_loader(eval_data, batch_size, False, num_workers)
        cbs = [ProgBarLogger(log_freq, verbose), LRScheduler()]
        if callbacks:
            cbs += list(callbacks)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cblist = CallbackList(cbs, model=self, params={"epochs": epochs, "steps": steps, "verbose": verbose})
        self.stop_training = False
        # the registry is the single source for fit's throughput numbers:
        # ProgBarLogger derives its ips from hapi_train_step_seconds, so the
        # progress line and telemetry exports can never disagree
        reg = _obs_metrics.default_registry()
        m_step_time = reg.histogram(
            "hapi_train_step_seconds", "wall time per Model.fit train step")
        m_steps = reg.counter("hapi_train_steps_total",
                              "train steps run by Model.fit")
        m_loss_sync = reg.counter(
            "hapi_loss_sync_total",
            "host syncs of the loss scalar inside the fit loop (log "
            "boundaries + epoch means; anything above log cadence is a "
            "callback paying for per-step values)")
        cblist.on_train_begin()
        it = 0
        for epoch in range(epochs):
            cblist.on_epoch_begin(epoch)
            self.network.train()
            loss_sum, n_steps = None, 0
            for step_i, batch in enumerate(loader):
                cblist.on_train_batch_begin(step_i)
                inputs, labels = self._split_batch(batch)
                tl = _obs_spans.active_timeline()
                if tl is not None:
                    tl.step_begin(it)
                t0 = time.perf_counter()
                with _obs_spans.span("fit/train_batch"):
                    loss_t = self._train_batch_async(inputs, labels)
                # device-side running mean: O(1) live buffers and a single
                # host sync per epoch instead of one blocking float() per
                # step (which serialized XLA's async dispatch pipeline)
                loss_sum = loss_t if loss_sum is None else loss_sum + loss_t
                n_steps += 1
                # sync the scalar only when ProgBarLogger will print it;
                # between log points callbacks get the 0-d device Tensor —
                # float()-able / formattable on demand, so a callback that
                # *wants* per-step values pays the per-step sync itself
                will_sync = step_i % log_freq == 0
                if will_sync:
                    loss_v = float(loss_t)
                    m_loss_sync.inc()
                else:
                    loss_v = loss_t
                dt = time.perf_counter() - t0
                m_step_time.observe(dt)
                m_steps.inc()
                if tl is not None:
                    tl.step_end(extra={"epoch": epoch,
                                       "loss_synced": will_sync})
                cblist.on_train_batch_end(step_i, {"loss": loss_v})
                it += 1
                if num_iters is not None and it >= num_iters:
                    break
            if n_steps:
                m_loss_sync.inc()  # the single per-epoch mean sync
            logs = {"loss": float(loss_sum) / n_steps if n_steps else 0.0}
            cblist.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, batch_size=batch_size, verbose=0, num_workers=num_workers)
                cblist.on_eval_end(eval_logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
            if self.stop_training or (num_iters is not None and it >= num_iters):
                break
        cblist.on_train_end(logs if "logs" in dir() else None)
        return self

    @no_grad()
    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._to_loader(eval_data, batch_size, False, num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        self.network.eval()
        if self._train_step is not None:
            self._train_step.sync_weights()
        for batch in loader:
            inputs, labels = self._split_batch(batch)
            outs = self.network(*[i if isinstance(i, Tensor) else to_tensor(np.asarray(i)) for i in inputs])
            outs_l = outs if isinstance(outs, (list, tuple)) else [outs]
            if self._loss is not None and labels:
                loss = self._loss(*outs_l, *labels)
                losses.append(float(loss))
            for m in self._metrics:
                res = m.compute(*outs_l, *labels)
                if isinstance(res, tuple):
                    m.update(*res)
                else:
                    m.update(res)
        self.network.train()
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            name = m.name()
            acc = m.accumulate()
            if isinstance(name, list):
                for n, a in zip(name, acc):
                    logs[n] = a
            else:
                logs[name] = acc
        return logs

    @no_grad()
    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._to_loader(test_data, batch_size, False, num_workers)
        outputs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch)
            outs = self.predict_batch(inputs)
            outputs.append(outs)
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    # ------------------------------------------------------------------ #

    def save(self, path, training=True):
        from ..framework.io import save as fsave

        if self._train_step is not None:
            self._train_step.sync_weights()
            self._train_step.sync_optimizer()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload

        state = fload(path + ".pdparams")
        self.network.set_state_dict(state)
        if self._train_step is not None:
            # refresh device-side copies
            self._train_step = None
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            self._optimizer.set_state_dict(fload(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtype)


def summary(net, input_size=None, dtypes=None):
    """Parameter-count summary (reference: python/paddle/hapi/summary.py)."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max((len(r[0]) for r in rows), default=20) + 2
    lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Param #':<12}"]
    lines += [f"{name:<{width}}{str(shape):<20}{n:<12}" for name, shape, n in rows]
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
