"""FFT module (reference: python/paddle/fft.py — fft/ifft/rfft/... built on
phi's cuFFT/onemkl kernels, paddle/phi/kernels/gpu/fft_kernel.cu).

TPU formulation: XLA owns the FFT lowering (HLO FftOp); every function is a
thin differentiable run_op over jnp.fft, so fft ops fuse into surrounding
jitted programs and work inside to_static/TrainStep like any other op."""

from __future__ import annotations

import jax.numpy as jnp

from .framework.core import Tensor, run_op, to_tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _norm(norm):
    # paddle uses "backward"/"ortho"/"forward" like numpy
    return norm or "backward"


def _mk1d(jfn, opname):
    def f(x, n=None, axis=-1, norm="backward", name=None):
        return run_op(opname, lambda v: jfn(v, n=n, axis=axis,
                                            norm=_norm(norm)), [_t(x)])

    f.__name__ = opname
    f.__doc__ = f"reference: python/paddle/fft.py {opname}. XLA FFT lowering."
    return f


def _mk2d(jfn, opname):
    def f(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return run_op(opname, lambda v: jfn(v, s=s, axes=axes,
                                            norm=_norm(norm)), [_t(x)])

    f.__name__ = opname
    f.__doc__ = f"reference: python/paddle/fft.py {opname}. XLA FFT lowering."
    return f


def _mkn(jfn, opname):
    def f(x, s=None, axes=None, norm="backward", name=None):
        return run_op(opname, lambda v: jfn(v, s=s, axes=axes,
                                            norm=_norm(norm)), [_t(x)])

    f.__name__ = opname
    f.__doc__ = f"reference: python/paddle/fft.py {opname}. XLA FFT lowering."
    return f


fft = _mk1d(jnp.fft.fft, "fft")
ifft = _mk1d(jnp.fft.ifft, "ifft")
rfft = _mk1d(jnp.fft.rfft, "rfft")
irfft = _mk1d(jnp.fft.irfft, "irfft")
hfft = _mk1d(jnp.fft.hfft, "hfft")
ihfft = _mk1d(jnp.fft.ihfft, "ihfft")

fft2 = _mk2d(jnp.fft.fft2, "fft2")
ifft2 = _mk2d(jnp.fft.ifft2, "ifft2")
rfft2 = _mk2d(jnp.fft.rfft2, "rfft2")
irfft2 = _mk2d(jnp.fft.irfft2, "irfft2")

fftn = _mkn(jnp.fft.fftn, "fftn")
ifftn = _mkn(jnp.fft.ifftn, "ifftn")
rfftn = _mkn(jnp.fft.rfftn, "rfftn")
irfftn = _mkn(jnp.fft.irfftn, "irfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    """reference: paddle.fft.fftfreq."""
    out = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        from .framework.dtype import convert_dtype

        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    """reference: paddle.fft.rfftfreq."""
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        from .framework.dtype import convert_dtype

        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    """reference: paddle.fft.fftshift."""
    return run_op("fftshift", lambda v: jnp.fft.fftshift(v, axes=axes), [_t(x)])


def ifftshift(x, axes=None, name=None):
    """reference: paddle.fft.ifftshift."""
    return run_op("ifftshift", lambda v: jnp.fft.ifftshift(v, axes=axes), [_t(x)])
