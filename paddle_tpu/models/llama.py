"""LLaMA family = the GPT decoder with RMSNorm + SwiGLU + RoPE + GQA + untied
embeddings (BASELINE.md sharding-stage-2/3 + flash_attn configs)."""

from __future__ import annotations

from .gpt import GPTConfig, GPTModel, GPTForCausalLM

__all__ = [
    "LlamaConfig", "LlamaModel", "LlamaForCausalLM",
    "llama_tiny", "llama_7b", "llama_13b",
]


def LlamaConfig(**kw):
    base = dict(
        vocab_size=32000,
        norm_type="rmsnorm",
        activation="swiglu",
        use_rope=True,
        tie_word_embeddings=False,
        layer_norm_epsilon=1e-6,
        max_position_embeddings=4096,
    )
    base.update(kw)
    return GPTConfig(**base)


LlamaModel = GPTModel
LlamaForCausalLM = GPTForCausalLM


def llama_tiny(**kw):
    return LlamaConfig(vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
                       num_kv_heads=2, max_position_embeddings=128, **kw)


def llama_7b(**kw):
    return LlamaConfig(hidden_size=4096, num_layers=32, num_heads=32,
                       intermediate_size=11008, **kw)


def llama_13b(**kw):
    return LlamaConfig(hidden_size=5120, num_layers=40, num_heads=40,
                       intermediate_size=13824, **kw)
