"""Flagship model families (the training configs from BASELINE.md).

The reference ships vision models in-tree (python/paddle/vision/models/) and
serves LLMs through fleet-parallel layer building blocks
(python/paddle/distributed/fleet/layers/mpu/mp_layers.py) that PaddleNLP
assembles into GPT/LLaMA. Here the assembled decoder LM is in-tree: it is the
framework's flagship model, bench target, and the exercise ground for
TP/SP/PP/sharding.
"""

from .gpt import (  # noqa: F401
    GPTConfig,
    GPTModel,
    GPTForCausalLM,
    GPTPretrainingCriterion,
    gpt3_tiny,
    gpt3_125m,
    gpt3_350m,
    gpt3_1p3b,
    gpt3_6p7b,
    gpt3_13b,
)
from .gpt_pipe import (  # noqa: F401
    GPTForCausalLMPipe,
    stack_layered_state_dict,
    unstack_to_layered_state_dict,
)
from .llama import (  # noqa: F401
    LlamaConfig,
    LlamaModel,
    LlamaForCausalLM,
    llama_tiny,
    llama_7b,
    llama_13b,
)

__all__ = [
    "GPTConfig", "GPTModel", "GPTForCausalLM", "GPTPretrainingCriterion",
    "gpt3_tiny", "gpt3_125m", "gpt3_350m", "gpt3_1p3b", "gpt3_6p7b", "gpt3_13b",
    "GPTForCausalLMPipe", "stack_layered_state_dict", "unstack_to_layered_state_dict",
    "LlamaConfig", "LlamaModel", "LlamaForCausalLM",
    "llama_tiny", "llama_7b", "llama_13b",
]

from .bert import (  # noqa: F401,E402
    BertConfig,
    BertForPretraining,
    BertForSequenceClassification,
    BertModel,
    BertPretrainingCriterion,
    bert_base,
    bert_tiny,
)
from .unet import UNetConfig, UNetModel, unet_tiny  # noqa: F401,E402
__all__ += [
    "BertConfig", "BertModel", "BertForPretraining",
    "BertForSequenceClassification", "BertPretrainingCriterion",
    "bert_base", "bert_tiny", "UNetConfig", "UNetModel", "unet_tiny",
]
