"""BERT family (reference API: the PaddleNLP-style BertModel the reference
ecosystem trains with fleet data-parallel — BASELINE.md config "BERT-base /
ERNIE-1.0 pretraining (fleet data-parallel only)"; encoder blocks are
paddle.nn.TransformerEncoder, python/paddle/nn/layer/transformer.py:697).

TPU notes: the whole model is MXU-dense (seq-major matmuls, fused LN);
masked-LM loss gathers only the masked positions before the vocab matmul so
the [B, S, V] logits tensor is never materialized (the HBM win that matters
at vocab 30k+)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn
from ..framework.core import Tensor, run_op, to_tensor

__all__ = ["BertConfig", "BertModel", "BertForPretraining",
           "BertForSequenceClassification", "BertPretrainingCriterion",
           "bert_base", "bert_tiny"]


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072,
                 max_position_embeddings=512, type_vocab_size=2,
                 hidden_dropout_prob=0.1, attention_dropout_prob=0.1,
                 layer_norm_eps=1e-12):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_dropout_prob = attention_dropout_prob
        self.layer_norm_eps = layer_norm_eps


def bert_base(**kw):
    return BertConfig(**kw)


def bert_tiny(**kw):
    return BertConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                      num_heads=4, intermediate_size=256,
                      max_position_embeddings=128, **kw)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size, cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        ids = input_ids if isinstance(input_ids, Tensor) else to_tensor(input_ids)
        B, S = ids.shape
        if position_ids is None:
            position_ids = Tensor(jnp.broadcast_to(jnp.arange(S)[None], (B, S)))
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros((B, S), jnp.int32))
        h = (self.word_embeddings(ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(h))


class BertPooler(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, hidden):
        first = run_op("bert_cls_token", lambda h: h[:, 0], [hidden])
        return nn.functional.tanh(self.dense(first))


class BertModel(nn.Layer):
    """Encoder trunk; returns (sequence_output, pooled_output)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation="gelu",
            attn_dropout=cfg.attention_dropout_prob,
            layer_norm_eps=cfg.layer_norm_eps)
        self.encoder = nn.TransformerEncoder(layer, cfg.num_layers)
        self.pooler = BertPooler(cfg)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None):
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        mask = None
        if attention_mask is not None:
            am = (attention_mask if isinstance(attention_mask, Tensor)
                  else to_tensor(attention_mask))
            # [B, S] keep-mask -> additive [B, 1, 1, S]
            mask = run_op(
                "bert_attn_mask",
                lambda m: (1.0 - m.astype(jnp.float32))[:, None, None, :] * -1e4,
                [am])
        seq = self.encoder(h, mask)
        return seq, self.pooler(seq)


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (reference BertForPretraining)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.transform_norm = nn.LayerNorm(cfg.hidden_size, cfg.layer_norm_eps)
        self.nsp_head = nn.Linear(cfg.hidden_size, 2)
        self.config = cfg

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_positions=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.transform_norm(nn.functional.gelu(self.transform(seq)))
        word_w = self.bert.embeddings.word_embeddings.weight  # tied decoder
        if masked_positions is not None:
            pos = (masked_positions if isinstance(masked_positions, Tensor)
                   else to_tensor(masked_positions))
            # gather masked slots BEFORE the vocab matmul: [B, M, H] @ [H, V]
            h = run_op(
                "mlm_gather",
                lambda hh, p: jnp.take_along_axis(
                    hh, p[..., None].astype(jnp.int32), axis=1),
                [h, pos])
        mlm_logits = run_op("mlm_decode",
                            lambda hh, w: jnp.matmul(hh, w.T), [h, word_w])
        nsp_logits = self.nsp_head(pooled)
        return mlm_logits, nsp_logits


class BertPretrainingCriterion(nn.Layer):
    """Masked-LM CE (ignore_index -100 slots) + NSP CE."""

    def __init__(self, cfg: BertConfig = None):
        super().__init__()

    def forward(self, mlm_logits, nsp_logits, mlm_labels, nsp_labels):
        def fn(lg, ng, ml, nl):
            V = lg.shape[-1]
            logp = jnp.take_along_axis(
                lg - jax.nn.logsumexp(lg, axis=-1, keepdims=True),
                jnp.maximum(ml, 0)[..., None].astype(jnp.int32), axis=-1)[..., 0]
            keep = (ml >= 0).astype(jnp.float32)
            mlm = -(logp * keep).sum() / jnp.maximum(keep.sum(), 1.0)
            nlogp = jnp.take_along_axis(
                ng - jax.nn.logsumexp(ng, axis=-1, keepdims=True),
                nl[..., None].astype(jnp.int32), axis=-1)[..., 0]
            return mlm - nlogp.mean()

        return run_op("bert_pretraining_loss", fn,
                      [mlm_logits, nsp_logits,
                       mlm_labels if isinstance(mlm_labels, Tensor) else to_tensor(mlm_labels),
                       nsp_labels if isinstance(nsp_labels, Tensor) else to_tensor(nsp_labels)])


class BertForSequenceClassification(nn.Layer):
    def __init__(self, cfg: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))
