"""GPT-style decoder LM — the flagship training model.

Reference analogs: fleet TP building blocks
(python/paddle/distributed/fleet/layers/mpu/mp_layers.py:49,336,543,744),
fused transformer kernels (paddle/phi/kernels/fusion/gpu/
fused_multi_transformer_kernel.cu, fused_rope_kernel.cu,
fused_layernorm_kernel.cu), flash attention
(python/paddle/nn/functional/flash_attention.py:358).

TPU-first design decisions:
- One config drives both GPT-3 (pre-LN LayerNorm, GELU MLP, learned positions)
  and LLaMA (RMSNorm, SwiGLU, RoPE, GQA) shapes.
- All parallelism is expressed as sharding annotations: TP via
  Column/RowParallelLinear dist_attr specs, SP/SEP via activation
  constraints. The same model object runs single-chip or under a hybrid mesh
  unchanged — GSPMD inserts the collectives the reference codes by hand.
- Attention goes through F.scaled_dot_product_attention → Pallas flash
  attention on TPU; everything else is left to XLA fusion (the epilogues the
  reference hand-fuses are single jnp expressions here).
- Static shapes throughout; the decode path keeps a static-capacity KV cache
  updated with dynamic_update_slice (reference analog: paged/cached decode
  attention masked_multihead_attention_kernel.cu) — no dynamic shapes under jit.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..framework.core import Tensor, run_op
from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..distributed.fleet.layers.mpu.mp_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    ParallelCrossEntropy,
    mark_as_sequence_parallel,
    _constrain,
)
from ..incubate.nn.functional import fused_rotary_position_embedding, swiglu

__all__ = [
    "GPTConfig",
    "GPTModel",
    "GPTForCausalLM",
    "GPTPretrainingCriterion",
    "gpt3_tiny",
    "gpt3_125m",
    "gpt3_350m",
    "gpt3_1p3b",
    "gpt3_6p7b",
    "gpt3_13b",
]


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: int | None = None  # GQA; None = MHA
    intermediate_size: int | None = None  # None → 4h (gelu) or 8h/3 rounded (swiglu)
    max_position_embeddings: int = 2048
    norm_type: str = "layernorm"  # "layernorm" | "rmsnorm"
    activation: str = "gelu"  # "gelu" | "swiglu"
    use_rope: bool = False  # False → learned position embeddings
    rope_theta: float = 10000.0
    use_neox_rotary_style: bool = True
    tie_word_embeddings: bool = True
    hidden_dropout_prob: float = 0.0
    attention_dropout_prob: float = 0.0
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    sequence_parallel: bool = False
    use_recompute: bool = False
    # "flash" = causal Pallas flash attention; "flashmask" = the Pallas
    # flashmask kernel fed per-key startend row indices (reference:
    # flashmask_attention, flash_attention.py:1299) — causal by default but
    # accepts document masks via forward(attn_startend_row_indices=...)
    attn_variant: str = "flash"
    # context parallelism: shard the sequence over the `sep` mesh axis and use
    # ring attention (paddle_tpu.parallel.ring). TPU-native upgrade over the
    # reference's bare SEP plumbing (segment_parallel.py:26); implies
    # attention_dropout_prob == 0.
    context_parallel: bool = False

    @property
    def kv_heads(self):
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def ffn_size(self):
        if self.intermediate_size is not None:
            return self.intermediate_size
        if self.activation == "swiglu":
            # LLaMA sizing: 2/3 * 4h rounded up to a multiple of 256
            return int(math.ceil(8 * self.hidden_size / 3 / 256) * 256)
        return 4 * self.hidden_size

    def num_params(self, include_embeddings=True):
        h, L, V = self.hidden_size, self.num_layers, self.vocab_size
        d = self.head_dim
        attn = h * (self.num_heads * d) + 2 * h * (self.kv_heads * d) + (self.num_heads * d) * h
        if self.activation == "swiglu":
            mlp = 3 * h * self.ffn_size
        else:
            mlp = 2 * h * self.ffn_size
        per_layer = attn + mlp + 2 * h
        total = L * per_layer + h
        if include_embeddings:
            total += V * h
            if not self.use_rope:
                total += self.max_position_embeddings * h
            if not self.tie_word_embeddings:
                total += V * h
        return total


def _make_norm(config: GPTConfig):
    if config.norm_type == "rmsnorm":
        return nn.RMSNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
    return nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)


def _init_attr(config: GPTConfig):
    return nn.ParamAttr(initializer=I.Normal(mean=0.0, std=config.initializer_range))


class GPTAttention(nn.Layer):
    """Multi-head / grouped-query causal self-attention, TP-sharded on heads.

    Reference: MultiHeadAttention (python/paddle/nn/layer/transformer.py) +
    the fused path (fused_attention_kernel.cu / flash_attn_kernel.cu); TP
    sharding as in mp_layers.py ColumnParallelLinear(gather_output=False) →
    RowParallelLinear(input_is_parallel=True).
    """

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        h, d = config.hidden_size, config.head_dim
        attr = _init_attr(config)
        bias = config.norm_type == "layernorm"  # GPT has biases, LLaMA doesn't
        self.q_proj = ColumnParallelLinear(h, config.num_heads * d, weight_attr=attr,
                                           has_bias=bias, gather_output=False)
        self.k_proj = ColumnParallelLinear(h, config.kv_heads * d, weight_attr=attr,
                                           has_bias=bias, gather_output=False)
        self.v_proj = ColumnParallelLinear(h, config.kv_heads * d, weight_attr=attr,
                                           has_bias=bias, gather_output=False)
        self.out_proj = RowParallelLinear(config.num_heads * d, h, weight_attr=attr,
                                          has_bias=bias, input_is_parallel=True)

    def forward(self, x, position_ids=None, cache=None, cache_offset=None,
                startend_row_indices=None, block_tables=None):
        cfg = self.config
        B, S = x.shape[0], x.shape[1]
        q = self.q_proj(x).reshape([B, S, cfg.num_heads, cfg.head_dim])
        k = self.k_proj(x).reshape([B, S, cfg.kv_heads, cfg.head_dim])
        v = self.v_proj(x).reshape([B, S, cfg.kv_heads, cfg.head_dim])
        # keep heads sharded over mp between the projections; batch dim
        # UNCONSTRAINED so its (dp, sharding) sharding survives (None would
        # force a replicate -> involuntary full remat in the backward)
        _U = P.UNCONSTRAINED
        q = _constrain(q, P(_U, None, "mp", None))
        k = _constrain(k, P(_U, None, "mp", None))
        v = _constrain(v, P(_U, None, "mp", None))
        if cfg.use_rope:
            q, k, _ = fused_rotary_position_embedding(
                q, k, position_ids=position_ids,
                use_neox_rotary_style=cfg.use_neox_rotary_style,
                rotary_emb_base=cfg.rope_theta,
            )
        new_cache = None
        if cache is not None and block_tables is not None:
            # paged KV cache: cache.k/v are [n_pages, Hkv, page_size, D];
            # block_tables [B, P] maps each row's logical pages to physical
            # ones. Single-token decode only — the step's K/V rows scatter
            # into each row's next slot, then the Pallas paged kernel streams
            # exactly the live pages (scalar-prefetched block table resolves
            # the physical index in the BlockSpec index_map; no gathered
            # cache copy is ever materialized). A 4-tuple cache is the
            # quantized layout (k, v, k_scale, v_scale): int8 payloads with
            # per-(page, head) f32 scales — the append requantizes under a
            # running abs-max and the kernel dequantizes in VMEM.
            if len(cache) == 4:
                k_all, k_sc = run_op(
                    "paged_kv_update_q8", _paged_update_q8,
                    [cache[0], cache[2], k, block_tables, cache_offset])
                v_all, v_sc = run_op(
                    "paged_kv_update_q8", _paged_update_q8,
                    [cache[1], cache[3], v, block_tables, cache_offset])
                new_cache = (k_all, v_all, k_sc, v_sc)
                out = run_op(
                    "paged_decode_attention_q8", _paged_attend_q8,
                    [q, k_all, v_all, k_sc, v_sc, block_tables,
                     cache_offset])
            else:
                k_all = run_op("paged_kv_update", _paged_update,
                               [cache[0], k, block_tables, cache_offset])
                v_all = run_op("paged_kv_update", _paged_update,
                               [cache[1], v, block_tables, cache_offset])
                new_cache = (k_all, v_all)
                out = run_op("paged_decode_attention", _paged_attend,
                             [q, k_all, v_all, block_tables, cache_offset])
        elif cache is not None:
            # static-capacity KV cache: cache.k/v are [B, S_max, Hkv, D]
            k_all = run_op("kv_cache_update", _dyn_update, [cache[0], k, cache_offset])
            v_all = run_op("kv_cache_update", _dyn_update, [cache[1], v, cache_offset])
            new_cache = (k_all, v_all)
            mask = _decode_mask(int(k_all.shape[1]), cache_offset, S)
            out = F.scaled_dot_product_attention(
                q, k_all, v_all, attn_mask=mask, is_causal=False,
                dropout_p=cfg.attention_dropout_prob, training=self.training,
            )
        elif cfg.context_parallel:
            assert cfg.attention_dropout_prob == 0.0, (
                "context_parallel ring attention does not support attention "
                "dropout; set attention_dropout_prob=0")
            q = _constrain(q, P(_U, "sep", "mp", None))
            k = _constrain(k, P(_U, "sep", "mp", None))
            v = _constrain(v, P(_U, "sep", "mp", None))
            out = F.ring_flash_attention(q, k, v, causal=True)
        elif cfg.attn_variant == "flashmask":
            assert cfg.attention_dropout_prob == 0.0, (
                "attn_variant='flashmask' does not support attention dropout "
                "(the flashmask kernel has no dropout path); set "
                "attention_dropout_prob=0")
            idx = startend_row_indices
            if idx is None:
                # trivial mask (= plain causal) so the flashmask kernel path
                # is exercised even without document boundaries
                idx = run_op(
                    "flashmask_causal_idx",
                    lambda qq: jnp.full((qq.shape[0], 1, qq.shape[1], 1), S,
                                        jnp.int32),
                    [q])
            out = F.flashmask_attention(
                q, k, v, startend_row_indices=idx, causal=True)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=cfg.attention_dropout_prob, training=self.training,
            )
        out = out.reshape([B, S, cfg.num_heads * cfg.head_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, new_cache
        return out


def _dyn_update(buf, new, off):
    """Write `new` [B,S,H,D] into static cache `buf` at sequence offset
    `off`. A VECTOR off [B] writes per-row offsets (continuous-batching
    decode, S==1: each slot appends at its own length)."""
    off = jnp.asarray(off).astype(jnp.int32)
    if off.ndim == 0:
        return jax.lax.dynamic_update_slice(
            buf, new.astype(buf.dtype), (0, off.reshape(()), 0, 0))
    B = buf.shape[0]
    return buf.at[jnp.arange(B), off].set(new[:, 0].astype(buf.dtype))


def _paged_update(buf, new, tables, lengths):
    """Write this step's `new` [B, 1, H, D] K/V rows into the paged cache
    `buf` [n_pages, Hkv, ps, D] at each row's next slot (decode is S==1)."""
    from ..ops.pallas.decode_attention import paged_kv_write

    return paged_kv_write(buf, new[:, 0], tables,
                          jnp.asarray(lengths).astype(jnp.int32))


def _paged_attend(q, kc, vc, tables, lengths):
    """q [B, 1, H, D] (one decode step) against the paged cache; `lengths`
    counts tokens present BEFORE this step, and the step's K/V were just
    written by _paged_update, so the kernel sees lengths + 1 valid tokens."""
    from ..ops.pallas.decode_attention import paged_decode_attention

    B, S, H, D = q.shape
    o = paged_decode_attention(
        q.reshape(B, H, D), kc, vc, tables,
        jnp.asarray(lengths).astype(jnp.int32) + 1)
    return o.reshape(B, S, H, D)


def _paged_update_q8(buf, scales, new, tables, lengths):
    """Quantized decode append: write this step's `new` [B, 1, H, D] K/V
    rows into the int8 paged cache, growing each target page's running
    abs-max scale when needed. Returns (cache, scales)."""
    from ..ops.pallas.decode_attention import paged_kv_write_q8

    return paged_kv_write_q8(buf, scales, new[:, 0], tables,
                             jnp.asarray(lengths).astype(jnp.int32))


def _paged_attend_q8(q, kc, vc, k_sc, v_sc, tables, lengths):
    """Dequant-fused decode attention over the int8 paged cache (same
    lengths + 1 contract as _paged_attend)."""
    from ..ops.pallas.decode_attention import paged_decode_attention

    B, S, H, D = q.shape
    o = paged_decode_attention(
        q.reshape(B, H, D), kc, vc, tables,
        jnp.asarray(lengths).astype(jnp.int32) + 1, kv_scales=(k_sc, v_sc))
    return o.reshape(B, S, H, D)


def _decode_mask(s_max, offset, s_new):
    """Bool mask: position i (absolute off+i) attends to j<=off+i.
    Scalar offset -> [1,1,S_new,S_max] (shared); vector offset [B] ->
    [B,1,S_new,S_max] (per-slot lengths, continuous batching)."""
    def fn(off):
        off = jnp.asarray(off).astype(jnp.int32)
        cols = jnp.arange(s_max)[None, :]
        if off.ndim == 0:
            rows = off.reshape(()) + jnp.arange(s_new)[:, None]
            return (cols <= rows)[None, None]
        rows = off[:, None, None] + jnp.arange(s_new)[None, :, None]
        return (cols[None] <= rows)[:, None]

    return run_op("decode_mask", fn, [offset])


class GPTMLP(nn.Layer):
    """FFN: gelu 2-matmul or swiglu 3-matmul, TP column→row sharded
    (reference: fused_feedforward_kernel.cu; swiglu.py:26)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        h, f = config.hidden_size, config.ffn_size
        attr = _init_attr(config)
        bias = config.norm_type == "layernorm"
        self.activation = config.activation
        if config.activation == "swiglu":
            self.gate_proj = ColumnParallelLinear(h, f, weight_attr=attr, has_bias=bias,
                                                  gather_output=False)
            self.up_proj = ColumnParallelLinear(h, f, weight_attr=attr, has_bias=bias,
                                                gather_output=False)
            self.down_proj = RowParallelLinear(f, h, weight_attr=attr, has_bias=bias,
                                               input_is_parallel=True)
        else:
            self.fc1 = ColumnParallelLinear(h, f, weight_attr=attr, has_bias=bias,
                                            gather_output=False)
            self.fc2 = RowParallelLinear(f, h, weight_attr=attr, has_bias=bias,
                                         input_is_parallel=True)

    def forward(self, x):
        if self.activation == "swiglu":
            return self.down_proj(swiglu(self.gate_proj(x), self.up_proj(x)))
        return self.fc2(F.gelu(self.fc1(x)))


class GPTDecoderLayer(nn.Layer):
    """Pre-norm decoder block (reference: the block fused_multi_transformer
    implements in one kernel, fused_multi_transformer_kernel.cu — here a
    traceable composition XLA fuses)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.input_layernorm = _make_norm(config)
        self.self_attn = GPTAttention(config)
        self.post_attention_layernorm = _make_norm(config)
        self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, position_ids=None, cache=None, cache_offset=None,
                startend_row_indices=None, block_tables=None):
        residual = x
        h = self.input_layernorm(x)
        if cache is not None:
            h, new_cache = self.self_attn(h, position_ids, cache, cache_offset,
                                          block_tables=block_tables)
        else:
            h = self.self_attn(
                h, position_ids, startend_row_indices=startend_row_indices)
            new_cache = None
        x = residual + self.dropout(h)
        residual = x
        h = self.mlp(self.post_attention_layernorm(x))
        x = residual + self.dropout(h)
        if self.config.sequence_parallel:
            x = mark_as_sequence_parallel(x)
        if cache is not None:
            return x, new_cache
        return x


class GPTModel(nn.Layer):
    """Embeddings + decoder stack + final norm."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        attr = _init_attr(config)
        self.embed_tokens = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=attr
        )
        if not config.use_rope:
            self.embed_positions = nn.Embedding(
                config.max_position_embeddings, config.hidden_size, weight_attr=attr
            )
        self.embed_dropout = nn.Dropout(config.hidden_dropout_prob)
        self.layers = nn.LayerList([GPTDecoderLayer(config) for _ in range(config.num_layers)])
        self.final_norm = _make_norm(config)

    def forward(self, input_ids, position_ids=None, caches=None,
                cache_offset=None, attn_startend_row_indices=None,
                block_tables=None):
        B, S = input_ids.shape[0], input_ids.shape[1]
        if position_ids is None:
            if caches is not None and cache_offset is not None:
                # decode default: absolute positions start at the cache offset
                position_ids = run_op(
                    "decode_positions",
                    lambda off: jnp.broadcast_to(
                        jnp.asarray(off).astype(jnp.int32).reshape(())
                        + jnp.arange(S)[None, :],
                        (B, S),
                    ),
                    [cache_offset],
                )
            else:
                position_ids = Tensor(
                    jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
                )
        h = self.embed_tokens(input_ids)
        if not self.config.use_rope:
            h = h + self.embed_positions(position_ids)
        h = self.embed_dropout(h)
        if self.config.sequence_parallel:
            h = mark_as_sequence_parallel(h)
        new_caches = [] if caches is not None else None

        if caches is not None and attn_startend_row_indices is not None:
            raise ValueError(
                "attn_startend_row_indices is not supported together with KV "
                "caches: the cached decode path would silently attend across "
                "document boundaries")

        def run_layer(layer, h, cache):
            if cache is not None:
                return layer(h, position_ids, cache, cache_offset,
                             block_tables=block_tables)
            return layer(h, position_ids,
                         startend_row_indices=attn_startend_row_indices)

        for i, layer in enumerate(self.layers):
            cache = caches[i] if caches is not None else None
            if self.config.use_recompute and self.training and cache is None:
                from ..distributed.fleet.recompute import recompute

                h = recompute(layer, h, position_ids,
                              startend_row_indices=attn_startend_row_indices)
            else:
                out = run_layer(layer, h, cache)
                if cache is not None:
                    h, nc = out
                    new_caches.append(nc)
                else:
                    h = out
        h = self.final_norm(h)
        if caches is not None:
            return h, new_caches
        return h


class GPTForCausalLM(nn.Layer):
    """LM head on top of GPTModel. Tied embeddings (GPT) share the
    vocab-sharded embedding matrix; untied (LLaMA) use a vocab-sharded
    ColumnParallelLinear. Logits stay vocab-sharded into the parallel
    cross-entropy (reference: mp_layers.py:744 ParallelCrossEntropy)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size,
                weight_attr=_init_attr(config), has_bias=False, gather_output=False,
            )

    def forward(self, input_ids, position_ids=None, caches=None,
                cache_offset=None, attn_startend_row_indices=None,
                block_tables=None):
        out = self.gpt(input_ids, position_ids, caches, cache_offset,
                       attn_startend_row_indices=attn_startend_row_indices,
                       block_tables=block_tables)
        if caches is not None:
            h, new_caches = out
        else:
            h = out
        if self.config.tie_word_embeddings:
            w = self.gpt.embed_tokens.weight
            logits = run_op("lm_head_tied", lambda a, ww: jnp.matmul(a, ww.T), [h, w])
            logits = _constrain(
                logits, P(P.UNCONSTRAINED, P.UNCONSTRAINED, "mp"))
        else:
            logits = self.lm_head(h)
        if caches is not None:
            return logits, new_caches
        return logits

    def init_kv_caches(self, batch_size, max_seq_len, dtype="float32"):
        """Static-capacity decode caches, one (k, v) pair per layer."""
        cfg = self.config
        shape = (batch_size, max_seq_len, cfg.kv_heads, cfg.head_dim)
        return [
            (Tensor(jnp.zeros(shape, jnp.dtype(dtype))), Tensor(jnp.zeros(shape, jnp.dtype(dtype))))
            for _ in range(cfg.num_layers)
        ]


class GPTPretrainingCriterion(nn.Layer):
    """Masked next-token cross entropy over (possibly vocab-sharded) logits."""

    def __init__(self, config: GPTConfig = None):
        super().__init__()
        self.ce = ParallelCrossEntropy()

    def forward(self, logits, labels, loss_mask=None):
        losses = self.ce(logits, labels)  # [B, S]
        if loss_mask is not None:
            m = loss_mask.reshape(losses.shape).astype("float32")
            return (losses.astype("float32") * m).sum() / m.sum().clip(min=1.0)
        return losses.mean()


# ----------------------------------------------------------------------- #
# presets (sizes per GPT-3 paper table 2.1 — the BASELINE.md configs)
# ----------------------------------------------------------------------- #


def gpt3_tiny(**kw):
    return GPTConfig(vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
                     max_position_embeddings=128, **kw)


def gpt3_125m(**kw):
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)


def gpt3_350m(**kw):
    return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16, **kw)


def gpt3_1p3b(**kw):
    return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16, **kw)


def gpt3_6p7b(**kw):
    return GPTConfig(hidden_size=4096, num_layers=32, num_heads=32, **kw)


def gpt3_13b(**kw):
    return GPTConfig(hidden_size=5120, num_layers=40, num_heads=40, **kw)


def _generate_method(self, input_ids, **kwargs):
    """Autoregressive decoding (paddle_tpu.models.generation.generate)."""
    from .generation import generate as _generate

    return _generate(self, input_ids, **kwargs)


GPTForCausalLM.generate = _generate_method
