"""Diffusion UNet (BASELINE.md config "Stable Diffusion UNet: conv +
cross-attn"; architecture per the latent-diffusion UNet, built on
paddle_tpu.nn — residual GroupNorm/SiLU conv blocks, self+cross attention
at low resolutions, sinusoidal timestep embedding, skip connections).

TPU notes: convs stay NCHW at the API (XLA retiles internally); attention
blocks flatten HxW into sequence and ride the same scaled_dot_product
/ flash path as the language models — the conv+cross-attn fusion coverage
the reference exercises via CINN lands on XLA here."""

from __future__ import annotations

import math

import jax.numpy as jnp

import paddle_tpu.nn as nn
from ..framework.core import Tensor, run_op, to_tensor

__all__ = ["UNetConfig", "UNetModel", "unet_tiny"]


class UNetConfig:
    def __init__(self, in_channels=4, out_channels=4, base_channels=128,
                 channel_mult=(1, 2, 4), num_res_blocks=2,
                 attention_levels=(1, 2), num_heads=4, context_dim=512,
                 groups=32):
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.base_channels = base_channels
        self.channel_mult = tuple(channel_mult)
        self.num_res_blocks = num_res_blocks
        self.attention_levels = tuple(attention_levels)
        self.num_heads = num_heads
        self.context_dim = context_dim
        self.groups = groups


def unet_tiny(**kw):
    return UNetConfig(in_channels=3, out_channels=3, base_channels=32,
                      channel_mult=(1, 2), num_res_blocks=1,
                      attention_levels=(1,), num_heads=2, context_dim=64,
                      groups=8, **kw)


def timestep_embedding(t, dim, max_period=10000.0):
    """Sinusoidal embedding [B, dim] (DDPM convention)."""
    tt = t if isinstance(t, Tensor) else to_tensor(t)

    def fn(v):
        half = dim // 2
        freqs = jnp.exp(-math.log(max_period)
                        * jnp.arange(half, dtype=jnp.float32) / half)
        args = v.astype(jnp.float32)[:, None] * freqs[None]
        return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)

    return run_op("timestep_embedding", fn, [tt])


class ResBlock(nn.Layer):
    def __init__(self, in_c, out_c, emb_dim, groups):
        super().__init__()
        self.norm1 = nn.GroupNorm(min(groups, in_c), in_c)
        self.conv1 = nn.Conv2D(in_c, out_c, 3, padding=1)
        self.emb_proj = nn.Linear(emb_dim, out_c)
        self.norm2 = nn.GroupNorm(min(groups, out_c), out_c)
        self.conv2 = nn.Conv2D(out_c, out_c, 3, padding=1)
        self.skip = (nn.Conv2D(in_c, out_c, 1) if in_c != out_c else None)
        self.act = nn.Silu()

    def forward(self, x, emb):
        h = self.conv1(self.act(self.norm1(x)))
        e = self.emb_proj(self.act(emb))
        h = run_op("res_emb_add", lambda a, b: a + b[:, :, None, None], [h, e])
        h = self.conv2(self.act(self.norm2(h)))
        s = self.skip(x) if self.skip is not None else x
        return h + s


class AttnBlock(nn.Layer):
    """Self-attention + cross-attention over flattened spatial positions."""

    def __init__(self, channels, num_heads, context_dim, groups):
        super().__init__()
        self.norm = nn.GroupNorm(min(groups, channels), channels)
        self.self_attn = nn.MultiHeadAttention(channels, num_heads)
        self.cross_attn = nn.MultiHeadAttention(
            channels, num_heads, kdim=context_dim, vdim=context_dim)
        self.norm2 = nn.LayerNorm(channels)
        self.proj = nn.Linear(channels, channels)

    def forward(self, x, context=None):
        B, C, H, W = x.shape
        seq = run_op("spatial_flatten",
                     lambda v: jnp.swapaxes(v.reshape(v.shape[0], v.shape[1], -1), 1, 2),
                     [self.norm(x)])
        h = seq + self.self_attn(seq, seq, seq)
        if context is not None:
            ctx = context if isinstance(context, Tensor) else to_tensor(context)
            h = h + self.cross_attn(self.norm2(h), ctx, ctx)
        h = self.proj(h)
        out = run_op(
            "spatial_unflatten",
            lambda v, hh=H, ww=W: jnp.swapaxes(v, 1, 2).reshape(
                v.shape[0], v.shape[2], hh, ww),
            [h])
        return x + out


class UNetModel(nn.Layer):
    """forward(x [B,C,H,W], timesteps [B], context [B,L,D]) -> [B,C,H,W]."""

    def __init__(self, cfg: UNetConfig):
        super().__init__()
        self.config = cfg
        ch = cfg.base_channels
        emb_dim = ch * 4
        self.time_mlp1 = nn.Linear(ch, emb_dim)
        self.time_mlp2 = nn.Linear(emb_dim, emb_dim)
        self.conv_in = nn.Conv2D(cfg.in_channels, ch, 3, padding=1)

        self.down_blocks = nn.LayerList()
        self.down_attns = nn.LayerList()
        self.downsamples = nn.LayerList()
        chans = [ch]
        cur = ch
        for lvl, mult in enumerate(cfg.channel_mult):
            out_c = ch * mult
            for _ in range(cfg.num_res_blocks):
                self.down_blocks.append(ResBlock(cur, out_c, emb_dim, cfg.groups))
                self.down_attns.append(
                    AttnBlock(out_c, cfg.num_heads, cfg.context_dim, cfg.groups)
                    if lvl in cfg.attention_levels else None)
                cur = out_c
                chans.append(cur)
            if lvl < len(cfg.channel_mult) - 1:
                self.downsamples.append(nn.Conv2D(cur, cur, 3, stride=2, padding=1))
                chans.append(cur)
            else:
                self.downsamples.append(None)

        self.mid_block1 = ResBlock(cur, cur, emb_dim, cfg.groups)
        self.mid_attn = AttnBlock(cur, cfg.num_heads, cfg.context_dim, cfg.groups)
        self.mid_block2 = ResBlock(cur, cur, emb_dim, cfg.groups)

        self.up_blocks = nn.LayerList()
        self.up_attns = nn.LayerList()
        self.upsamples = nn.LayerList()
        for lvl, mult in reversed(list(enumerate(cfg.channel_mult))):
            out_c = ch * mult
            for _ in range(cfg.num_res_blocks + 1):
                skip_c = chans.pop()
                self.up_blocks.append(
                    ResBlock(cur + skip_c, out_c, emb_dim, cfg.groups))
                self.up_attns.append(
                    AttnBlock(out_c, cfg.num_heads, cfg.context_dim, cfg.groups)
                    if lvl in cfg.attention_levels else None)
                cur = out_c
            if lvl > 0:
                self.upsamples.append(nn.Conv2D(cur, cur, 3, padding=1))
            else:
                self.upsamples.append(None)

        self.norm_out = nn.GroupNorm(min(cfg.groups, cur), cur)
        self.conv_out = nn.Conv2D(cur, cfg.out_channels, 3, padding=1)
        self.act = nn.Silu()

    def forward(self, x, timesteps, context=None):
        cfg = self.config
        emb = timestep_embedding(timesteps, cfg.base_channels)
        emb = self.time_mlp2(self.act(self.time_mlp1(emb)))

        h = self.conv_in(x if isinstance(x, Tensor) else to_tensor(x))
        skips = [h]
        i = 0
        for lvl in range(len(cfg.channel_mult)):
            for _ in range(cfg.num_res_blocks):
                h = self.down_blocks[i](h, emb)
                if self.down_attns[i] is not None:
                    h = self.down_attns[i](h, context)
                skips.append(h)
                i += 1
            if self.downsamples[lvl] is not None:
                h = self.downsamples[lvl](h)
                skips.append(h)

        h = self.mid_block1(h, emb)
        h = self.mid_attn(h, context)
        h = self.mid_block2(h, emb)

        i = 0
        for uidx, lvl in enumerate(reversed(range(len(cfg.channel_mult)))):
            for _ in range(cfg.num_res_blocks + 1):
                skip = skips.pop()
                h = run_op("unet_skip_cat",
                           lambda a, b: jnp.concatenate([a, b], axis=1),
                           [h, skip])
                h = self.up_blocks[i](h, emb)
                if self.up_attns[i] is not None:
                    h = self.up_attns[i](h, context)
                i += 1
            if self.upsamples[uidx] is not None:
                h = run_op(
                    "unet_upsample",
                    lambda v: jnp.repeat(jnp.repeat(v, 2, axis=2), 2, axis=3),
                    [h])
                h = self.upsamples[uidx](h)

        return self.conv_out(self.act(self.norm_out(h)))
