"""Pipelined flagship GPT: stacked decoder params + compiled pp schedule.

Reference analog: GPT-style models built from PipelineLayer/LayerDesc and
driven by the 1F1B runtime (python/paddle/distributed/fleet/meta_parallel/
pp_layers.py:258, pipeline_parallel.py:684). There the pipeline is a process
schedule; here it is program structure:

- All L decoder layers' parameters live in STACKED arrays with leading dim L
  (flax scan-over-layers style). Benefits on TPU: one compiled layer body
  regardless of depth (compile time O(1) in L), and the leading dim is the
  natural pp shard axis.
- pp=1: the decoder stack is a lax.scan over the leading dim.
- pp>1: the stack reshapes to [S, L/S, ...] and runs through
  paddle_tpu.parallel.pipeline_spmd — microbatches ride a ppermute ring over
  the `pp` mesh axis while TP/DP/sharding stay GSPMD auto axes inside each
  stage.

The per-layer compute is the SAME GPTDecoderLayer as models/gpt.py (TP
sharding constraints included) executed through jit.functional_call against
a parameter-less template — so single-chip GPT and pipelined GPT cannot
drift apart numerically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..framework.core import Parameter, Tensor, run_op
from .. import nn
from ..nn import initializer as I
from ..distributed import env as _env
from ..distributed.fleet.layers.mpu.mp_layers import (
    VocabParallelEmbedding,
    ColumnParallelLinear,
    _constrain,
)
from ..parallel.pipeline import (
    microbatch,
    pack_chunked,
    pipeline_1f1b,
    pipeline_interleaved,
    pipeline_spmd,
    unmicrobatch,
)
from .gpt import GPTConfig, GPTDecoderLayer, _init_attr, _make_norm

__all__ = ["GPTForCausalLMPipe", "stack_layered_state_dict", "unstack_to_layered_state_dict"]


def _stacked_name(template_name: str) -> str:
    return "stack__" + template_name.replace(".", "__")


class GPTForCausalLMPipe(nn.Layer):
    """GPT/LLaMA causal LM with stacked decoder params and a compiled
    pipeline schedule over the mesh's `pp` axis.

    num_microbatches: microbatch count M for the pipeline (reference
    accumulate_steps, pipeline_parallel.py:940). Ignored when pp == 1.

    pp_schedule selects the compiled schedule (reference schedule_mode in
    pp_configs + VPP selection, fleet/model.py:160-185):
      - "gpipe": forward scan, autodiff backward (FThenB-like).
      - "vpp": interleaved virtual stages, vpp_degree chunks per stage
        (reference pipeline_parallel.py:1308); needs num_layers divisible
        by pp*vpp_degree and num_microbatches >= pp.
      - "1f1b": per-tick mixed fwd/bwd with in-schedule grads
        (reference :684); engaged through forward_loss() during training
        (forward() falls back to gpipe for inference).
    """

    def __init__(self, config: GPTConfig, num_microbatches: int = 4,
                 pp_schedule: str = "gpipe", vpp_degree: int = 1):
        super().__init__()
        self.config = config
        self.num_microbatches = num_microbatches
        if pp_schedule not in ("gpipe", "vpp", "1f1b"):
            raise ValueError(f"unknown pp_schedule {pp_schedule!r}")
        self.pp_schedule = pp_schedule
        self.vpp_degree = vpp_degree if pp_schedule == "vpp" else 1
        attr = _init_attr(config)
        self.embed_tokens = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=attr
        )
        if not config.use_rope:
            self.embed_positions = nn.Embedding(
                config.max_position_embeddings, config.hidden_size, weight_attr=attr
            )
        self.embed_dropout = nn.Dropout(config.hidden_dropout_prob)
        self.final_norm = _make_norm(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size,
                weight_attr=attr, has_bias=False, gather_output=False,
            )

        # template holds the layer STRUCTURE; its own params are never used
        # (functional_call swaps in slices of the stacked params). Stored
        # outside the Layer registry so it contributes nothing to
        # parameters()/state_dict().
        template = GPTDecoderLayer(config)
        template.eval()
        object.__setattr__(self, "_template", template)
        self._param_names = [n for n, _ in template.named_parameters()]

        # inherit exact per-layer init distributions by building ONE layer at
        # a time into preallocated host buffers (peak memory = stacked params
        # + a single layer, not 2x the decoder)
        import numpy as np

        L = config.num_layers
        bufs = {}
        for i in range(L):
            layer = template if i == 0 else GPTDecoderLayer(config)
            named = dict(layer.named_parameters())
            for tname in self._param_names:
                v = np.asarray(named[tname]._value)
                if tname not in bufs:
                    bufs[tname] = np.empty((L,) + v.shape, v.dtype)
                bufs[tname][i] = v
            del layer, named
        tparams = dict(template.named_parameters())
        for tname in self._param_names:
            tparam = tparams[tname]
            stacked = Parameter(jnp.asarray(bufs.pop(tname)), name=_stacked_name(tname))
            tspec = tuple(tparam.dist_attr) if tparam.dist_attr is not None else ()
            pad = (None,) * (stacked._value.ndim - 1 - len(tspec))
            stacked.dist_attr = P("pp", *(tuple(tspec) + pad))
            self.add_parameter(_stacked_name(tname), stacked)

    # ------------------------------------------------------------------ #

    def _stacked_tensors(self):
        return [getattr(self, _stacked_name(n)) for n in self._param_names]

    def _layer_fn(self, training):
        """Single decoder-layer apply through the template (memoized so the
        compiled-pipeline cache in parallel.pipeline keys on a stable fn)."""
        cached = self.__dict__.setdefault("_layer_fn_cache", {})
        if training not in cached:
            template = self._template
            names = self._param_names

            from ..jit import functional_call

            def layer_fn(pslice, hh, pos, key):
                out, _ = functional_call(
                    template, dict(zip(names, pslice)), {},
                    [Tensor(hh), Tensor(pos)], train=training, rng_key=key,
                )
                return out

            cached[training] = layer_fn
        return cached[training]

    def _stage_fn(self, training, lps):
        """Stage body for the compiled pipeline: scan lps layers, each with
        its own dropout key folded with the microbatch index."""
        cached = self.__dict__.setdefault("_stage_fn_cache", {})
        k = (training, lps)
        if k not in cached:
            layer_fn = self._layer_fn(training)

            def stage_fn(pstage, inp):
                hh, pos, mb_idx = inp
                params, keys = pstage

                def scan_body(carry, x):
                    pslice, key = x
                    key = jax.random.fold_in(key, mb_idx[0])
                    return layer_fn(pslice, carry, pos, key), None

                hh, _ = jax.lax.scan(scan_body, hh, (params, keys))
                return (hh, pos, mb_idx)

            cached[k] = stage_fn
        return cached[k]

    def forward(self, input_ids, position_ids=None):
        cfg = self.config
        B, S = input_ids.shape[0], input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.broadcast_to(jnp.arange(S)[None, :], (B, S)))
        h = self.embed_tokens(input_ids)
        if not cfg.use_rope:
            h = h + self.embed_positions(position_ids)
        h = self.embed_dropout(h)

        training = self.training
        mesh = _env.get_global_mesh()
        pp = mesh.shape.get("pp", 1) if mesh is not None else 1
        M = self.num_microbatches
        L = cfg.num_layers
        layer_fn = self._layer_fn(training)
        stage_fn = self._stage_fn(training, L // pp if pp > 1 else L)
        from ..framework import random as rnd

        def decoder_stack(h_raw, pos_raw, *stacked_raw):
            # one dropout key per layer (reference: TP-aware RNG tracker,
            # fleet/layers/mpu/random.py); pipeline path additionally folds
            # in the microbatch index so ticks decorrelate
            base_key = rnd.next_key()
            keys = jax.vmap(lambda i: jax.random.fold_in(base_key, i))(
                jnp.arange(L)
            )
            stacked = list(stacked_raw)
            if pp > 1:
                V = self.vpp_degree
                if L % (pp * V) != 0:
                    raise ValueError(
                        f"num_layers {L} not divisible by pp*vpp {pp * V}")
                lps = L // (pp * V)
                mb = h_raw.shape[0] // M
                mb_idx = jnp.repeat(jnp.arange(M, dtype=jnp.int32), mb)
                inp_mb = microbatch((h_raw, pos_raw, mb_idx), M)
                if V > 1:
                    vstage_fn = self._stage_fn(training, lps)
                    chunked = pack_chunked(
                        [a.reshape((pp * V, lps) + a.shape[1:])
                         for a in stacked], pp, V)
                    keys_c = pack_chunked(
                        keys.reshape((pp * V, lps) + keys.shape[1:]), pp, V)
                    out_mb = pipeline_interleaved(
                        vstage_fn, (chunked, keys_c), inp_mb,
                        mesh=mesh, axis="pp", num_chunks=V,
                    )
                else:
                    staged = [a.reshape((pp, lps) + a.shape[1:]) for a in stacked]
                    keys_staged = keys.reshape((pp, lps) + keys.shape[1:])
                    out_mb = pipeline_spmd(
                        stage_fn, (staged, keys_staged), inp_mb,
                        mesh=mesh, axis="pp", remat=True,
                    )
                out, _, _ = unmicrobatch(out_mb)
                return out

            def scan_body(carry, x):
                pslice, key = x
                return layer_fn(pslice, carry, pos_raw, key), None

            out, _ = jax.lax.scan(scan_body, h_raw, (stacked, keys))
            return out

        h = run_op("decoder_stack_pipeline", decoder_stack,
                   [h, position_ids] + self._stacked_tensors())
        h = self.final_norm(h)
        if cfg.tie_word_embeddings:
            w = self.embed_tokens.weight
            logits = run_op("lm_head_tied", lambda a, ww: jnp.matmul(a, ww.T), [h, w])
            logits = _constrain(logits, P(P.UNCONSTRAINED, P.UNCONSTRAINED, "mp"))
        else:
            logits = self.lm_head(h)
        return logits


    # ------------------------------------------------------------------ #
    # 1F1B training path: loss inside the schedule
    # ------------------------------------------------------------------ #

    def _stage_fn_1f1b(self, training, lps):
        """Stage body whose activation pytree carries the labels rider so the
        last stage can seed its own backward (1F1B contract)."""
        cached = self.__dict__.setdefault("_stage_fn_1f1b_cache", {})
        k = (training, lps)
        if k not in cached:
            layer_fn = self._layer_fn(training)

            def stage_fn(pstage, inp):
                hh, pos, mb_idx, labels = inp
                params, keys = pstage

                def scan_body(carry, x):
                    pslice, key = x
                    key = jax.random.fold_in(key, mb_idx[0])
                    return layer_fn(pslice, carry, pos, key), None

                hh, _ = jax.lax.scan(scan_body, hh, (params, keys))
                return (hh, pos, mb_idx, labels)

            cached[k] = stage_fn
        return cached[k]

    def _loss_fn_1f1b(self, criterion):
        """Last-stage head: final_norm -> lm_head -> criterion, applied to
        raw values (reference: loss_fn as the last PipelineLayer entry,
        pp_layers.py)."""
        cached = self.__dict__.setdefault("_loss_fn_1f1b_cache", {})
        if criterion not in cached:
            cfg = self.config
            norm = self.final_norm
            norm_names = [n for n, _ in norm.named_parameters()]

            from ..jit import functional_call

            def loss_fn(lp, out):
                hh, pos, mb_idx, labels = out
                h_n, _ = functional_call(
                    norm, dict(zip(norm_names, lp["norm"])), {},
                    [Tensor(hh)], train=False)
                if cfg.tie_word_embeddings:
                    logits = jnp.matmul(h_n, lp["head"].T)
                else:
                    logits = jnp.matmul(h_n, lp["head"])
                logits = _constrain(logits, P(P.UNCONSTRAINED, P.UNCONSTRAINED, "mp"))
                loss = criterion(Tensor(logits), Tensor(labels))
                return loss._value.astype(jnp.float32)

            cached[criterion] = loss_fn
        return cached[criterion]

    def forward_loss(self, input_ids, labels, criterion):
        """Mean LM loss via the compiled 1F1B schedule: embedding runs ahead
        of the pipeline (its grads arrive through the schedule's input
        cotangents), decoder stages run per-tick mixed fwd/bwd, and
        final_norm + lm_head + criterion form the last-stage loss that seeds
        each microbatch's backward (reference forward_backward_pipeline
        :684). Falls back to forward()+criterion when pp == 1."""
        cfg = self.config
        mesh = _env.get_global_mesh()
        pp = mesh.shape.get("pp", 1) if mesh is not None else 1
        if pp <= 1 or self.pp_schedule != "1f1b":
            return criterion(self.forward(input_ids), labels)

        B, S = input_ids.shape[0], input_ids.shape[1]
        position_ids = Tensor(jnp.broadcast_to(jnp.arange(S)[None, :], (B, S)))
        h = self.embed_tokens(input_ids)
        if not cfg.use_rope:
            h = h + self.embed_positions(position_ids)
        h = self.embed_dropout(h)

        training = self.training
        M = self.num_microbatches
        L = cfg.num_layers
        if L % pp != 0:
            raise ValueError(f"num_layers {L} not divisible by pp {pp}")
        lps = L // pp
        stage_fn = self._stage_fn_1f1b(training, lps)
        loss_fn = self._loss_fn_1f1b(criterion)
        from ..framework import random as rnd

        norm_params = [p for _, p in self.final_norm.named_parameters()]
        head_w = (self.embed_tokens.weight if cfg.tie_word_embeddings
                  else self.lm_head.weight)
        n_norm = len(norm_params)

        def fused(h_raw, pos_raw, lab_raw, head_raw, *rest):
            norm_raw = list(rest[:n_norm])
            stacked = list(rest[n_norm:])
            base_key = rnd.next_key()
            keys = jax.vmap(lambda i: jax.random.fold_in(base_key, i))(
                jnp.arange(L))
            staged = [a.reshape((pp, lps) + a.shape[1:]) for a in stacked]
            keys_staged = keys.reshape((pp, lps) + keys.shape[1:])
            mb = h_raw.shape[0] // M
            mb_idx = jnp.repeat(jnp.arange(M, dtype=jnp.int32), mb)
            inp_mb = microbatch(
                (h_raw, pos_raw, mb_idx, lab_raw.astype(jnp.int32)), M)
            lp = {"norm": norm_raw, "head": head_raw}
            return pipeline_1f1b(
                stage_fn, loss_fn, (staged, keys_staged), lp, inp_mb,
                mesh=mesh, axis="pp")

        labels_t = labels if isinstance(labels, Tensor) else Tensor(labels)
        return run_op(
            "pp_1f1b_loss", fused,
            [h, position_ids, labels_t, head_w] + norm_params
            + self._stacked_tensors())


# ------------------------------------------------------------------------- #
# state-dict interop with the layered GPTForCausalLM
# ------------------------------------------------------------------------- #


def stack_layered_state_dict(layered: dict, num_layers: int) -> dict:
    """Convert a GPTForCausalLM state_dict (gpt.layers.<i>.<p> keys) to the
    pipe model's layout (stack__<p> keys + shared embed/norm/head keys)."""
    out = {}
    per_layer: dict[str, list] = {}
    for k, v in layered.items():
        if k.startswith("gpt.layers."):
            rest = k[len("gpt.layers."):]
            idx, pname = rest.split(".", 1)
            per_layer.setdefault(pname, [None] * num_layers)[int(idx)] = v
        elif k.startswith("gpt."):
            out[k[len("gpt."):]] = v
        else:
            out[k] = v
    for pname, vals in per_layer.items():
        if any(v is None for v in vals):
            raise ValueError(f"missing layers for {pname}")
        out[_stacked_name(pname)] = Tensor(
            jnp.stack([v._value if isinstance(v, Tensor) else jnp.asarray(v) for v in vals])
        )
    return out


def unstack_to_layered_state_dict(pipe_sd: dict, num_layers: int) -> dict:
    """Inverse of stack_layered_state_dict."""
    out = {}
    for k, v in pipe_sd.items():
        if k.startswith("stack__"):
            pname = k[len("stack__"):].replace("__", ".")
            arr = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            for i in range(num_layers):
                out[f"gpt.layers.{i}.{pname}"] = Tensor(arr[i])
        else:
            out["gpt." + k if not k.startswith("lm_head") else k] = v
    return out
