"""Autoregressive generation for the causal-LM models.

Reference analog: the decode loops PaddleNLP builds over
fused_multi_transformer / masked_multihead_attention (the framework itself
ships the kernels; SURVEY §2.2 block attention / MMHA). TPU-native design:
prefill and per-token decode are TWO jitted programs with static shapes
(prompt padded to a bucket, cache at fixed capacity); the python loop only
feeds back the sampled token — every FLOP is inside XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..framework import random as rnd

__all__ = ["generate"]


def _sample(logits, temperature, top_k, top_p, key):
    """logits [B, V] -> token ids [B]."""
    if temperature == 0.0:
        return jnp.argmax(logits, -1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    V = logits.shape[-1]
    if top_k and top_k > 0 and top_k < V:
        kth = jnp.sort(logits, -1)[:, V - top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, -1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, -1)
        cum = jnp.cumsum(probs, -1)
        # keep the smallest set with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < top_p, -1)  # [B]
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], -1)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(key, logits, -1).astype(jnp.int32)


def generate(model, input_ids, max_new_tokens=32, temperature=1.0, top_k=0,
             top_p=1.0, eos_token_id=None, use_cache=True, seed=None):
    """Greedy/sampled decoding. input_ids: Tensor or ndarray [B, S_prompt].
    Returns Tensor [B, S_prompt + n_generated] (stops early when every row
    emitted eos_token_id)."""
    from ..jit import functional_call

    ids = input_ids.numpy() if isinstance(input_ids, Tensor) else np.asarray(input_ids)
    ids = ids.astype(np.int32)
    B, S0 = ids.shape
    total = S0 + max_new_tokens
    was_training = model.training
    model.eval()
    params = {k: p._value for k, p in model.named_parameters()}
    buffers = {k: b._value for k, b in model.named_buffers()}
    cfg = model.config
    caches = [(jnp.zeros((B, total, cfg.kv_heads, cfg.head_dim), jnp.float32),) * 2
              for _ in range(cfg.num_layers)]

    greedy = temperature == 0.0

    def prefill(p, b, tok, cache_list, key):
        pos = jnp.arange(S0)[None, :].repeat(B, 0)
        c = [(Tensor(k_), Tensor(v_)) for k_, v_ in cache_list]
        (logits, new_c), _ = functional_call(
            model, p, b, [Tensor(tok), Tensor(pos), c, Tensor(jnp.int32(0))],
            train=False)
        nxt = _sample(logits[:, -1], temperature, top_k, top_p, key)
        return nxt, new_c

    def decode(p, b, tok, cache_list, off, key):
        pos = off[None, None] + jnp.zeros((B, 1), jnp.int32)
        c = [(Tensor(k_), Tensor(v_)) for k_, v_ in cache_list]
        (logits, new_c), _ = functional_call(
            model, p, b, [Tensor(tok[:, None]), Tensor(pos), c, Tensor(off)],
            train=False)
        nxt = _sample(logits[:, -1], temperature, top_k, top_p, key)
        return nxt, new_c

    # cache the compiled programs on the model so repeated generate() calls
    # with the same shapes/sampling config reuse them (jit's cache is keyed
    # by closure identity, which would otherwise miss every call)
    jit_cache = model.__dict__.setdefault("_generation_jit_cache", {})
    cache_key = (B, S0, total, temperature, top_k, top_p)
    if cache_key in jit_cache:
        prefill_j, decode_j = jit_cache[cache_key]
    else:
        prefill_j = jax.jit(prefill)
        decode_j = jax.jit(decode, donate_argnums=(3,))
        jit_cache[cache_key] = (prefill_j, decode_j)

    key = jax.random.PRNGKey(seed if seed is not None else 0) if not greedy \
        else jax.random.PRNGKey(0)

    if use_cache:
        key, sub = jax.random.split(key)
        nxt, caches = prefill_j(params, buffers, ids, caches, sub)
        out_ids = [ids, np.asarray(nxt)[:, None]]
        finished = np.zeros(B, bool)
        if eos_token_id is not None:
            finished |= np.asarray(nxt) == eos_token_id
        for step in range(1, max_new_tokens):
            if eos_token_id is not None and finished.all():
                break
            key, sub = jax.random.split(key)
            nxt, caches = decode_j(params, buffers, nxt,
                                   caches, jnp.int32(S0 + step - 1), sub)
            tok = np.asarray(nxt)
            if eos_token_id is not None:
                tok = np.where(finished, eos_token_id, tok)
                finished |= tok == eos_token_id
            out_ids.append(tok[:, None].astype(np.int32))
        result = np.concatenate(out_ids, axis=1)
    else:
        # no-cache fallback: re-run the full (growing) sequence each step
        seq = ids
        for step in range(max_new_tokens):
            logits, _ = functional_call(
                model, params, buffers, [Tensor(seq)], train=False)
            key, sub = jax.random.split(key)
            nxt = np.asarray(_sample(logits[:, -1], temperature,
                                     top_k, top_p, sub))
            seq = np.concatenate([seq, nxt[:, None].astype(np.int32)], 1)
            if eos_token_id is not None and (nxt == eos_token_id).all():
                break
        result = seq
    if was_training:
        model.train()
    return Tensor(jnp.asarray(result))
