"""MoE all-to-all accounting: trace-time registration, per-step emission.

The compiled MoE fast path (incubate/.../moe_layer.py) issues its
dispatch/combine all-to-alls INSIDE the jitted train step — XLA gives the
host no per-collective timing, so the eager-collective counters
(`collective_{calls,bytes}_total{op="all_to_all"}`) and the StepTimeline's
comm intervals would miss MoE traffic entirely (exactly the gap ISSUE-14's
first satellite closes for the eager path in collective.py/moe_utils.py).

The split mirrors PR 7's offload instrumentation: the traced layer runs its
host code ONCE per trace, so it registers the per-step a2a volume here
(`note_a2a` — a plain list append, no metric emission inside the traced
region: GL006), and the host-side step wrapper
(`DistributedTrainStep._post_dispatch`) drains the registration at compile
time and re-emits it every executed step:

- `collective_calls_total{op="all_to_all"}` / `collective_bytes_total{...}`
  counters (the same family the eager collectives bump), and
- `comm_task(kind="a2a")` intervals for the overlap accounting. The
  interval duration is the ANALYTIC bytes/ICI-bandwidth estimate (marked
  `[est]` in the desc), anchored inside the step's compute span — the
  chunked fast path overlaps its a2a with expert GEMMs by construction, and
  XLA exposes no host-visible boundary to measure instead. Eager
  global_scatter/global_gather intervals (moe_utils.py) are real measured
  times; only compiled-path intervals are estimates (docs/MOE.md).
"""

from __future__ import annotations

import time

__all__ = ["note_a2a", "trace_marker", "drain_since", "emit_step",
           "estimated_seconds"]

# records appended at trace time by the MoE layer; drained by the train
# step right after its first (tracing) call. Single-threaded trace
# assumption, same as the dispatch cache. Markers are absolute sequence
# numbers so the bounded-growth eviction can never skew a drain slice.
_registered: list = []
_seq = [0]


def trace_marker() -> int:
    return _seq[0]


def note_a2a(desc: str, nbytes: int, calls: int = 1, overlapped: bool = True):
    """Register one per-step all-to-all volume (bytes are the analytic
    per-step estimate for the traffic GSPMD materializes). Called at TRACE
    time from inside the traced MoE forward — appends only; metric
    emission happens host-side in emit_step. `overlapped` states what the
    traced schedule arranges (chunked pipeline = True; a single unchunked
    exchange = False) so the estimated interval lands on the covered or
    exposed side of the overlap accounting accordingly."""
    _registered.append({"desc": str(desc), "bytes": int(nbytes),
                        "calls": int(calls), "overlapped": bool(overlapped),
                        "seq": _seq[0]})
    _seq[0] += 1
    if len(_registered) > 512:
        # eager-only callers (no train step ever drains) must not leak:
        # drop the oldest half — absolute seq markers stay valid
        del _registered[:256]


def drain_since(marker: int) -> tuple:
    """Hand the records registered at/after sequence `marker` to the
    caller (the train step that just traced them) and drop them from the
    shared list."""
    taken = tuple({k: v for k, v in r.items() if k != "seq"}
                  for r in _registered if r["seq"] >= marker)
    _registered[:] = [r for r in _registered if r["seq"] < marker]
    return taken


def estimated_seconds(nbytes: int) -> float:
    """bytes / per-chip ICI bandwidth, resolved through the planner's chip
    spec table (the same numbers the cost model's a2a term uses)."""
    try:
        import jax

        from .planner.cost_model import chip_specs

        _peak, _hbm, ici, _kind = chip_specs(jax.devices()[0])
    except Exception:  # graftlint: disable=GL003 spec probe must not break a train step; v4-class fallback below
        ici = 0.27e12
    return nbytes / max(ici, 1.0)


def emit_step(records, floor_ns: int = 0) -> None:
    """Host-side, once per executed step: bump the collective counters and
    fire comm_task observers with the estimated a2a intervals, anchored to
    reflect what the traced schedule arranges on device:

    - `overlapped` records (the chunked pipeline) anchor BACKWARD from now
      — inside the step's compute span, where _post_dispatch runs — and
      are floored at `floor_ns` (the caller's dispatch start, which the
      span opens just after), so a large estimate can never poke out ahead
      of the span and get miscounted as exposed;
    - unchunked records (PADDLE_TPU_MOE_A2A_CHUNKS=1, the A/B baseline)
      anchor FORWARD from now, past the span's imminent end — counted as
      exposed comm, so the chunking knob's effect is visible in
      overlap_fraction, not just wall clock."""
    if not records:
        return
    from . import comm_watchdog
    from .collective import record_collective_traffic

    for rec in records:
        record_collective_traffic("all_to_all", rec["bytes"], rec["calls"])
        now = time.perf_counter_ns()
        est = max(int(estimated_seconds(rec["bytes"]) * 1e9), 1)
        if rec.get("overlapped", True):
            t0, t1 = now - est, now
            if floor_ns:
                t0 = max(t0, min(floor_ns, t1 - 1))
        else:
            t0, t1 = now, now + est
        comm_watchdog.record_task(f"{rec['desc']}[est]", t0, t1, kind="a2a")
