"""Eager cross-process collectives for the multi-controller path.

Reference analog: the eager ProcessGroupNCCL/Gloo collectives
(paddle/phi/core/distributed/collective/process_group_nccl.cc, python API
python/paddle/distributed/communication/*.py) used by dygraph DataParallel.

TPU formulation: when `jax.distributed` is initialized with N > 1 processes,
each controller owns a slice of the global device set. Eager collectives are
built on jax's multihost utilities — process_allgather stages host-local
values into a global array and runs ONE compiled all-gather over ICI/DCN,
after which each process reduces/selects locally. Object collectives ride
the same path via pickle + uint8 staging. P2P send/recv rendezvous through
the native TCPStore (native/tcp_store.cc), the same store that bootstraps
the job — the analog of the reference's ncclSend/Recv over a store-brokered
ring (paddle/phi/core/distributed/store/tcp_store.h).

These paths are engaged by paddle_tpu.distributed.collective when
process_count() > 1; the compiled shard_map primitives remain the
performance path inside jitted programs.
"""

from __future__ import annotations

import pickle

import numpy as np


def _mu():
    from jax.experimental import multihost_utils

    return multihost_utils


def _jax():
    import jax

    return jax


def nprocs() -> int:
    try:
        return _jax().process_count()
    except (RuntimeError, ValueError, AttributeError):
        # no distributed backend initialized (or a jax too old to have the
        # query): by definition a single-controller process
        return 1


def rank() -> int:
    return _jax().process_index()


def allgather_values(v):
    """[nprocs, ...] stacked gather of a host-local array (one compiled
    all-gather over the global device set)."""
    return np.asarray(_mu().process_allgather(np.asarray(v), tiled=False))


def _reduce_rows(g, op):
    """Reduce a stacked [n, ...] array over its leading axis — the single
    reduce-op dispatch shared by every eager reduction path."""
    if op in ("sum",):
        return g.sum(axis=0)
    if op in ("max",):
        return g.max(axis=0)
    if op in ("min",):
        return g.min(axis=0)
    if op in ("prod",):
        return g.prod(axis=0)
    if op in ("avg",):
        return g.mean(axis=0)
    raise ValueError(f"unsupported reduce op {op!r}")


def allreduce_value(v, op="sum"):
    return _reduce_rows(allgather_values(v), op)


_group_seq: dict = {}


def cleanup_group_keys(store, gid=None):
    """Delete this rank's residual gar/ keys (the last two rounds per tag
    are kept live by the rolling cleanup in store_allreduce_group; without
    this, communicators used once or twice leak keys for the job's life).
    Called on group destroy / shutdown; gid=None sweeps every tag."""
    me = rank()
    for tag, seq in list(_group_seq.items()):
        if gid is not None and not tag.endswith(f"#g{gid}"):
            continue
        for s in (seq - 1, seq - 2):
            if s >= 0:
                try:
                    store.delete_key(f"gar/{tag}/{s}/{me}")
                except (KeyError, OSError, RuntimeError):
                    pass  # already deleted by a peer's sweep / store gone
        _group_seq.pop(tag, None)


def store_allreduce_group(store, v, ranks, op="sum", gid=None):
    """MEMBER-ONLY subgroup all-reduce over the TCPStore: each member posts
    its value under a sequenced group key, waits for all members' posts, and
    reduces. Non-members never participate (unlike the jax.distributed
    gather, which is a global collective), so member-only calls — the
    reference's new_group semantics — cannot deadlock the world, and
    different groups may reduce different shapes concurrently.

    Cleanup: a member's round-(s-2) key is deleted when it enters round s —
    by then every peer has posted round s-1, which required completing its
    round-(s-2) reads."""
    ranks = sorted(int(r) for r in ranks)
    # gid distinguishes two communicators with identical membership
    # (new_group called twice) — their reductions must not cross-mix
    tag = ",".join(map(str, ranks)) + (f"#g{gid}" if gid is not None else "")
    seq = _group_seq.get(tag, 0)
    _group_seq[tag] = seq + 1
    me = rank()
    store.set(f"gar/{tag}/{seq}/{me}", pickle.dumps(np.asarray(v)))
    keys = [f"gar/{tag}/{seq}/{r}" for r in ranks]
    store.wait(keys)
    vals = np.stack([pickle.loads(store.get(k)) for k in keys])
    if seq >= 2:
        try:
            store.delete_key(f"gar/{tag}/{seq - 2}/{me}")
        except (KeyError, OSError, RuntimeError):
            pass  # rolling cleanup is best-effort; reduction already done
    return _reduce_rows(vals, op)


def allgather_objects(obj):
    """Pickle-based object all-gather (reference all_gather_object,
    communication/all_gather.py)."""
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    n = int(payload.size)
    lens = allgather_values(np.asarray([n], np.int64))[:, 0]
    cap = int(lens.max())
    padded = np.zeros(cap, np.uint8)
    padded[:n] = payload
    rows = allgather_values(padded)
    return [pickle.loads(rows[i, : int(lens[i])].tobytes())
            for i in range(rows.shape[0])]


def broadcast_value(v, src):
    return allgather_values(v)[src]


def broadcast_objects(objs, src):
    return allgather_objects(objs)[src]


def barrier(name="paddle_tpu_barrier"):
    _mu().sync_global_devices(name)


def alltoall_single_value(v, n):
    """Equal-split single-tensor all-to-all: row-chunk j of every process's
    input lands on process j, concatenated in source order."""
    if v.shape[0] % n != 0:
        raise ValueError(
            f"alltoall_single: leading dim {v.shape[0]} not divisible by "
            f"world size {n}")
    g = allgather_values(v)  # [src, rows, ...]
    per = v.shape[0] // n
    r = rank()
    return np.concatenate(
        [g[j, r * per:(r + 1) * per] for j in range(n)], axis=0)


# --------------------------------------------------------------------------- #
# P2P over the native TCPStore
# --------------------------------------------------------------------------- #

_seq: dict = {}


def p2p_send(store, value, src, dst):
    key = f"p2p/{src}->{dst}/{_seq.setdefault((src, dst), 0)}"
    _seq[(src, dst)] += 1
    store.set(key, pickle.dumps(np.asarray(value)))


def p2p_recv(store, src, dst):
    key = f"p2p/{src}->{dst}/{_seq.setdefault((src, dst), 0)}"
    _seq[(src, dst)] += 1
    store.wait([key])
    out = pickle.loads(store.get(key))
    # consume: long-running send/recv loops must not grow the store
    try:
        store.delete_key(key)
    except (KeyError, OSError, RuntimeError):
        pass  # value already read; a leaked key only costs store memory
    return out
