"""TCPStore — the rendezvous key-value store, backed by the native C++ server
(native/tcp_store.cc; reference: paddle/phi/core/distributed/store/
tcp_store.h:121 and python create_or_get_global_tcp_store,
python/paddle/distributed/collective.py:342).

The master rank hosts the server; every rank (master included) connects as a
client. Used for multi-host bootstrap (before jax.distributed is up),
barriers, and elastic bookkeeping.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from ..framework import native

__all__ = ["TCPStore", "create_or_get_global_tcp_store"]


def _connect_with_backoff(lib, host, port, timeout_ms, io_timeout_ms):
    """Connect with bounded exponential backoff inside the overall timeout.

    Workers racing the master's bind at pod start is THE common elastic
    failure: on a restart every worker reconnects immediately while rank 0
    is still re-binding the server socket, so the first attempts get
    ECONNREFUSED and must retry, not die. Each attempt gets a FRESH socket:
    the native connect loop reuses its fd across connect() calls, and POSIX
    leaves a socket's state undefined after a failed connect — retrying on
    the same fd can spin to the deadline without ever succeeding even once
    the server is up. Returns (fd, attempts)."""
    deadline = time.monotonic() + timeout_ms / 1000.0
    delay = 0.05
    attempt = 0
    while True:
        attempt += 1
        remaining_ms = max(1, int((deadline - time.monotonic()) * 1000))
        # early attempts are short (ECONNREFUSED returns instantly while the
        # master hasn't bound yet); later attempts get 3s so a SYN dropped by
        # a full listen backlog can ride out the ~1s kernel retransmit
        per_attempt_ms = 500 if attempt <= 3 else 3000
        fd = lib.tcp_store_connect(host.encode(), int(port),
                                   min(remaining_ms, per_attempt_ms),
                                   io_timeout_ms)
        if fd >= 0:
            return fd, attempt
        if time.monotonic() + delay >= deadline:
            return fd, attempt
        if attempt == 1 or attempt % 8 == 0:
            print(f"[tcp_store] connect to {host}:{port} refused "
                  f"(attempt {attempt}), retrying for another "
                  f"{deadline - time.monotonic():.1f}s", file=sys.stderr)
        time.sleep(delay)
        delay = min(delay * 2, 1.0)


class TCPStore:
    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=30, io_timeout=900):
        """`timeout` bounds connect(); `io_timeout` bounds each blocking
        GET/WAIT (rendezvous waits legitimately run minutes while stragglers
        start up — reference default is 900s, tcp_store.h:121). A timed-out
        request desynchronizes the connection; treat it as fatal."""
        lib = native.load()
        if lib is None:
            raise RuntimeError(
                "native runtime unavailable (g++ build failed) — TCPStore "
                "requires native/libpaddle_tpu_native.so")
        self._lib = lib
        self._server = None
        self._host = host
        self._timeout_ms = int(timeout * 1000)
        if is_master:
            self._server = lib.tcp_store_server_start(int(port))
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
            port = lib.tcp_store_server_port(self._server)
        self._port = int(port)
        self._fd, attempts = _connect_with_backoff(
            lib, host, self._port, self._timeout_ms, int(io_timeout * 1000))
        if self._fd < 0:
            if self._server:
                lib.tcp_store_server_stop(self._server)
                # clear it: __del__→close() on this half-built instance
                # would otherwise stop (and free) the server a second time
                self._server = None
            raise RuntimeError(
                f"TCPStore: cannot connect to {host}:{port} after "
                f"{attempts} attempt(s) over {self._timeout_ms / 1000:.0f}s")
        self._lock = threading.Lock()

    @property
    def port(self):
        return self._port

    def set(self, key: str, value):
        if isinstance(value, str):
            value = value.encode()
        with self._lock:
            rc = self._lib.tcp_store_set(self._fd, key.encode(), value, len(value))
        if rc != 0:
            raise RuntimeError("TCPStore.set failed")

    def get(self, key: str) -> bytes:
        import ctypes

        cap = 1 << 20
        with self._lock:
            for _ in range(8):  # value may grow between round-trips
                buf = ctypes.create_string_buffer(cap)
                n = self._lib.tcp_store_get(self._fd, key.encode(), buf, cap)
                if n <= cap:
                    break
                cap = int(n)
            else:
                raise RuntimeError("TCPStore.get: value kept outgrowing buffer")
        if n < 0:
            raise RuntimeError("TCPStore.get failed")
        return buf.raw[:n]

    def tryget(self, key: str):
        """Non-blocking probe: value bytes, or None when the key is absent
        (used by the elastic liveness watcher — a blocking GET on a dead
        node's heartbeat would stall the whole watch loop)."""
        import ctypes

        if not hasattr(self._lib, "tcp_store_tryget"):
            raise RuntimeError(
                "native library predates tcp_store_tryget — rebuild with "
                "`make -C native`")
        cap = 1 << 20
        with self._lock:
            for _ in range(8):
                buf = ctypes.create_string_buffer(cap)
                n = self._lib.tcp_store_tryget(self._fd, key.encode(), buf, cap)
                if n <= cap:
                    break
                cap = int(n)
            else:
                raise RuntimeError("TCPStore.tryget: value kept outgrowing buffer")
        if n == -2:
            return None
        if n < 0:
            raise RuntimeError("TCPStore.tryget failed")
        return buf.raw[:n]

    def add(self, key: str, amount: int) -> int:
        import ctypes

        out = ctypes.c_longlong(0)
        with self._lock:
            rc = self._lib.tcp_store_add(self._fd, key.encode(), int(amount),
                                         ctypes.byref(out))
        if rc != 0:
            raise RuntimeError("TCPStore.add failed")
        return int(out.value)

    def wait(self, keys):
        if isinstance(keys, str):
            keys = [keys]
        for k in keys:
            with self._lock:
                rc = self._lib.tcp_store_wait(self._fd, k.encode())
            if rc != 0:
                raise RuntimeError(f"TCPStore.wait({k}) failed")

    def delete_key(self, key: str):
        with self._lock:
            self._lib.tcp_store_delete(self._fd, key.encode())

    def barrier(self, prefix: str, world_size: int, rank: int):
        """Counter barrier: every rank adds 1, waits for the done key."""
        n = self.add(f"{prefix}/count", 1)
        if n >= world_size:
            self.set(f"{prefix}/done", b"1")
        self.wait(f"{prefix}/done")

    def close(self):
        # getattr guards: __del__ reaches here for instances whose __init__
        # raised before these attributes existed (e.g. failed bind)
        if getattr(self, "_fd", -1) >= 0:
            self._lib.tcp_store_close(self._fd)
            self._fd = -1
        if getattr(self, "_server", None):
            self._lib.tcp_store_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception as e:
            # never raise out of GC, but never swallow silently either — a
            # failed close can leak the server socket and wedge the NEXT
            # rendezvous on this port
            try:
                print(f"[tcp_store] warning: close failed during GC: {e!r}",
                      file=sys.stderr)
            except Exception:  # graftlint: disable=GL003 interpreter teardown: stderr may already be gone
                pass


_global_store = None


def create_or_get_global_tcp_store():
    """reference: python/paddle/distributed/collective.py:342 — master from
    PADDLE_MASTER / MASTER_ADDR:PORT envs, rank 0 hosts."""
    global _global_store
    if _global_store is not None:
        return _global_store
    master = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR", "127.0.0.1")
    if ":" in master:
        host, port = master.rsplit(":", 1)
        port = int(port)
    else:
        host, port = master, int(os.environ.get("MASTER_PORT", "6170"))
    rank = int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", "0")))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", "1")))
    _global_store = TCPStore(host, port, is_master=(rank == 0), world_size=world)
    return _global_store
