"""ResilientTrainer — the recovery story wired end to end.

The survival organs already exist in isolation: the launcher's restart loop
(`launch/main.py`, PADDLE_RESTART_COUNT), ElasticManager heartbeats/liveness
(`fleet/elastic/manager.py`), the native comm watchdog
(`comm_watchdog.comm_task`), and the crash-safe sharded checkpoint
(`distributed/checkpoint/`). This module composes them into one driver:

    def step_fn(step):
        return train_step(x, y)          # one optimization step

    trainer = ResilientTrainer(step_fn, state_dict, "ckpts",
                               save_every=100, step_timeout=600)
    trainer.run(num_steps)

Per failure mode (docs/RESILIENCE.md):

* **Worker death / preemption** (incl. mid-save): the launcher respawns the
  pod; on entry `run()` restores from `latest_checkpoint`, which skips any
  uncommitted/corrupt save. Resume is automatic — the step offset comes from
  the checkpoint dir name, not from any state the dead process held.
* **Hang** (stuck collective / wedged host sync): every step runs inside
  `comm_watchdog.comm_task` with a deadline; the watchdog's monitor thread
  spills its report to PADDLE_WD_REPORT_FILE and (under the launcher) emits
  a FatalError line that the LogWatcher turns into a pod teardown + restart.
* **Node loss below min_np**: the elastic manager reports HOLD; the trainer
  pauses (keeps heartbeating) until the cluster refills or `hold_timeout`
  expires, and honors the RESTART reform signal after a rejoin.
* **Corrupt checkpoint on disk**: checksums reject it at restore and
  discovery falls back to the previous committed step.

Resume works across a changed (dp, mp) layout: `load_state_dict` reshards
saved shards onto each tensor's CURRENT placement, so a pod that comes back
with a different mesh factorization restores the same global state.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from ..observability import flight as _flight
from ..observability import metrics as _metrics
from ..observability import spans as _spans
from . import comm_watchdog, faults
from .checkpoint.manager import CheckpointManager

__all__ = ["ResilientTrainer", "run_with_recovery", "REFORM_EXIT_CODE"]

# a worker exits with this code to request an in-place pod re-form from the
# launcher's restart loop (distinct from faults.FAULT_EXIT_CODE and from
# ordinary crashes only for log readability — any nonzero code restarts)
REFORM_EXIT_CODE = 75

# Trainer metric handles, resolved per registry instance (HandleCache: a
# reset_default_registry() between two trainers must not strand the second
# one emitting into a dead registry).
_tm = _metrics.HandleCache(lambda reg: {
    "step": reg.histogram(
        "trainer_step_seconds", "ResilientTrainer wall time per step"),
    "ckpt": reg.histogram(
        "trainer_checkpoint_save_seconds",
        "checkpoint save latency (submit time for async saves)"),
    "hb_age": reg.gauge(
        "trainer_heartbeat_age_seconds",
        "seconds since this rank's last elastic heartbeat, sampled at "
        "step boundaries"),
    "wd": reg.counter(
        "trainer_watchdog_timeouts_total",
        "comm-watchdog deadline overruns observed by the trainer"),
})

# a persistently-slow job trips the watchdog every step; one full post-
# mortem per overrun would grow PADDLE_FLIGHT_FILE without bound
_WD_DUMP_MIN_INTERVAL_S = 60.0


class ResilientTrainer:
    """Drive `step_fn` for `num_steps` with periodic crash-safe checkpoints,
    elastic liveness, watchdog deadlines, and auto-resume.

    Parameters
    ----------
    step_fn : callable(step:int) -> loss
        One optimization step. Must mutate the same tensors that
        `state_dict` exposes (the usual TrainStep/optimizer contract).
    state_dict : dict | callable() -> dict
        name -> Tensor map covering model AND optimizer state; loaded in
        place on resume (reshard-on-load handles a changed mesh). A callable
        is re-evaluated at save/restore time for trainers that rebuild
        state views.
    ckpt_dir : str
        Checkpoint root (step_N dirs are managed under it).
    save_every : int
        Commit a checkpoint every N steps (and once at the end).
    keep_last_n : int
        Checkpoint rotation depth.
    async_save : bool
        Double-buffered background saves (single-process runs).
    elastic : ElasticManager | None
        When given: heartbeat each step, pause on HOLD, and exit with
        REFORM_EXIT_CODE on a reform signal if `exit_on_reform`.
    step_timeout : float | None
        Per-step watchdog deadline in seconds; enables the native comm
        watchdog when set (no-op if the native lib is unavailable).
    plan_path : str | None
        Where the canonical MeshPlan artifact lives (docs/PLANNER.md).
        Defaults to <ckpt_dir>/mesh_plan.json when planning is enabled, so
        the plan travels next to the checkpoints it describes.
    planner_cfg : dict | None
        Tuner-config dict (model_cfg, global_batch_size, grid
        restrictions...) enabling elastic plan adoption: on entry, if the
        stored plan's device count differs from the current one, `run()`
        re-plans ANALYTICALLY (no measurement — a restart is not the moment
        to burn a cluster on trials), persists the new MeshPlan next to the
        checkpoint, and reshard-on-load then restores the state onto the
        new mesh. `num_devices` inside it is overridden by the live count.
    on_plan : callable(MeshPlan) | None
        Called with the adopted plan BEFORE resume() — the hook where the
        caller rebuilds mesh/step/state for the plan's layout so
        `restore_latest` reshards the checkpoint onto it.
    plan_devices : int | None
        Device count to plan for (default: jax.device_count() — the count
        the restarted pod actually came back with).
    """

    def __init__(self, step_fn, state_dict, ckpt_dir, *, save_every=100,
                 keep_last_n=3, async_save=True, elastic=None,
                 step_timeout=None, hold_poll=1.0, hold_timeout=300.0,
                 exit_on_reform=False, log=None, plan_path=None,
                 planner_cfg=None, on_plan=None, plan_devices=None):
        self.step_fn = step_fn
        self._state_dict = state_dict
        self.manager = CheckpointManager(ckpt_dir, keep_last_n=keep_last_n,
                                         async_save=async_save)
        self.save_every = max(1, int(save_every))
        self.elastic = elastic
        self.step_timeout = step_timeout
        self.hold_poll = hold_poll
        self.hold_timeout = hold_timeout
        self.exit_on_reform = exit_on_reform
        self.plan_path = plan_path
        self.planner_cfg = planner_cfg
        self.on_plan = on_plan
        self.plan_devices = plan_devices
        self.plan = None
        self.plan_changed = False
        self.restart_count = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
        self.resumed_from = None
        self._log = log or (lambda msg: print(f"[resilience] {msg}",
                                              file=sys.stderr, flush=True))
        self._timeouts_seen = 0
        self._last_beat = None  # monotonic time of the latest heartbeat
        # monotonic time of the last overrun dump; None = never dumped
        # (0.0 would silently suppress the FIRST dump for the first
        # _WD_DUMP_MIN_INTERVAL_S of system uptime — monotonic starts at
        # boot, and a preempted VM restarts its job well inside a minute)
        self._last_wd_dump = None

    # ------------------------------------------------------------------ #

    def state(self):
        return self._state_dict() if callable(self._state_dict) \
            else self._state_dict

    def resume(self):
        """Restore the newest valid checkpoint; returns the first step to
        run (0 on a fresh start)."""
        step = self.manager.restore_latest(self.state())
        if step is None:
            if self.restart_count > 0:
                self._log(f"restart #{self.restart_count}: no valid "
                          "checkpoint found, starting from step 0")
            return 0
        self.resumed_from = step
        self._log(f"restart #{self.restart_count}: resumed from committed "
                  f"step {step} ({self.manager.path_for(step)})")
        return step + 1

    def _adopt_plan(self):
        """Elastic plan adoption (docs/PLANNER.md): load the MeshPlan next
        to the checkpoint; when the device count changed (or no plan exists
        yet) and a planner_cfg is available, re-plan analytically and
        persist the new artifact BEFORE resume(), so an elastic job
        migrates to a newly tuned mesh across a restart instead of merely
        surviving one (restore_latest reshards the state onto whatever
        mesh `on_plan` built from the adopted plan)."""
        from .planner import MeshPlan, analytic_plan, note_replan
        from .planner.layout import PLAN_FILENAME

        path = self.plan_path or os.path.join(self.manager.root,
                                              PLAN_FILENAME)
        ndev = self.plan_devices
        if ndev is None:
            import jax

            ndev = jax.device_count()
        plan = None
        if os.path.exists(path):
            try:
                plan = MeshPlan.load(path)
            except Exception as e:
                # a torn/corrupt plan is re-derivable state, unlike a
                # checkpoint: log and fall through to re-planning
                self._log(f"mesh plan at {path} unreadable "
                          f"({type(e).__name__}: {e}); re-planning")
        if plan is not None and plan.num_devices == ndev:
            self.plan = plan
            self._log(f"mesh plan: adopted {path} ({plan.describe()})")
        elif self.planner_cfg is None:
            self.plan = plan
            if plan is not None:
                self._log(
                    f"mesh plan: {path} was planned for {plan.num_devices} "
                    f"devices but {ndev} are live; no planner_cfg given, "
                    "keeping the stale plan (pass planner_cfg to re-plan)")
        else:
            old = plan.num_devices if plan is not None else None
            new_plan = analytic_plan(dict(self.planner_cfg,
                                          num_devices=ndev))
            new_plan.save(path)
            self.plan = new_plan
            self.plan_changed = True
            note_replan(old, ndev)
            _flight.get_recorder().note(
                "mesh_plan_adopted", old_devices=old, new_devices=ndev,
                mesh=dict(new_plan.mesh),
                predicted_step_time_s=new_plan.predicted_step_time_s)
            self._log(f"mesh plan: re-planned for {ndev} devices "
                      f"(was {old}) -> {path} ({new_plan.describe()})")
        if self.plan is not None and self.on_plan is not None:
            self.on_plan(self.plan)
        return self.plan

    # ------------------------------------------------------------------ #

    def _wait_ready(self, step):
        """Heartbeat + elastic gate: block while the cluster is below
        min_np, honor the reform signal after a rejoin."""
        if self.elastic is None:
            return
        from .fleet.elastic.manager import ElasticStatus

        self.elastic.heartbeat()
        self._last_beat = time.monotonic()
        status = self.elastic.watch()
        if status == ElasticStatus.HOLD:
            deadline = time.monotonic() + self.hold_timeout
            self._log(f"step {step}: cluster below min_np, holding "
                      f"(up to {self.hold_timeout}s)")
            while status == ElasticStatus.HOLD:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"elastic hold timed out after {self.hold_timeout}s "
                        "waiting for the cluster to refill")
                time.sleep(self.hold_poll)
                self.elastic.heartbeat()
                status = self.elastic.watch()
            self._log(f"step {step}: cluster refilled ({status})")
        # exit only on a genuine reform signal (a node left, or the cluster
        # refilled after a hold) — flagged by the manager's shared reform
        # generation. A partial-but-runnable cluster reports RESTART
        # steady-state as a scale-up hint; exiting on that would livelock:
        # every respawned worker would exit at its first step without ever
        # training.
        if (status == ElasticStatus.RESTART and self.exit_on_reform
                and getattr(self.elastic, "last_restart_was_reform", True)):
            self._log(f"step {step}: membership changed — exiting for an "
                      "in-place pod re-form")
            self.manager.wait()
            sys.exit(REFORM_EXIT_CODE)

    def _check_watchdog(self, step):
        n = comm_watchdog.timeout_count()
        if n > self._timeouts_seen:
            new = n - self._timeouts_seen
            self._timeouts_seen = n
            report = comm_watchdog.drain_report()
            # the spill thread may have drained it to the report file first;
            # either way the timeout itself is worth a log line
            self._log(f"step {step}: comm watchdog flagged a deadline "
                      f"overrun ({n} total)"
                      + (f"\n{report}" if report else ""))
            _tm.get()["wd"].inc(new)
            rec = _flight.get_recorder()
            rec.note("watchdog_timeout", step=step, total=n)
            # a deadline overrun is exactly the moment the last-N-steps ring
            # is worth persisting — the process may be torn down next. Rate-
            # limited: every overrun is still note()d above, but the full
            # dump repeats at most once per interval.
            now = time.monotonic()
            if (self._last_wd_dump is None
                    or now - self._last_wd_dump >= _WD_DUMP_MIN_INTERVAL_S):
                self._last_wd_dump = now
                rec.dump(reason=f"watchdog deadline overrun at step {step}")

    # ------------------------------------------------------------------ #

    def _start_heartbeat_thread(self):
        """Heartbeat on a cadence independent of step duration: a 15-minute
        first-step compile or a multi-GB sync save must not age this node's
        heartbeat past the liveness timeout and read as a death to peers."""
        stop = threading.Event()
        interval = getattr(self.elastic, "heartbeat_interval", 2.0)

        def _beat():
            while not stop.wait(interval):
                try:
                    self.elastic.heartbeat()
                    self._last_beat = time.monotonic()
                except Exception:
                    pass  # store hiccup: the next beat retries

        t = threading.Thread(target=_beat, daemon=True, name="elastic-hb")
        t.start()
        return stop

    def _save(self, step):
        t0 = time.perf_counter()
        self.manager.save(self.state(), step)
        dt = time.perf_counter() - t0
        _tm.get()["ckpt"].observe(dt)
        _flight.get_recorder().note("checkpoint_save", step=step,
                                    latency_s=round(dt, 6))

    def run(self, num_steps):
        """Train to `num_steps` total steps (counting completed pre-crash
        progress); returns a summary dict."""
        recorder = _flight.get_recorder()
        recorder.snapshot_metrics()  # dump reports deltas from this run
        # SIGTERM (preemption) + uncaught-exception post-mortems; chained
        # and idempotent, path from PADDLE_FLIGHT_FILE (set by the launcher)
        _flight.install_crash_handlers()
        if self.planner_cfg is not None or self.plan_path is not None:
            # adopt/re-plan the mesh BEFORE restore: on_plan rebuilds the
            # state views, then resume() reshards the checkpoint onto them
            self._adopt_plan()
        start = self.resume()
        recorder.note("trainer_start", start_step=start,
                      resumed_from=self.resumed_from,
                      restart_count=self.restart_count)
        if self.step_timeout is not None:
            comm_watchdog.enable()
            # only report overruns from THIS run, not a previous trainer's
            self._timeouts_seen = comm_watchdog.timeout_count()
        hb_stop = None
        if self.elastic is not None:
            hb_stop = self._start_heartbeat_thread()
        last_loss = None
        saved_at = start - 1
        step = start
        try:
            for step in range(start, num_steps):
                self._wait_ready(step)
                tl = _spans.active_timeline()
                if tl is not None:
                    tl.step_begin(step)
                t0 = time.perf_counter()
                # kind="step": a deadline-only region — the whole step is
                # not "communication", and counting it in the overlap
                # accounting would swamp the real comm intervals inside it
                with comm_watchdog.comm_task(f"train_step/{step}",
                                             self.step_timeout, kind="step"):
                    # inside the watchdog region: an injected stall here is
                    # exactly a step wedged in a collective
                    faults.fault_point("trainer.before_step")
                    last_loss = self.step_fn(step)
                tm = _tm.get()
                tm["step"].observe(time.perf_counter() - t0)
                if self._last_beat is not None:
                    tm["hb_age"].set(time.monotonic() - self._last_beat)
                self._check_watchdog(step)
                if tl is not None:
                    tl.step_end(extra={"restart_count": self.restart_count})
                if (step + 1) % self.save_every == 0:
                    self._save(step)
                    saved_at = step
            if num_steps > start and saved_at != num_steps - 1:
                self._save(num_steps - 1)
            self.manager.wait()
        except Exception as e:
            # the post-mortem the flight recorder exists for: last N step
            # timelines + metric deltas + watchdog peek, written before the
            # exception unwinds (SystemExit — the reform path — excluded)
            tl = _spans.active_timeline()
            if tl is not None:
                # the dying step never reached step_end; close it so the
                # dump's ring includes the step that killed the run
                tl.step_end(extra={"aborted": True,
                                   "restart_count": self.restart_count})
            recorder.dump(reason=f"trainer crash at step {step}: "
                                 f"{type(e).__name__}: {e}")
            raise
        finally:
            if hb_stop is not None:
                hb_stop.set()
        if self.elastic is not None:
            self.elastic.exit(completed=True)
        return {
            "start_step": start,
            "last_step": max(num_steps - 1, start - 1),
            "resumed_from": self.resumed_from,
            "restart_count": self.restart_count,
            "last_loss": last_loss,
        }


def run_with_recovery(step_fn, state_dict, ckpt_dir, num_steps, **kwargs):
    """Functional wrapper: build a ResilientTrainer and run it."""
    return ResilientTrainer(step_fn, state_dict, ckpt_dir, **kwargs).run(num_steps)
