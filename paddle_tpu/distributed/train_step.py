"""DistributedTrainStep: the hybrid-parallel compiled train step.

This is where the reference's whole runtime distributed machinery lands on
TPU: fleet.distributed_model + HybridParallelOptimizer + EagerReducer grad
bucketing + GroupSharded stages + mp/sp collectives (SURVEY §2.3) become ONE
jax.jit over the hybrid mesh with:

- params placed by NamedSharding from Parameter.dist_attr (TP layers set
  column/row specs; sharding stage 3 adds FSDP specs),
- optimizer states sharded over the `sharding` axis (ZeRO-1/2; reference
  DygraphShardingOptimizer dygraph_sharding_optimizer.py:54),
- batch sharded over (dp, sharding) — grad reduction becomes XLA's
  reduce-scatter/all-reduce over ICI, replacing EagerReducer bucketing
  (paddle/fluid/distributed/collective/reducer.cc),
- everything else (clip, AMP, update) inherited from jit.TrainStep.
"""

from __future__ import annotations

import os
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.core import Tensor
from ..jit import TrainStep, _unwrap_pytree
from . import env as _env

__all__ = ["DistributedTrainStep", "fsdp_spec", "shard_params_for_stage3",
           "host_memory_kind"]


def host_memory_kind(mesh):
    """The host-side memory kind this mesh's devices can address —
    "pinned_host" on TPU, "unpinned_host" on the CPU backend (where host
    and device memory coincide, so offload degenerates to a no-op
    placement but exercises the same code path), None when the runtime
    has no memories API at all."""
    try:
        dev = next(iter(mesh.devices.flat))
        kinds = {m.kind for m in dev.addressable_memories()}
    except Exception:  # graftlint: disable=GL003 probing an optional runtime API (pre-memories jaxlibs raise various types); the fallback IS the handling
        return "pinned_host"  # pre-memories probing: keep the TPU default
    for k in ("pinned_host", "unpinned_host"):
        if k in kinds:
            return k
    return None


def fsdp_spec(shape, axis="sharding", mesh=None, existing=None):
    """Shard the largest dim divisible by the axis size; replicate otherwise.
    Respects dims already taken by an existing spec (TP)."""
    mesh = mesh or _env.default_mesh()
    size = mesh.shape.get(axis, 1)
    if size <= 1 or not shape:
        return existing
    used = set()
    base = list(existing) if existing is not None else [None] * len(shape)
    while len(base) < len(shape):
        base.append(None)
    for i, s in enumerate(base):
        if s is not None:
            used.add(i)
            # axis already mapped (e.g. stage-3 params feeding _update_spec)
            if s == axis or (isinstance(s, tuple) and axis in s):
                return P(*base)
    # pick largest divisible unused dim
    cands = [
        (shape[i], i) for i in range(len(shape))
        if i not in used and shape[i] % size == 0 and shape[i] >= size
    ]
    if not cands:
        return P(*base) if existing is not None else None
    _, dim = max(cands)
    base[dim] = axis
    return P(*base)


def shard_params_for_stage3(model, axis="sharding", mesh=None):
    """Annotate every parameter with an FSDP spec (GroupShardedStage3 analog,
    reference: group_sharded_stage3.py:85)."""
    for _, p in model.named_parameters():
        existing = getattr(p, "dist_attr", None)
        p.dist_attr = fsdp_spec(tuple(p.shape), axis, mesh, existing)


def _bucket_tag(shardings):
    """Identity on a tuple of param values whose VJP applies the grad's
    reduce-scatter sharding constraint AT THE POINT the backward produces
    the bucket's cotangents — i.e. per-layer inside the backward, where XLA
    can overlap the collective with the remaining backward compute — rather
    than at the step-end consumption site. The optimization_barrier ties the
    bucket's grads together so their reduce-scatters issue as one group
    (EagerReducer bucket semantics, reference reducer.cc)."""

    @jax.custom_vjp
    def tag(*xs):
        return xs

    def tag_fwd(*xs):
        return xs, None

    def tag_bwd(_, gs):
        gs = jax.lax.optimization_barrier(tuple(gs))
        return tuple(jax.lax.with_sharding_constraint(g, s)
                     for g, s in zip(gs, shardings))

    tag.defvjp(tag_fwd, tag_bwd)
    return tag


class DistributedTrainStep(TrainStep):
    def __init__(self, model, loss_fn, optimizer, mesh=None,
                 input_specs=None, label_specs=None, sharding_stage=None,
                 offload=False, batch_axes=("dp", "sharding"),
                 comm_overlap=None, **kw):
        self.mesh = mesh or _env.default_mesh()
        _env.set_global_mesh(self.mesh)
        if sharding_stage is None:
            # group_sharded_parallel() annotates the optimizer
            sharding_stage = getattr(optimizer, "_sharding_stage", 0)
        self.sharding_stage = sharding_stage
        self.offload = offload or getattr(optimizer, "_sharding_offload", False)
        self.batch_axes = tuple(a for a in batch_axes if self.mesh.shape.get(a, 1) >= 1)
        self.input_specs = input_specs
        self.label_specs = label_specs
        # comm_overlap (default on; PADDLE_TPU_COMM_OVERLAP=0 restores the
        # exposed-collective step for A/B runs): in-backward reduce-scatter
        # bucket tags + in-program offload streaming + overlap-attributed
        # host transfers. Fixed at construction — it shapes the compiled
        # program, so an A/B needs two instances, not a flag flip.
        if comm_overlap is None:
            comm_overlap = os.environ.get("PADDLE_TPU_COMM_OVERLAP", "1") != "0"
        self.comm_overlap = bool(comm_overlap)
        self._host_kind = host_memory_kind(self.mesh)
        self._bucket_plan = None
        # MoE a2a records registered by THIS step's traces: __call__ marks
        # the registry before each dispatch, _post_dispatch claims whatever
        # that call's (re)trace registered — a shape-change retrace
        # replaces the emitted set instead of leaving it stale, and records
        # from another model's build can never land in this step's window
        from . import moe_comm as _moe_comm

        self._moe_a2a = None
        self._moe_pre = _moe_comm.trace_marker()
        self._moe_t0 = 0
        if sharding_stage == 3:
            shard_params_for_stage3(model, mesh=self.mesh)
        super().__init__(model, loss_fn, optimizer, **kw)
        self._place_state()

    # ------------------------------------------------------------------ #

    def _param_spec(self, name):
        p = self._state.params[name]
        spec = getattr(p, "dist_attr", None)
        if spec is None:
            spec = P()
        return spec

    def _opt_state_spec(self, name, state_key, arr):
        pspec = self._param_spec(name)
        pshape = tuple(self._state.params[name].shape)
        if tuple(arr.shape) == pshape:
            # moment tensors follow the param layout, plus ZeRO sharding
            if self.sharding_stage in (1, 2) and self.mesh.shape.get("sharding", 1) > 1:
                s = fsdp_spec(tuple(arr.shape), "sharding", self.mesh, pspec)
                return s if s is not None else pspec
            return pspec
        return P()

    def _update_spec(self, name):
        """The spec the optimizer update runs under: the grad's owner shard
        (reference: GroupShardedStage2 reduce-scatter-to-rank,
        group_sharded_stage2.py:47)."""
        pspec = self._param_spec(name)
        if self.sharding_stage in (2, 3) and self.mesh.shape.get("sharding", 1) > 1:
            s = fsdp_spec(tuple(self._state.params[name].shape),
                          "sharding", self.mesh, pspec)
            if s is not None:
                return s
        return pspec

    def _shard_grad(self, name, g):
        spec = self._update_spec(name)
        if spec == self._param_spec(name):
            return g
        # XLA lowers this to a reduce-scatter over ICI instead of the
        # all-reduce the replicated-grad path would use
        return jax.lax.with_sharding_constraint(g, self._sharding(spec))

    def _shard_param_for_update(self, name, pv):
        spec = self._update_spec(name)
        if spec == self._param_spec(name):
            return pv
        return jax.lax.with_sharding_constraint(pv, self._sharding(spec))

    def _restore_param(self, name, np_):
        # all-gather fresh shards back to the param layout (stage 2; stage 3
        # params stay sharded because _param_spec == _update_spec there)
        return jax.lax.with_sharding_constraint(
            np_, self._sharding(self._param_spec(name)))

    # -- comm/compute overlap: in-backward grad reduce-scatter ----------- #

    def _grad_bucket_plan(self):
        """[(param names, bucket tag fn)] in REVERSE topological order (the
        order the backward pass produces grads), bucketed by cumulative
        bytes (PADDLE_TPU_RS_BUCKET_MB, default 25 — the EagerReducer
        bucket size). Only params whose update layout differs from their
        param layout are tagged; the rest have no reduce-scatter to place."""
        if self._bucket_plan is not None:
            return self._bucket_plan
        plan = []
        if (self.comm_overlap and self.sharding_stage in (2, 3)
                and self.mesh.shape.get("sharding", 1) > 1):
            cap = float(os.environ.get("PADDLE_TPU_RS_BUCKET_MB", "25")) * 1e6
            names, shards, size = [], [], 0.0
            for name in reversed(list(self._state.params)):
                spec = self._update_spec(name)
                if spec == self._param_spec(name):
                    continue  # grad already produced in its update layout
                p = self._state.params[name]
                names.append(name)
                shards.append(self._sharding(spec))
                size += (int(np.prod(p.shape))
                         * jnp.dtype(p.dtype).itemsize)
                if size >= cap:
                    plan.append((tuple(names), _bucket_tag(tuple(shards))))
                    names, shards, size = [], [], 0.0
            if names:
                plan.append((tuple(names), _bucket_tag(tuple(shards))))
        self._bucket_plan = plan
        return plan

    def _tag_grad_buckets(self, p):
        plan = self._grad_bucket_plan()
        if not plan:
            return p
        p = dict(p)
        for names, tag in plan:
            for name, v in zip(names, tag(*(p[n] for n in names))):
                p[name] = v
        return p

    # -- comm/compute overlap: offload state streaming ------------------- #

    def _offload_streaming(self):
        """In-program host<->device streaming of the optimizer states: the
        compiled program itself device_puts them in at the start and back to
        host memory per-param after each update, so XLA overlaps the copies
        with compute instead of the host serializing them around the step."""
        return (self.offload and self.comm_overlap
                and self._host_kind is not None)

    def _fetch_opt_states(self, opt_states):
        if not self._offload_streaming():
            return opt_states
        return {
            k: {sk: jax.device_put(
                    sv, self._sharding(self._opt_state_spec(k, sk, sv)))
                if hasattr(sv, "shape") else sv
                for sk, sv in st.items()}
            for k, st in opt_states.items()
        }

    def _emit_opt_state(self, name, st):
        if not self._offload_streaming():
            return st
        return {sk: jax.device_put(
                    sv, self._sharding(self._opt_state_spec(name, sk, sv),
                                       host=True))
                if hasattr(sv, "shape") else sv
                for sk, sv in st.items()}

    def _post_dispatch(self):
        # non-streaming offload with overlap on: issue the d2h restream
        # INSIDE the compute span, while the dispatched program is still
        # executing — the device_puts queue behind the step's outputs, so
        # they pipeline against the tail of the computation instead of
        # running as a post-step barrier
        if self.offload and self.comm_overlap and not self._offload_streaming():
            from . import comm_watchdog

            with comm_watchdog.comm_task("offload/d2h", kind="comm"):
                self._move_opt_states(host=True)
        # MoE expert-parallel a2a accounting: the traced MoE fast path
        # registered its per-step dispatch/combine all-to-all volume during
        # this program's trace (moe_comm.note_a2a); any (re)trace inside
        # THIS call's window replaces the claimed set, and every call
        # re-emits it as collective_{calls,bytes}_total{op="all_to_all"} +
        # estimated comm_task(kind="a2a") intervals — anchored inside this
        # step's compute span (floored at the dispatch start), mirroring
        # how the chunked schedule overlaps them on device.
        from . import moe_comm as _moe_comm

        fresh = _moe_comm.drain_since(self._moe_pre)
        if fresh or self._moe_a2a is None:
            self._moe_a2a = fresh
        _moe_comm.emit_step(self._moe_a2a, floor_ns=self._moe_t0)

    def _sharding(self, spec, host=False):
        kind = self._host_kind if host else None
        return NamedSharding(self.mesh, spec if spec is not None else P(),
                             memory_kind=kind)

    def _place_state(self):
        """device_put params/opt-states/buffers with their shardings; with
        offload=True the optimizer states (and master weights) live in host
        memory between steps (reference: GroupSharded cpu offload,
        group_sharded_stage3.py offload params / sharding_optimizer)."""
        for k, v in self.params.items():
            self.params[k] = jax.device_put(v, self._sharding(self._param_spec(k)))
        for k, st in self.opt_states.items():
            for sk, sv in st.items():
                if hasattr(sv, "shape"):
                    st[sk] = jax.device_put(
                        sv, self._sharding(self._opt_state_spec(k, sk, sv),
                                           host=self.offload)
                    )
        for k, v in self.buffers.items():
            self.buffers[k] = jax.device_put(v, self._sharding(P()))

    def _batch_spec(self, arr):
        axes = tuple(a for a in self.batch_axes if self.mesh.shape.get(a, 1) > 1)
        if not axes or arr.ndim == 0:
            return P()
        n = int(np.prod([self.mesh.shape[a] for a in axes]))
        if arr.shape[0] % n != 0:
            return P()
        return P(axes if len(axes) > 1 else axes[0])

    def _move_opt_states(self, host):
        for k, st in self.opt_states.items():
            for sk, sv in st.items():
                if hasattr(sv, "shape"):
                    st[sk] = jax.device_put(
                        sv, self._sharding(self._opt_state_spec(k, sk, sv),
                                           host=host))

    def __call__(self, inputs, labels):
        from . import comm_watchdog

        streaming = self._offload_streaming()
        if self.offload and not streaming:
            # host-side move barrier (legacy / no-memories-API path): stream
            # optimizer states host→device for the update (reference:
            # GroupSharded offload=True keeping the moments on CPU between
            # steps, group_sharded_stage3.py offload). With streaming the
            # compiled program carries these transfers itself.
            with comm_watchdog.comm_task("offload/h2d", kind="comm"):
                self._move_opt_states(host=False)
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        raw_in = [_unwrap_pytree(i if isinstance(i, Tensor) else Tensor(jnp.asarray(np.asarray(i)))) for i in inputs]
        raw_lb = [_unwrap_pytree(l if isinstance(l, Tensor) else Tensor(jnp.asarray(np.asarray(l)))) for l in labels]
        in_specs = self.input_specs or [self._batch_spec(a) for a in raw_in]
        lb_specs = self.label_specs or [self._batch_spec(a) for a in raw_lb]
        # the previous step's program still executing (async dispatch) means
        # this step's input h2d is genuinely pipelined behind device compute.
        # The credit is conservative: is_ready() (a non-blocking peek) must
        # report busy BOTH before and after the placement window, or no
        # compute span is recorded — a program finishing mid-window drops
        # the whole credit rather than inflating overlap_fraction.
        prev = getattr(self, "_inflight", None)
        pipelined = (self.comm_overlap and prev is not None
                     and hasattr(prev, "is_ready") and not prev.is_ready())
        with comm_watchdog.comm_task("h2d/inputs", kind="comm"):
            t0 = time.perf_counter_ns() if pipelined else 0
            placed_in = [jax.device_put(a, self._sharding(s)) for a, s in zip(raw_in, in_specs)]
            placed_lb = [jax.device_put(a, self._sharding(s)) for a, s in zip(raw_lb, lb_specs)]
            if pipelined and not prev.is_ready():
                from ..observability import spans as _obs_spans

                _obs_spans.record_span("train_step/prev_step_inflight",
                                       t0, time.perf_counter_ns(),
                                       kind="compute")
        # a2a-accounting window for this dispatch (see _post_dispatch):
        # registry mark scopes retraces to this call; the timestamp floors
        # the estimated intervals inside the step's compute span
        from . import moe_comm as _moe_comm

        self._moe_pre = _moe_comm.trace_marker()
        self._moe_t0 = time.perf_counter_ns()
        loss = super().__call__([Tensor(a) for a in placed_in], [Tensor(a) for a in placed_lb])
        self._inflight = loss._value
        if self.offload and not streaming and not self.comm_overlap:
            # pre-change semantics: the d2h restream runs as an exposed
            # post-step barrier (comm_overlap=True issues it inside the
            # compute span via _post_dispatch instead)
            with comm_watchdog.comm_task("offload/d2h", kind="comm"):
                self._move_opt_states(host=True)
        return loss
