"""Fault-injection points for the resilience test harness.

Production code calls ``fault_point("name")`` at the instants a real failure
would land (mid-checkpoint-save, before commit, inside a train step). With no
configuration the call is a near-free no-op; tests (tools/fault_inject.py and
the ``fault_injector`` pytest fixture) arm points through the env var

    PADDLE_FAULT_INJECT="point:action[:arg][@n][,point2:action2...]"

Actions:
    kill      os._exit(FAULT_EXIT_CODE) — simulates SIGKILL/preemption (no
              atexit, no cleanup, exactly what a preempted TPU host looks like)
    exc       raise FaultInjected (an in-process crash the caller may catch)
    sleep:S   block S seconds — simulates a hang for the comm watchdog

``@n`` trips the point only on its n-th hit (1-based, counted per process),
so e.g. ``ckpt.before_commit:kill@2`` lets the first checkpoint commit and
kills the second mid-save.
"""

from __future__ import annotations

import os
import time

__all__ = ["FaultInjected", "fault_point", "reset", "FAULT_EXIT_CODE"]

# distinct from any exit code the trainers use, so tests can assert the death
# really came from the injected fault
FAULT_EXIT_CODE = 43

_parsed_env = None  # (env string, {point: (action, arg, nth)})
_hit_counts: dict = {}


class FaultInjected(RuntimeError):
    """Raised by an armed ``exc`` fault point."""


def reset():
    """Clear hit counters and the parsed-spec cache. Test fixtures call this
    on arm/disarm: the env-string cache can't see unset→re-set of the SAME
    spec (no fault_point call in between re-parses), so a @n counter from an
    earlier arm would otherwise survive and suppress the new one."""
    global _parsed_env
    _parsed_env = None
    _hit_counts.clear()


def _spec():
    global _parsed_env
    raw = os.environ.get("PADDLE_FAULT_INJECT", "")
    if _parsed_env is not None and _parsed_env[0] == raw:
        return _parsed_env[1]
    _hit_counts.clear()  # re-arming starts a fresh @n count
    spec = {}
    for entry in filter(None, (e.strip() for e in raw.split(","))):
        nth = 1
        if "@" in entry:
            entry, n = entry.rsplit("@", 1)
            nth = int(n)
        parts = entry.split(":")
        if len(parts) < 2:
            continue
        point, action = parts[0], parts[1]
        arg = parts[2] if len(parts) > 2 else None
        spec[point] = (action, arg, nth)
    _parsed_env = (raw, spec)
    return spec


def fault_point(name: str):
    """Trip the named injection point if armed; no-op otherwise."""
    spec = _spec()
    if name not in spec:
        return
    action, arg, nth = spec[name]
    _hit_counts[name] = _hit_counts.get(name, 0) + 1
    if _hit_counts[name] != nth:
        return
    if action == "kill":
        os._exit(FAULT_EXIT_CODE)
    if action == "exc":
        raise FaultInjected(f"fault point '{name}' tripped")
    if action == "sleep":
        time.sleep(float(arg or "1"))
