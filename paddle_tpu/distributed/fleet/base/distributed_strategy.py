"""DistributedStrategy (reference: python/paddle/distributed/fleet/base/
distributed_strategy.py:284 backed by distributed_strategy.proto; the
hybrid_configs property at :1892 carries dp/mp/pp/sharding/sep degrees).

Plain-python config object here — the protobuf serialization layer adds
nothing on a single-controller runtime."""

from __future__ import annotations

import copy

__all__ = ["DistributedStrategy"]

_DEFAULT_HYBRID = {
    "dp_degree": 1,
    "mp_degree": 1,
    "pp_degree": 1,
    "sharding_degree": 1,
    "sep_degree": 1,
    "order": ["dp", "pp", "sharding", "sep", "mp"],
    "mp_configs": {},
    "pp_configs": {
        "micro_batch_size": 1,
        "accumulate_steps": 1,
        "schedule_mode": "1F1B",
        "p2p_cache_shape": True,
        "enable_partial_send_recv": True,
    },
    "sharding_configs": {
        "tensor_fusion": False,
        "comm_overlap": False,
        "split_param": False,
    },
}


class DistributedStrategy:
    def __init__(self):
        self._hybrid_configs = copy.deepcopy(_DEFAULT_HYBRID)
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0,
            "custom_white_list": [],
            "custom_black_list": [],
            "use_pure_fp16": False,
            "use_bf16": True,
            "level": "O1",
        }
        self.recompute = False
        self.recompute_configs = {"checkpoints": [], "enable_offload": False}
        self.sharding = False
        self.sharding_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.lamb = False
        self.lamb_configs = {}
        self.dgc = False
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.sync_nccl_allreduce = True

    @property
    def hybrid_configs(self):
        return self._hybrid_configs

    @hybrid_configs.setter
    def hybrid_configs(self, configs):
        for k, v in configs.items():
            if isinstance(v, dict) and k in self._hybrid_configs:
                self._hybrid_configs[k].update(v)
            else:
                self._hybrid_configs[k] = v

    def __repr__(self):
        return f"DistributedStrategy(hybrid_configs={self._hybrid_configs})"
