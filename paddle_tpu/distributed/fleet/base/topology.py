"""Hybrid N-D topology (reference: python/paddle/distributed/fleet/base/
topology.py — CommunicateTopology :70 with dims ordered
[data, pipe, sharding, sep, model] :73-80, HybridCommunicateGroup :189).

On TPU the rank grid IS the device mesh: axes map 1:1 onto
jax.sharding.Mesh axes (dp, pp, sharding, sep, mp). Per-axis "comm groups"
are Group objects naming mesh axes; the collectives they imply are compiled
into programs rather than created as NCCL rings."""

from __future__ import annotations

import itertools

import numpy as np

from ... import collective as coll
from ... import env as _env

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep", "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = itertools.product(*[range(d) for d in dims])
        self._world = int(np.prod(dims))
        self._coord_to_rank = {}
        self._rank_to_coord = {}
        for rank, coord in enumerate(itertools.product(*[range(d) for d in dims])):
            self._coord_to_rank[coord] = rank
            self._rank_to_coord[rank] = coord

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[n] for n in self._parallel_names)
        return self._coord_to_rank[coord]

    def get_coord(self, rank):
        return self._rank_to_coord[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on axis_name equals index."""
        ax = self._parallel_names.index(axis_name)
        return sorted(r for r, c in self._rank_to_coord.items() if c[ax] == index)

    def get_comm_list(self, axis_name):
        """List of rank-groups along axis_name (one per setting of the other
        axes) — what the reference turns into one comm ring each."""
        ax = self._parallel_names.index(axis_name)
        others = [range(d) for i, d in enumerate(self._dims) if i != ax]
        groups = []
        for combo in itertools.product(*others):
            ranks = []
            for v in range(self._dims[ax]):
                coord = list(combo)
                coord.insert(ax, v)
                ranks.append(self._coord_to_rank[tuple(coord)])
            groups.append(ranks)
        return groups

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = list(self._rank_to_coord[global_rank])
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self._coord_to_rank[tuple(coord)]


_NAME_TO_AXIS = {"data": "dp", "pipe": "pp", "sharding": "sharding", "sep": "sep", "model": "mp"}


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.global_rank = _env.get_rank()
        self.nranks = topology.world_size()
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep")
        self._mp_degree = topology.get_dim("model")
        # mesh with reference axis order
        self.mesh = _env.build_mesh(
            dp=self._dp_degree, pp=self._pp_degree, sharding=self._sharding_degree,
            sep=self._sep_degree, mp=self._mp_degree,
        )
        coord = topology.get_coord(min(self.global_rank, self.nranks - 1))
        self._coord = dict(zip(topology.get_hybrid_group_names(), coord))
        self._groups = {}
        for name in topology.get_hybrid_group_names():
            axis = _NAME_TO_AXIS[name]
            comm = topology.get_comm_list(name)
            my = next((g for g in comm if self.global_rank in g), comm[0])
            self._groups[name] = coll.Group(ranks=my, axis_names=(axis,), mesh=self.mesh)
        # fused-axis groups (reference topology.py:256-260): all ranks that
        # share this rank's coordinates on every OTHER axis
        self._dp_sharding_group = self._fused_group(("data", "sharding"), ("dp", "sharding"))
        self._dp_sep_group = self._fused_group(("data", "sep"), ("dp", "sep"))

    def _fused_group(self, names, axes):
        fixed = [n for n in self._topo.get_hybrid_group_names() if n not in names]
        ranks = sorted(
            r for r in range(self.nranks)
            if all(
                self._topo.get_coord(r)[self._topo.get_hybrid_group_names().index(n)]
                == self._coord[n]
                for n in fixed
            )
        )
        return coll.Group(ranks=ranks, axis_names=axes, mesh=self.mesh)

    # -- topology info (reference HybridCommunicateGroup API) -------------- #

    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and self._sharding_degree == 1 and self._sep_degree == 1:
            return "data_parallel" if self._dp_degree > 1 else "single"
        if self._pp_degree > 1:
            return "pipeline_parallel"
        if self._sharding_degree > 1 and self._mp_degree == 1:
            return "sharding_parallel"
        if self._sep_degree > 1 and self._mp_degree == 1:
            return "segment_parallel"
        return "tensor_parallel"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._coord["data"]

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._groups["data"]

    def get_data_parallel_group_src_rank(self):
        return self._groups["data"].ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._coord["model"]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._groups["model"]

    def get_model_parallel_group_src_rank(self):
        return self._groups["model"].ranks[0]

    # pipeline
    def get_stage_id(self):
        return self._coord["pipe"]

    def get_pipe_parallel_rank(self):
        return self._coord["pipe"]

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._groups["pipe"]

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._coord["sharding"]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._groups["sharding"]

    def get_sharding_parallel_group_src_rank(self):
        return self._groups["sharding"].ranks[0]

    # sep
    def get_sep_parallel_rank(self):
        return self._coord["sep"]

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._groups["sep"]

    def get_dp_sep_parallel_group(self):
        return self._dp_sep_group

    def get_pipe_parallel_peers(self):
        return self._groups["pipe"].ranks
