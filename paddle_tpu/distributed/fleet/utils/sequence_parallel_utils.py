"""Sequence-parallel utilities.

Reference: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py —
ScatterOp/GatherOp/AllGatherOp/ReduceScatterOp PyLayers (:85-127),
ColumnSequenceParallelLinear (:429), RowSequenceParallelLinear (:564),
register_sequence_parallel_allreduce_hooks (:192).

TPU-native: the scatter/gather pairs are sharding constraints on the sequence
dim over the mp axis; GSPMD inserts all-gather/reduce-scatter at the TP
boundary exactly where the reference places explicit PyLayers. The explicit
PyLayer classes are kept for API parity and for eager single-device use,
where they are identity maps (world=1) with the correct backward duals.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from ....autograd import PyLayer
from ... import env as _env
from ..layers.mpu.mp_layers import ColumnParallelLinear, RowParallelLinear, _constrain

__all__ = [
    "ScatterOp",
    "GatherOp",
    "AllGatherOp",
    "ReduceScatterOp",
    "identity_in_mp",
    "mark_as_sequence_parallel_parameter",
    "is_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
    "ColumnSequenceParallelLinear",
    "RowSequenceParallelLinear",
]

_SP_MARK = "sequence_parallel"


def _seq_spec(ndim, seq_axis=1):
    spec = [None] * ndim
    spec[seq_axis] = "mp"
    return P(*spec)


class ScatterOp(PyLayer):
    """Split activation along seq dim across mp (fwd) / all-gather (bwd)."""

    @staticmethod
    def forward(ctx, x, axis=1):
        ctx.axis = axis
        return _constrain(x, _seq_spec(x.ndim, axis))

    @staticmethod
    def backward(ctx, g):
        return _constrain(g, P(*([None] * g.ndim)))


class GatherOp(PyLayer):
    """All-gather along seq dim (fwd) / scatter (bwd)."""

    @staticmethod
    def forward(ctx, x, axis=1):
        ctx.axis = axis
        return _constrain(x, P(*([None] * x.ndim)))

    @staticmethod
    def backward(ctx, g):
        return _constrain(g, _seq_spec(g.ndim, ctx.axis))


class AllGatherOp(PyLayer):
    @staticmethod
    def forward(ctx, x):
        return _constrain(x, P(*([None] * x.ndim)))

    @staticmethod
    def backward(ctx, g):
        return _constrain(g, _seq_spec(g.ndim, 1))


class ReduceScatterOp(PyLayer):
    @staticmethod
    def forward(ctx, x):
        return _constrain(x, _seq_spec(x.ndim, 1))

    @staticmethod
    def backward(ctx, g):
        return _constrain(g, P(*([None] * g.ndim)))


def identity_in_mp(x):
    return x


def mark_as_sequence_parallel_parameter(param):
    setattr(param, "_sp_mark", True)


def is_sequence_parallel_parameter(param):
    return getattr(param, "_sp_mark", False)


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1, fuse=False):
    """reference :192 — LN params used inside SP regions need grad allreduce
    over mp. Under the compiled train step GSPMD already sums replicated-param
    grads across mp; kept as an explicit no-op hook registry for API parity
    in eager mode."""
    return []


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """reference :429 — input arrives sequence-sharded; all-gather then
    column-parallel matmul. Expressed as: constrain input to seq-sharded,
    let GSPMD gather at the matmul."""

    def forward(self, x):
        x = _constrain(x, _seq_spec(x.ndim, 1))
        out = F.linear(x, self.weight, self.bias)
        spec = [None] * out.ndim
        spec[-1] = "mp"
        return _constrain(out, P(*spec))


class RowSequenceParallelLinear(RowParallelLinear):
    """reference :564 — row-parallel matmul then reduce-scatter onto seq dim."""

    def forward(self, x):
        spec = [None] * x.ndim
        spec[-1] = "mp"
        x = _constrain(x, P(*spec))
        out = F.linear(x, self.weight, self.bias)
        return _constrain(out, _seq_spec(out.ndim, 1))
