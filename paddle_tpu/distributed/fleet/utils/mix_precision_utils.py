"""Mixed-precision master-grad utilities (reference:
python/paddle/distributed/fleet/utils/mix_precision_utils.py —
MixPrecisionLayer :35 keeping fp32 main_grad per param via grad hooks,
MixPrecisionOptimizer :97 stepping on the main grads).

TPU formulation: the compiled DistributedTrainStep already keeps f32 master
weights/grads when amp_level='O2' (jit/TrainStep multi-precision path), so
these wrappers serve the EAGER loop: the layer registers a grad hook that
accumulates every incoming low-precision gradient into an f32 `main_grad`,
and the optimizer steps on those f32 grads."""

from __future__ import annotations

import jax.numpy as jnp

import paddle_tpu.nn as nn
from ....framework.core import Tensor

__all__ = ["MixPrecisionLayer", "MixPrecisionOptimizer"]


class MixPrecisionLayer(nn.Layer):
    """reference mix_precision_utils.py:35."""

    def __init__(self, layers, dtype="float16"):
        super().__init__()
        self._layers = layers
        self._dtype = dtype
        for _, param in layers.named_parameters():
            param.main_grad = None
            param._register_grad_hook_handle = param.register_hook(
                self._make_hook(param))

    @staticmethod
    def _make_hook(param):
        def hook(grad):
            g32 = grad._value.astype(jnp.float32)
            if param.main_grad is None:
                param.main_grad = Tensor(g32, stop_gradient=True)
            else:
                param.main_grad = Tensor(param.main_grad._value + g32,
                                         stop_gradient=True)
            return grad

        return hook

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)

    def parameters(self, *a, **kw):
        return self._layers.parameters(*a, **kw)

    def named_parameters(self, *a, **kw):
        return self._layers.named_parameters(*a, **kw)


class MixPrecisionOptimizer:
    """reference mix_precision_utils.py:97 — steps on the f32 main grads."""

    def __init__(self, optimizer):
        self._inner_opt = optimizer
        # without f32 master weights the inner update would immediately cast
        # the f32 main grad back to the param dtype (lr*g below bf16 epsilon
        # silently stalls training) — master weights are the point here
        optimizer._multi_precision = True

    def __getattr__(self, item):
        return getattr(self.__dict__["_inner_opt"], item)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        # must route through the wrapper's step: the inherited minimize
        # would call the inner step and bypass the main_grad swap
        loss.backward()
        self.step()
        self.clear_grad()

    def step(self):
        opt = self._inner_opt
        swapped = []
        for p in opt._parameter_list or []:
            params = p["params"] if isinstance(p, dict) else [p]
            for q in params:
                mg = getattr(q, "main_grad", None)
                if mg is not None:
                    swapped.append((q, q.grad))
                    q.grad = mg
        try:
            opt.step()
        finally:
            for q, g in swapped:
                q.grad = g

    def clear_grad(self, set_to_zero=True):
        opt = self._inner_opt
        for p in opt._parameter_list or []:
            params = p["params"] if isinstance(p, dict) else [p]
            for q in params:
                if getattr(q, "main_grad", None) is not None:
                    q.main_grad = None
        opt.clear_grad()
