"""PipelineParallel wrapper (reference: python/paddle/distributed/fleet/
meta_parallel/pipeline_parallel.py — PipelineParallel :242,
forward_backward_pipeline :684 (1F1B), train_batch :940, interleaved VPP
:1308; schedule selection fleet/model.py:160-185).

TPU-native execution model: in the reference, pp ranks are processes
exchanging activations over NCCL p2p in a hand-scheduled 1F1B loop. Under a
single-controller mesh the schedule is *compiled*: when the strategy's
pp_configs select "1F1B" and the PipelineLayer's stages are uniform (same
per-stage parameter structure, activation-preserving bodies — the
transformer-block case), train_batch stacks the per-stage parameters and
drives the microbatches through paddle_tpu.parallel.pipeline_1f1b — one XLA
program whose every tick runs a forward AND a backward microbatch per stage,
accumulating grads in-schedule. The resulting stacked grads are scattered
back onto the eager Parameters and the optimizer steps as usual.

Stages that cannot ride a uniform SPMD program (heterogeneous layer stacks,
shared embeddings, activation-shape changes) fall back to the sequential
micro-batch accumulation loop ("FThenB" semantics) — same numerics, no
overlap.
"""

from __future__ import annotations

import numpy as np

from ....framework.core import Parameter, Tensor, no_grad
from . import pp_layers

__all__ = ["PipelineParallel"]


class PipelineParallel:
    def __init__(self, layers, hcg, strategy):
        if not isinstance(layers, pp_layers.PipelineLayer):
            raise TypeError("PipelineParallel requires a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pp_cfg = strategy.hybrid_configs.get("pp_configs", {})
        self.micro_batch_size = strategy.hybrid_configs.get("micro_batch_size") or \
            pp_cfg.get("micro_batch_size", 1)
        self.accumulate_steps = pp_cfg.get("accumulate_steps", 1)
        self.schedule_mode = pp_cfg.get("schedule_mode", "1F1B")
        self._compiled = None      # lazily-built compiled 1F1B closure
        self._compiled_state = 0   # 0 unknown / 1 available / -1 infeasible

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    # ------------------------------------------------------------------ #
    # compiled 1F1B route
    # ------------------------------------------------------------------ #

    def _build_compiled(self):
        """Return a (x, y, n_micro) -> (loss, set_grads_fn) runner using the
        compiled 1F1B schedule, or None when the layer structure can't ride
        a uniform SPMD pipeline."""
        import jax
        import jax.numpy as jnp

        from ... import env as _env
        from ....jit import functional_call
        from ....parallel.pipeline import microbatch, pipeline_1f1b

        mesh = _env.get_global_mesh()
        S = mesh.shape.get("pp", 1) if mesh is not None else 1
        pl = self._layers
        if S <= 1 or pl.get_num_stages() != S or pl._shared:
            return None
        import paddle_tpu.nn as nn

        stages = [pl.get_stage_layers(s) for s in range(S)]
        if any(fwd is not None or not isinstance(l, nn.Layer)
               for st in stages for l, fwd in st):
            return None
        n_per = len(stages[0])
        if any(len(st) != n_per for st in stages):
            return None
        # stages must be CONSTRUCTED identically, not merely have matching
        # param shapes — stage 0's layer objects execute every stage's
        # weights, so differing ctor args (activation, eps, ...) would
        # silently compute the wrong function
        parts = pl.segment_parts
        desc_rows = [pl.descs[parts[s]:parts[s + 1]] for s in range(S)]
        for row in desc_rows:
            for j, d in enumerate(row):
                d0 = desc_rows[0][j]
                if not (isinstance(d, pp_layers.LayerDesc)
                        and isinstance(d0, pp_layers.LayerDesc)
                        and type(d) is type(d0)
                        and d.layer_func is d0.layer_func
                        and d.inputs == d0.inputs
                        and d.kwargs == d0.kwargs):
                    return None
        template = [l for l, _ in stages[0]]
        # per-stage param value lists must be structurally identical
        names = [[n for n, _ in l.named_parameters()] for l in template]
        stage_params = []  # [S][layer][pname] -> Parameter
        for st in stages:
            per = []
            for i, (l, _) in enumerate(st):
                d = dict(l.named_parameters())
                if sorted(d) != sorted(names[i]):
                    return None
                per.append([d[n] for n in names[i]])
            stage_params.append(per)
        shapes0 = [[tuple(p.shape) for p in lay] for lay in stage_params[0]]
        for per in stage_params[1:]:
            if [[tuple(p.shape) for p in lay] for lay in per] != shapes0:
                return None

        loss_layer = pl._loss_fn
        loss_names = ([n for n, _ in loss_layer.named_parameters()]
                      if isinstance(loss_layer, nn.Layer) else [])
        loss_tensors = ([dict(loss_layer.named_parameters())[n]
                         for n in loss_names]
                        if loss_names else [])

        def stage_fn(pstage, inp):
            h, y, mb_i = inp
            for i, l in enumerate(template):
                out, _ = functional_call(
                    l, dict(zip(names[i], pstage[i])), {}, [Tensor(h)])
                h = out
            return (h, y, mb_i)

        def loss_fn(lp, out):
            h, y, mb_i = out
            if isinstance(loss_layer, nn.Layer):
                loss, _ = functional_call(
                    loss_layer, dict(zip(loss_names, lp)), {},
                    [Tensor(h), Tensor(y)])
                return jnp.asarray(loss).astype(jnp.float32)
            from ....framework.core import tracing_guard

            with tracing_guard(True):
                return loss_layer(Tensor(h), Tensor(y))._value.astype(
                    jnp.float32)

        def runner(x, y, n_micro):
            stacked = [
                [jnp.stack([stage_params[s][i][j]._value
                            for s in range(S)])
                 for j in range(len(names[i]))]
                for i in range(n_per)
            ]
            lp = [t._value for t in loss_tensors]
            mb_i = jnp.repeat(jnp.arange(n_micro, dtype=jnp.int32),
                              x.shape[0] // n_micro)
            inp_mb = microbatch((x, y, mb_i), n_micro)
            try:
                loss, (g_stacked, g_lp) = jax.value_and_grad(
                    lambda sp, l: pipeline_1f1b(
                        stage_fn, loss_fn, sp, l, inp_mb, mesh=mesh,
                        axis="pp"),
                    (0, 1))(stacked, lp)
            except (TypeError, ValueError) as e:  # shape-changing stages
                raise _InfeasibleCompiled(str(e))

            def set_grads():
                for i in range(n_per):
                    for j in range(len(names[i])):
                        g = g_stacked[i][j]
                        if g is None:
                            continue
                        for s in range(S):
                            p = stage_params[s][i][j]
                            gv = Tensor(g[s])
                            p.grad = gv if p.grad is None else p.grad + gv
                for t, g in zip(loss_tensors, g_lp):
                    if g is not None:
                        t.grad = Tensor(g) if t.grad is None else t.grad + Tensor(g)

            return loss, set_grads

        return runner

    def _compiled_runner(self):
        if self._compiled_state == 0:
            import warnings

            try:
                self._compiled = self._build_compiled()
                if self._compiled is None:
                    warnings.warn(
                        "PipelineParallel: layer structure is not eligible "
                        "for the compiled 1F1B schedule (non-uniform stages, "
                        "shared params, or custom forwards); falling back to "
                        "the sequential micro-batch loop (no pipelining)",
                        RuntimeWarning, stacklevel=3)
            except Exception as e:
                warnings.warn(
                    "PipelineParallel: compiled 1F1B schedule could not be "
                    f"built ({type(e).__name__}: {e}); falling back to the "
                    "sequential micro-batch loop (no pipelining)",
                    RuntimeWarning, stacklevel=3)
                self._compiled = None
            self._compiled_state = 1 if self._compiled is not None else -1
        return self._compiled

    # ------------------------------------------------------------------ #

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Micro-batched forward/backward with grad accumulation
        (reference train_batch :940 / forward_backward_pipeline :684).
        Routes onto the compiled 1F1B schedule when schedule_mode is
        "1F1B" and the stage structure allows it; the scaler path and
        irregular models use the sequential loop."""
        x, y = data
        x = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
        y = y if isinstance(y, Tensor) else Tensor(np.asarray(y))
        total = x.shape[0]
        mbs = self.micro_batch_size
        if total % mbs != 0:
            # reference asserts divisibility (pipeline_parallel.py:940 path)
            raise ValueError(
                f"batch size {total} is not divisible by micro_batch_size {mbs}"
            )
        n_micro = max(total // mbs, 1)

        if (self.schedule_mode.upper() == "1F1B" and scaler is None
                and n_micro > 1):
            runner = self._compiled_runner()
            if runner is not None:
                try:
                    loss, set_grads = runner(x._value, y._value, n_micro)
                except _InfeasibleCompiled as e:
                    import warnings

                    warnings.warn(
                        "PipelineParallel: compiled 1F1B schedule is "
                        f"infeasible for this model ({e}); falling back to "
                        "the sequential micro-batch loop (no pipelining)",
                        RuntimeWarning, stacklevel=2)
                    self._compiled = None
                    self._compiled_state = -1
                else:
                    set_grads()
                    optimizer.step()
                    optimizer.clear_grad()
                    if lr_scheduler is not None:
                        lr_scheduler.step()
                    return Tensor(loss)

        loss_acc = None  # device-side accumulation: no host sync per microbatch
        for m in range(n_micro):
            lo, hi = m * mbs, min((m + 1) * mbs, total)
            xm, ym = x[lo:hi], y[lo:hi]
            out = self._layers(xm)
            loss = self._layers._loss_fn(out, ym)
            scaled = loss * (1.0 / n_micro)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            ld = loss.detach()
            loss_acc = ld if loss_acc is None else loss_acc + ld
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss_acc * (1.0 / n_micro)

    @no_grad()
    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x if isinstance(x, Tensor) else Tensor(np.asarray(x)))
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, y if isinstance(y, Tensor) else Tensor(np.asarray(y)))
        return out

    def parameters(self, *a, **kw):
        return self._layers.parameters(*a, **kw)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)


class _InfeasibleCompiled(Exception):
    pass
