"""PipelineParallel wrapper (reference: python/paddle/distributed/fleet/
meta_parallel/pipeline_parallel.py — PipelineParallel :242,
forward_backward_pipeline :684 (1F1B), train_batch :940, interleaved VPP
:1308).

TPU-native execution model: in the reference, pp ranks are processes
exchanging activations over NCCL p2p in a hand-scheduled 1F1B loop. Under a
single-controller mesh the schedule is *compiled*: train_batch splits the
batch into micro-batches and drives them through the stage graph; the
compiled collective-permute pipeline (paddle_tpu.parallel.pipeline) maps
stages onto the `pp` mesh axis so micro-batch k+1's stage-0 work overlaps
micro-batch k's stage-1 work inside one XLA program — the same steady-state
overlap 1F1B achieves, scheduled by XLA instead of Python.

This wrapper provides the reference API (train_batch with grad accumulation,
micro-batching, scaler support) with eager semantics; the compiled pipeline
path is engaged by GPT-style models through paddle_tpu.parallel.pipeline.
"""

from __future__ import annotations

import numpy as np

from ....framework.core import Tensor, no_grad
from . import pp_layers

__all__ = ["PipelineParallel"]


class PipelineParallel:
    def __init__(self, layers, hcg, strategy):
        if not isinstance(layers, pp_layers.PipelineLayer):
            raise TypeError("PipelineParallel requires a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pp_cfg = strategy.hybrid_configs.get("pp_configs", {})
        self.micro_batch_size = strategy.hybrid_configs.get("micro_batch_size") or \
            pp_cfg.get("micro_batch_size", 1)
        self.accumulate_steps = pp_cfg.get("accumulate_steps", 1)

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Micro-batched forward/backward with grad accumulation
        (reference train_batch :940 / forward_backward_pipeline :684)."""
        x, y = data
        x = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
        y = y if isinstance(y, Tensor) else Tensor(np.asarray(y))
        total = x.shape[0]
        mbs = self.micro_batch_size
        if total % mbs != 0:
            # reference asserts divisibility (pipeline_parallel.py:940 path)
            raise ValueError(
                f"batch size {total} is not divisible by micro_batch_size {mbs}"
            )
        n_micro = max(total // mbs, 1)
        losses = []
        for m in range(n_micro):
            lo, hi = m * mbs, min((m + 1) * mbs, total)
            xm, ym = x[lo:hi], y[lo:hi]
            out = self._layers(xm)
            loss = self._layers._loss_fn(out, ym)
            scaled = loss * (1.0 / n_micro)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            losses.append(float(loss.numpy()))
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(np.asarray(np.mean(losses), np.float32))

    @no_grad()
    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x if isinstance(x, Tensor) else Tensor(np.asarray(x)))
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, y if isinstance(y, Tensor) else Tensor(np.asarray(y)))
        return out

    def parameters(self, *a, **kw):
        return self._layers.parameters(*a, **kw)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)
