"""Meta-parallel model wrappers (reference: python/paddle/distributed/fleet/
meta_parallel/ — TensorParallel, ShardingParallel, SegmentParallel wrappers).

On TPU these wrappers are thin: parameters already carry their sharding specs
(set by the mpu layers or stage-3 annotation); the wrapper's reference job —
param broadcast across groups, backward-hook grad sync — is subsumed by GSPMD
in the compiled train step. They remain real Layer wrappers so user code
behaves identically.
"""

from __future__ import annotations

import paddle_tpu.nn as nn

from .pipeline_parallel import PipelineParallel  # noqa: F401
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401

__all__ = [
    "MetaParallelBase",
    "TensorParallel",
    "ShardingParallel",
    "SegmentParallel",
    "PipelineParallel",
    "PipelineLayer",
    "LayerDesc",
    "SharedLayerDesc",
]


class MetaParallelBase(nn.Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


class TensorParallel(MetaParallelBase):
    """reference: meta_parallel/tensor_parallel.py."""


class ShardingParallel(MetaParallelBase):
    """reference: meta_parallel/sharding_parallel.py."""


class SegmentParallel(MetaParallelBase):
    """reference: meta_parallel/segment_parallel.py:26."""
