"""Pipeline layer partitioning (reference: python/paddle/distributed/fleet/
meta_parallel/parallel_layers/pp_layers.py — LayerDesc :57, SharedLayerDesc
:77, PipelineLayer :258 with seg_method uniform/layer-count partitioning)."""

from __future__ import annotations

import numpy as np

import paddle_tpu.nn as nn

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, nn.Layer):
            raise TypeError("LayerDesc expects an nn.Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(nn.Layer):
    """Holds the full layer list plus the stage partition.

    Single-controller difference from the reference: every stage's layers are
    materialized in this process (the mesh, not the process set, carries the
    pp dimension); `get_stage_layers(i)` exposes per-stage slices for the
    compiled pipeline schedule (paddle_tpu.parallel.pipeline).
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._num_stages = num_stages or (topology.get_dim("pipe") if topology else 1)
        self._seg_method = seg_method
        self.descs = list(layers)
        self._shared = {}
        built = []
        for i, d in enumerate(self.descs):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    layer = self._shared[d.layer_name]
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                built.append((layer, d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, nn.Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, None))
            else:
                raise TypeError(f"unsupported pipeline entry {d!r}")
        self.run_funcs = built
        self._layer_list = nn.LayerList([l for l, _ in built if isinstance(l, nn.Layer)])
        self.segment_parts = self._partition(len(built), self._num_stages)
        self._mark_shared_ownership()

    def _mark_shared_ownership(self):
        """Shared-param convention (reference PipelineLayer shared_layers /
        is_firstly_shared): in multi-controller runs, only the stage that
        FIRST declares a shared layer owns it for distributed grad-norm
        accounting — other stages' copies get is_firstly_shared=False so
        _HybridParallelClipGrad counts the tied weight exactly once across
        the pp group."""
        if not self._shared:
            return
        try:
            import jax

            if jax.process_count() <= 1:
                return  # single controller: one object, counted once anyway
            from .. import get_hybrid_communicate_group

            hcg = get_hybrid_communicate_group()
            local_stage = hcg.get_stage_id() if hcg is not None else None
        except Exception:
            return
        if local_stage is None:
            return
        name_owner = {}
        for i, d in enumerate(self.descs):
            if isinstance(d, SharedLayerDesc) and d.layer_name not in name_owner:
                stage = next(
                    s for s in range(self._num_stages)
                    if self.segment_parts[s] <= i < self.segment_parts[s + 1])
                name_owner[d.layer_name] = stage
        for name, layer in self._shared.items():
            owned = name_owner.get(name, 0) == local_stage
            for p in layer.parameters():
                p.is_firstly_shared = owned

    @staticmethod
    def _partition(n_layers, n_stages):
        """Uniform partition boundaries (reference seg_method='uniform')."""
        base = n_layers // n_stages
        extra = n_layers % n_stages
        parts = [0]
        for s in range(n_stages):
            parts.append(parts[-1] + base + (1 if s < extra else 0))
        return parts

    def get_num_stages(self):
        return self._num_stages

    def get_stage_layers(self, stage):
        lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
        return self.run_funcs[lo:hi]

    def forward(self, x):
        for fn, fwd in self.run_funcs:
            if fwd is not None:
                x = fwd(fn, x)
            elif isinstance(fn, nn.Layer) or callable(fn):
                x = fn(x)
        return x
