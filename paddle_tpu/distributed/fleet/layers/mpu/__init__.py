from .mp_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
