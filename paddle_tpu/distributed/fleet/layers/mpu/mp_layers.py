"""Megatron-style tensor-parallel layers.

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding :49, ColumnParallelLinear :336, RowParallelLinear :543,
ParallelCrossEntropy :744 — implemented there with explicit _c_identity/
_c_concat/allreduce ops around sharded weights.

TPU-native redesign: the layer annotates its weight with a PartitionSpec
(Parameter.dist_attr) and constrains activations; GSPMD inserts the identity/
all-reduce/all-gather collectives when the surrounding train step is jitted
over the mesh. Eagerly (single device, tests) the layers compute on the full
weight — numerically identical by construction. The explicit-collective
variants used inside shard_map bodies live in `primitives` form in
paddle_tpu.distributed.collective.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from .....framework.core import Tensor, run_op
from .....nn import initializer as I
from .... import env as _env

__all__ = [
    "VocabParallelEmbedding",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "ParallelCrossEntropy",
    "mark_as_sequence_parallel",
]


def _mp_degree():
    m = _env.get_global_mesh()
    if m is None:
        return 1
    return m.shape.get("mp", 1)


def _constrain(x: Tensor, spec: P) -> Tensor:
    """with_sharding_constraint when inside a jit over the global mesh.

    Inside a partial-manual shard_map region (the compiled pp pipeline,
    paddle_tpu/parallel/pipeline.py), the constraint must be built on the
    CONTEXT abstract mesh (whose pp axis is Manual) — a sharding carrying the
    concrete all-Auto mesh poisons downstream op types. Axes that are manual
    in context are dropped from the spec: the region is already
    device-local over them.
    """
    mesh = _env.get_global_mesh()
    if mesh is None:
        return x

    def fn(a):
        return _env.constrain_array(a, spec)

    return run_op("sharding_constraint", fn, [x])


class VocabParallelEmbedding(nn.Layer):
    """Embedding with the vocab dim sharded over mp
    (reference: mp_layers.py:49 — per-rank vocab range + allreduce)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal() if weight_attr is None else None,
        )
        self.weight.dist_attr = P("mp", None)
        self.weight.is_distributed = True

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return out


class ColumnParallelLinear(nn.Layer):
    """Linear with output-features sharded over mp (reference: mp_layers.py:336)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.is_mp = _mp_degree() > 1
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        self.weight.dist_attr = P(None, "mp")
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.dist_attr = P("mp")
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output:
            # keep activation sharded on the feature dim; leading dims stay
            # UNCONSTRAINED so the batch's (dp, sharding) sharding propagates
            # (None would force-replicate them -> SPMD involuntary full
            # rematerialization in the backward, round-4 weak #5)
            spec = P(*([P.UNCONSTRAINED] * (out.ndim - 1) + ["mp"]))
            out = _constrain(out, spec)
        return out


class RowParallelLinear(nn.Layer):
    """Linear with input-features sharded over mp; output needs an allreduce
    (reference: mp_layers.py:543) — GSPMD derives the psum from the contraction
    over the sharded dim."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.is_mp = _mp_degree() > 1
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        self.weight.dist_attr = P("mp", None)
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            spec = P(*([P.UNCONSTRAINED] * (x.ndim - 1) + ["mp"]))
            x = _constrain(x, spec)
        return F.linear(x, self.weight, self.bias)


class ParallelCrossEntropy(nn.Layer):
    """Cross entropy over mp-sharded logits (reference: mp_layers.py:744 —
    c_softmax_with_cross_entropy kernel doing the max/sum allreduces). The
    jnp log-softmax reductions over the sharded class dim lower to the same
    psums under GSPMD."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(
            input, label, reduction="none", ignore_index=self.ignore_index
        )


def mark_as_sequence_parallel(x: Tensor) -> Tensor:
    """Constrain an activation [B, S, H] to be sequence-sharded over mp —
    Megatron-SP's scatter (reference: fleet/utils/sequence_parallel_utils.py
    ScatterOp). GSPMD materializes the all-gather where full sequences are
    needed."""
    spec = P(P.UNCONSTRAINED, "mp", *([P.UNCONSTRAINED] * (x.ndim - 2)))
    return _constrain(x, spec)
