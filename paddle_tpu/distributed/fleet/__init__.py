"""fleet facade (reference: python/paddle/distributed/fleet/fleet.py —
init :218, _init_hybrid_parallel_env :674, distributed_model in model.py:33
dispatching by parallel mode :135-185, distributed_optimizer :1448)."""

from __future__ import annotations

from ...framework.core import Parameter
from .. import env as _env
from .base.distributed_strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup

__all__ = [
    "init",
    "is_initialized",
    "distributed_model",
    "distributed_optimizer",
    "get_hybrid_communicate_group",
    "DistributedStrategy",
    "worker_index",
    "worker_num",
    "HybridCommunicateGroup",
    "CommunicateTopology",
]

_fleet_state = {"initialized": False, "strategy": None, "hcg": None}


def init(role_maker=None, is_collective=False, strategy=None, log_level="INFO"):
    """Build the hybrid topology + global mesh from strategy.hybrid_configs."""
    if strategy is None:
        strategy = DistributedStrategy()
    hc = strategy.hybrid_configs
    topo = CommunicateTopology(
        dims=(
            hc["dp_degree"], hc["pp_degree"], hc["sharding_degree"],
            hc["sep_degree"], hc["mp_degree"],
        )
    )
    _env.init_parallel_env()
    hcg = HybridCommunicateGroup(topo)
    _fleet_state.update(initialized=True, strategy=strategy, hcg=hcg)
    return None


def is_initialized():
    return _fleet_state["initialized"]


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    return _fleet_state["hcg"]


def worker_index():
    return _env.get_rank()


def worker_num():
    return _env.get_world_size()


def distributed_model(model):
    """Wrap by parallel mode (reference fleet/model.py:135-185). On TPU the
    wrappers annotate sharding metadata; the actual collectives are compiled
    into the DistributedTrainStep."""
    hcg = _fleet_state["hcg"]
    if hcg is None:
        raise RuntimeError("call fleet.init() first")
    from ..parallel import DataParallel
    from .meta_parallel import (
        PipelineParallel,
        SegmentParallel,
        ShardingParallel,
        TensorParallel,
    )

    mode = hcg.get_parallel_mode()
    strategy = _fleet_state["strategy"]
    if mode == "pipeline_parallel":
        from .meta_parallel.pp_layers import PipelineLayer

        if isinstance(model, PipelineLayer):
            return PipelineParallel(model, hcg, strategy)
        return TensorParallel(model, hcg, strategy)
    if mode == "tensor_parallel":
        return TensorParallel(model, hcg, strategy)
    if mode == "sharding_parallel":
        return ShardingParallel(model, hcg, strategy)
    if mode == "segment_parallel":
        return SegmentParallel(model, hcg, strategy)
    if mode == "data_parallel":
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy=None):
    """reference fleet.py:1448 -> HybridParallelOptimizer
    (dygraph_optimizer/hybrid_parallel_optimizer.py:275)."""
    from .meta_optimizers import HybridParallelOptimizer

    return HybridParallelOptimizer(
        optimizer, _fleet_state["hcg"], strategy or _fleet_state["strategy"]
    )
