"""Activation recomputation (reference: python/paddle/distributed/fleet/
recompute/recompute.py — RecomputeFunction :128 replays forward under saved
RNG state in backward; recompute_hybrid.py adds offload).

TPU-native: jax.checkpoint (rematerialization) IS this feature, applied at
trace time — XLA recomputes the segment in the backward pass, RNG is
deterministic because keys are values. The eager path replays via PyLayer
with rng_guard for exact reference semantics.
"""

from __future__ import annotations

import jax

from ...autograd import PyLayer
from ...framework import random as rnd
from ...framework.core import Tensor, in_tracing, no_grad, run_op

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, use_reentrant=True, preserve_rng_state=True, **kwargs):
    """paddle.distributed.fleet.utils.recompute equivalent."""
    if in_tracing():
        # inside a jitted program: use XLA remat on the raw function
        tensors = [a for a in args if isinstance(a, Tensor)]
        others = [a for a in args if not isinstance(a, Tensor)]

        def raw(*vals):
            it = iter(vals)
            rebuilt = [Tensor(next(it)) if isinstance(a, Tensor) else a for a in args]
            out = function(*rebuilt, **kwargs)
            return out._value if isinstance(out, Tensor) else tuple(o._value for o in out)

        ck = jax.checkpoint(raw)
        return run_op("recompute", ck, tensors)

    tensor_args = tuple(a for a in args if isinstance(a, Tensor))
    n_args = len(tensor_args)
    trainable = _collect_trainable_params(function)

    class _Recompute(PyLayer):
        @staticmethod
        def forward(ctx, *all_inputs):
            ctx.rng = rnd.get_rng_state()
            ctx.tensor_args = all_inputs[:n_args]
            with no_grad():
                out = function(*args, **kwargs)
            return out

        @staticmethod
        def backward(ctx, *grads):
            from ...autograd import backward as autograd_backward
            from ...framework.core import enable_grad

            # replay with grad re-enabled (PyLayer backwards run under
            # no_grad) and the saved RNG state, then run the real backward
            with enable_grad(), rnd.rng_guard(ctx.rng[0]):
                detached = [
                    Tensor(t._value, stop_gradient=t.stop_gradient)
                    for t in ctx.tensor_args
                ]
                it = iter(detached)
                rebuilt = [next(it) if isinstance(a, Tensor) else a for a in args]
                out = function(*rebuilt, **kwargs)
                outs = out if isinstance(out, (tuple, list)) else [out]
                autograd_backward(list(outs), list(grads), retain_graph=False)
            # param grads accumulated directly on the live Parameters during
            # the replay; return None for those slots
            return tuple(d.grad if d.grad is not None else None for d in detached) + \
                (None,) * len(trainable)

    return _Recompute.apply(*tensor_args, *trainable)


def _collect_trainable_params(function):
    """Find trainable Parameters reachable from `function` so the recompute
    PyLayer participates in the autograd graph even when the data inputs are
    constants (params enter via closure, like the reference's detection of
    trainable weights in RecomputeFunction)."""
    from ...nn.layer.layers import Layer

    seen = []

    def from_layer(layer):
        seen.extend(p for p in layer.parameters() if not p.stop_gradient)

    if isinstance(function, Layer):
        from_layer(function)
    elif hasattr(function, "__self__") and isinstance(function.__self__, Layer):
        from_layer(function.__self__)
    elif hasattr(function, "__closure__") and function.__closure__:
        for cell in function.__closure__:
            try:
                v = cell.cell_contents
            except ValueError:
                continue
            if isinstance(v, Layer):
                from_layer(v)
            elif isinstance(v, Tensor) and not v.stop_gradient:
                seen.append(v)
    return seen


def recompute_sequential(ctx, functions, *args, **kwargs):
    """reference: recompute_sequential — chunked recompute over a Sequential."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    n = len(layers)
    per = max(n // segments, 1)
    out = args[0] if len(args) == 1 else args

    def seg_fn(layers_slice):
        def run(x):
            for l in layers_slice:
                x = l(x)
            return x

        return run

    i = 0
    while i < n:
        sl = layers[i:i + per]
        out = recompute(seg_fn(sl), out, **kwargs)
        i += per
    return out
