from .manager import ElasticManager, ElasticStatus

__all__ = ["ElasticManager", "ElasticStatus"]
