"""Elastic fault detection / recovery (reference:
python/paddle/distributed/fleet/elastic/manager.py — ElasticManager :125,
LauncherInterface watchdog, np range N:M scaling).

TPU formulation: the rendezvous substrate is the native TCPStore
(native/tcp_store.cc) instead of etcd. Each node heartbeats
`<job>/heartbeat/<rank>` with a timestamp; the manager watches the live set
against the `np` range — a missing heartbeat marks the node dead, shrinking
below min-nodes makes the job NOT-ready (the launcher tears down and
restarts the pod, launch/main.py restart loop), and rejoin within the range
resumes. Host failure detection on a TPU pod is exactly this liveness
protocol; chip failure surfaces as a jax.distributed error that kills the
worker, which the same loop catches."""

from __future__ import annotations

import os
import time

__all__ = ["ElasticStatus", "ElasticManager"]


class ElasticStatus:
    COMPLETED = "completed"  # training finished (complete() was called)
    ERROR = "error"
    HOLD = "hold"            # below min nodes: wait for rejoin
    RESTART = "restart"      # live set can still grow / changed: re-form
    EXIT = "exit"
    OK = "ok"                # healthy full cluster, no action (TPU extension)


class ElasticManager:
    """reference elastic/manager.py:125."""

    def __init__(self, store=None, job_id=None, np_range=None, rank=None,
                 heartbeat_interval=2.0, timeout=10.0):
        self.job_id = job_id or os.environ.get("PADDLE_JOB_ID", "default")
        np_spec = np_range or os.environ.get("PADDLE_ELASTIC_NP", "1")
        if isinstance(np_spec, str) and ":" in np_spec:
            lo, hi = np_spec.split(":")
            self.min_np, self.max_np = int(lo), int(hi)
        else:
            self.min_np = self.max_np = int(np_spec)
        self.rank = rank if rank is not None else int(
            os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.heartbeat_interval = heartbeat_interval
        self.timeout = timeout
        if store is None:
            from ...store import create_or_get_global_tcp_store

            store = create_or_get_global_tcp_store()
        self.store = store
        self.enable = self.max_np > 1 or self.min_np != self.max_np
        self._stopped = False

    # ------------------------------------------------------------------ #

    def _key(self, rank):
        return f"{self.job_id}/heartbeat/{rank}"

    def heartbeat(self):
        """Publish this node's liveness (reference: etcd lease refresh)."""
        self.store.set(self._key(self.rank), str(time.time()).encode())

    def alive_nodes(self):
        """Ranks whose heartbeat is fresher than the timeout."""
        now = time.time()
        alive = []
        probe = getattr(self.store, "tryget", None)
        if probe is None:
            # a blocking get() on a dead rank's key would stall the scan for
            # the full io timeout — exactly what this probe exists to avoid
            raise TypeError(
                "ElasticManager requires a store with a non-blocking "
                "tryget() (native TCPStore)")
        for r in range(self.max_np):
            try:
                raw = probe(self._key(r))
            except Exception:
                continue
            if not raw:
                continue
            try:
                ts = float(raw.decode())
            except ValueError:
                continue
            if now - ts <= self.timeout:
                alive.append(r)
        return alive

    def is_ready(self):
        """Job can (re)start: live nodes within [min_np, max_np]."""
        return len(self.alive_nodes()) >= self.min_np

    def complete(self):
        """Mark the job finished (reference: trainers reporting completion
        before the manager exits the watch loop)."""
        self.store.set(f"{self.job_id}/completed", b"1")

    def is_completed(self):
        probe = getattr(self.store, "tryget", None)
        try:
            return bool(probe and probe(f"{self.job_id}/completed"))
        except Exception:
            return False

    def watch(self):
        """One scheduling decision (reference manager.watch loop):
        COMPLETED when training reported done, HOLD below min (wait for
        rejoin), RESTART while the live set can still change, OK for a
        healthy full cluster."""
        if self.is_completed():
            return ElasticStatus.COMPLETED
        alive = self.alive_nodes()
        if len(alive) < self.min_np:
            return ElasticStatus.HOLD
        if len(alive) < self.max_np:
            return ElasticStatus.RESTART
        return ElasticStatus.OK

    def exit(self, completed=True):
        self._stopped = True
        if completed:
            try:
                self.complete()
            except Exception:
                pass
        try:
            self.store.delete_key(self._key(self.rank))
        except Exception:
            pass
