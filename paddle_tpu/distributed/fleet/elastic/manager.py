"""Elastic fault detection / recovery (reference:
python/paddle/distributed/fleet/elastic/manager.py — ElasticManager :125,
LauncherInterface watchdog, np range N:M scaling).

TPU formulation: the rendezvous substrate is the native TCPStore
(native/tcp_store.cc) instead of etcd. Each node heartbeats
`<job>/heartbeat/<rank>` with a timestamp; the manager watches the live set
against the `np` range — a missing heartbeat marks the node dead, shrinking
below min-nodes makes the job NOT-ready (the launcher tears down and
restarts the pod, launch/main.py restart loop), and rejoin within the range
resumes. Host failure detection on a TPU pod is exactly this liveness
protocol; chip failure surfaces as a jax.distributed error that kills the
worker, which the same loop catches."""

from __future__ import annotations

import os
import time

__all__ = ["ElasticStatus", "ElasticManager"]


class ElasticStatus:
    COMPLETED = "completed"  # training finished (complete() was called)
    ERROR = "error"
    HOLD = "hold"            # below min nodes: wait for rejoin
    RESTART = "restart"      # live set can still grow / changed: re-form
    EXIT = "exit"
    OK = "ok"                # healthy full cluster, no action (TPU extension)


class ElasticManager:
    """reference elastic/manager.py:125."""

    def __init__(self, store=None, job_id=None, np_range=None, rank=None,
                 heartbeat_interval=2.0, timeout=10.0):
        self.job_id = job_id or os.environ.get("PADDLE_JOB_ID", "default")
        np_spec = np_range or os.environ.get("PADDLE_ELASTIC_NP", "1")
        if isinstance(np_spec, str) and ":" in np_spec:
            lo, hi = np_spec.split(":")
            self.min_np, self.max_np = int(lo), int(hi)
        else:
            self.min_np = self.max_np = int(np_spec)
        self.rank = rank if rank is not None else int(
            os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.heartbeat_interval = heartbeat_interval
        self.timeout = timeout
        if store is None:
            from ...store import create_or_get_global_tcp_store

            store = create_or_get_global_tcp_store()
        self.store = store
        self.enable = self.max_np > 1 or self.min_np != self.max_np
        self._stopped = False
        # reform tracking: a formed job that loses a node (below min ⇒ HOLD,
        # or a shrink within the runnable band) must make every survivor
        # observe exactly one reform signal, so collective groups re-form
        # around the new membership. The signal is a GENERATION COUNTER in
        # the shared store (not a local flag): nodes whose polls never
        # landed inside the shrink window still see the bumped generation.
        # `last_restart_was_reform` distinguishes that signal from the
        # steady "can still scale up" RESTART of a partial band, which a
        # runnable cluster must NOT keep exiting on. Known limitation
        # (docs/RESILIENCE.md): growth within the band (a node JOINING a
        # runnable partial cluster) is a scale-out event for the launcher,
        # not an in-step reform — matching the seed semantics where growth
        # to full strength reads OK.
        self._was_ready = False
        self._bump_pending = False
        self._last_alive = None
        self._reform_gen_seen = None
        self.last_restart_was_reform = False

    # ------------------------------------------------------------------ #

    def _key(self, rank):
        return f"{self.job_id}/heartbeat/{rank}"

    def _reform_key(self):
        return f"{self.job_id}/reform_gen"

    def _reform_gen(self):
        probe = getattr(self.store, "tryget", None)
        try:
            raw = probe(self._reform_key()) if probe else None
            if not raw:
                return 0
            try:
                return int(raw)  # decimal (fake stores)
            except ValueError:
                # native ADD stores 8-byte little-endian i64
                return int.from_bytes(raw[:8], "little", signed=True)
        except (OSError, RuntimeError, ConnectionError):
            # store unreachable mid-poll: treat as "no reform signal yet";
            # the next watch() tick re-probes
            return 0

    def heartbeat(self):
        """Publish this node's liveness (reference: etcd lease refresh)."""
        self.store.set(self._key(self.rank), str(time.time()).encode())

    def alive_nodes(self):
        """Ranks whose heartbeat is fresher than the timeout."""
        now = time.time()
        alive = []
        probe = getattr(self.store, "tryget", None)
        if probe is None:
            # a blocking get() on a dead rank's key would stall the scan for
            # the full io timeout — exactly what this probe exists to avoid
            raise TypeError(
                "ElasticManager requires a store with a non-blocking "
                "tryget() (native TCPStore)")
        for r in range(self.max_np):
            try:
                raw = probe(self._key(r))
            except (OSError, RuntimeError, ConnectionError):
                continue  # unreadable heartbeat == not provably alive
            if not raw:
                continue
            try:
                ts = float(raw.decode())
            except ValueError:
                continue
            if now - ts <= self.timeout:
                alive.append(r)
        return alive

    def is_ready(self):
        """Job can (re)start: live nodes within [min_np, max_np]."""
        return len(self.alive_nodes()) >= self.min_np

    def complete(self):
        """Mark the job finished (reference: trainers reporting completion
        before the manager exits the watch loop)."""
        self.store.set(f"{self.job_id}/completed", b"1")

    def is_completed(self):
        probe = getattr(self.store, "tryget", None)
        try:
            return bool(probe and probe(f"{self.job_id}/completed"))
        except (OSError, RuntimeError, ConnectionError):
            return False  # store down != job done; keep polling

    def watch(self):
        """One scheduling decision (reference manager.watch loop):
        COMPLETED when training reported done, HOLD below min (wait for
        rejoin), RESTART while the live set can still change OR right after
        a hold ends (rejoin ⇒ re-form the groups), OK for a healthy full
        cluster."""
        self.last_restart_was_reform = False
        if self.is_completed():
            return ElasticStatus.COMPLETED
        alive = self.alive_nodes()
        alive_set = frozenset(alive)
        below = len(alive) < self.min_np

        # detect an un-signaled departure: a formed job entering HOLD, or a
        # shrink inside the runnable band. The pending flag is STICKY until
        # the generation actually advances — advancing local state before a
        # successful store.add() would lose the one-shot signal forever on
        # a transient store error.
        if below:
            if self._was_ready:
                self._bump_pending = True
            self._was_ready = False
            self._last_alive = None
        else:
            if self._last_alive is not None and self._last_alive - alive_set:
                self._bump_pending = True
            self._was_ready = True
            self._last_alive = alive_set

        # only the LOWEST surviving rank bumps for an event: all survivors
        # observe the same departure, and N bumps for one event would read
        # as N distinct reforms to late adopters
        if self._bump_pending and alive and self.rank == min(alive):
            try:
                self.store.add(self._reform_key(), 1)
                self._bump_pending = False
            except Exception:  # graftlint: disable=GL003 sticky by design: the pending flag survives and the bump is retried on the next poll
                pass

        if below:
            return ElasticStatus.HOLD
        gen = self._reform_gen()
        if self._reform_gen_seen is None:
            # first formation sighting by this process: its own groups are
            # forming fresh anyway, nothing to re-form
            self._reform_gen_seen = gen
        elif gen > self._reform_gen_seen:
            self._reform_gen_seen = gen
            self._bump_pending = False  # signaled — by this node or a peer
            self.last_restart_was_reform = True
            return ElasticStatus.RESTART
        if len(alive) < self.max_np:
            return ElasticStatus.RESTART  # can still scale up (steady state)
        return ElasticStatus.OK

    def exit(self, completed=True):
        self._stopped = True
        if completed:
            try:
                self.complete()
            except Exception:  # graftlint: disable=GL003 exit path: the store may already be torn down
                pass
        try:
            self.store.delete_key(self._key(self.rank))
        except Exception:  # graftlint: disable=GL003 exit path: a leaked heartbeat key just ages out
            pass
