"""Hybrid-parallel optimizer wrappers.

Reference: HybridParallelOptimizer (python/paddle/distributed/fleet/
meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:275) — wraps
the inner optimizer with a TP/PP-aware ClipGradByGlobalNorm
(_HybridParallelClipGrad :45) and grad sync; DygraphShardingOptimizer
(dygraph_sharding_optimizer.py:54) — ZeRO-1 param-to-rank assignment +
post-step broadcast.

TPU: inside the compiled train step grad sync / ZeRO sharding are placement
properties (DistributedTrainStep) and the jit clip is already global. These
wrappers carry the *eager-path* semantics — in multi-controller eager runs
(jax.distributed, one process per device group) each process only holds its
TP shard and its pipeline stage's params, so the global-norm reduction must
span the mp and pp groups, while replicated params are counted once. In
single-controller SPMD mode params hold global values, and the collective
calls below are placement-transparent no-ops.
"""

from __future__ import annotations

from ...framework.core import Tensor, no_grad
from ...nn.clip import ClipGradByGlobalNorm

__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer"]


class _HybridParallelClipGrad:
    """Distributed ClipGradByGlobalNorm (reference
    hybrid_parallel_optimizer.py:45 _HybridParallelClipGrad._dygraph_clip):

    ||g||^2 = mp_allreduce(sum of TP-sharded sq) + sum of replicated sq,
    then pp_allreduce(total) when pipeline stages own disjoint params.
    TP-duplicate handling: params with is_distributed=True are genuinely
    sharded (each mp rank holds distinct rows/cols — their local sq sums),
    while replicated params appear identically on every mp rank and must be
    counted exactly once, so only the distributed part rides the mp reduce.
    """

    def __init__(self, clip, hcg):
        self._clip = clip
        self._hcg = hcg
        self.clip_norm = clip.clip_norm

    @no_grad()
    def __call__(self, params_grads):
        import jax.numpy as jnp

        from ..collective import all_reduce

        sq_dist, sq_rep = [], []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            if not getattr(p, "is_firstly_shared", True):
                # non-owner copy of a cross-stage tied weight: its norm is
                # counted by the owning stage (reference shared-param flag)
                continue
            s = jnp.sum(jnp.square(g._value.astype(jnp.float32)))
            if getattr(p, "is_distributed", False):
                sq_dist.append(s)
            else:
                sq_rep.append(s)
        # NO early return on empty: in multi-controller runs the mp/pp
        # reductions below are collectives every rank must enter, even a
        # rank whose stage holds only frozen params (its contribution is 0)

        dist_sq = sum(sq_dist) if sq_dist else jnp.zeros(())
        hcg = self._hcg
        if hcg is not None and hcg.get_model_parallel_world_size() > 1:
            t = Tensor(dist_sq)
            all_reduce(t, group=hcg.get_model_parallel_group())
            dist_sq = t._value
        total = dist_sq + (sum(sq_rep) if sq_rep else jnp.zeros(()))
        if hcg is not None and hcg.get_pipe_parallel_world_size() > 1:
            t = Tensor(total)
            all_reduce(t, group=hcg.get_pipe_parallel_group())
            total = t._value

        gnorm = jnp.sqrt(total)
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(
                (g._value.astype(jnp.float32) * scale).astype(g._value.dtype))))
        return out


class HybridParallelOptimizer:
    """Wraps the user optimizer for hybrid-parallel eager training: swaps a
    plain ClipGradByGlobalNorm for the mp/pp-aware distributed clip and
    exposes the deduplicated parameter list (reference
    _obtain_optimizer_parameters_list :275 — shared embedding/lm-head params
    appear once)."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        inner_clip = getattr(optimizer, "_grad_clip", None)
        self._dist_clip = None
        if isinstance(inner_clip, ClipGradByGlobalNorm) and hcg is not None:
            self._dist_clip = _HybridParallelClipGrad(inner_clip, hcg)
            optimizer._grad_clip = self._dist_clip

    @property
    def _learning_rate(self):
        return self._inner_opt._learning_rate

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)

    def _obtain_optimizer_parameters_list(self):
        """Flat, deduplicated (by identity) parameter list — shared params
        (tied embeddings across pipeline stages) contribute once."""
        seen, out = set(), []
        for p in self._inner_opt._parameter_list or []:
            params = p["params"] if isinstance(p, dict) else [p]
            for q in params:
                if id(q) not in seen:
                    seen.add(id(q))
                    out.append(q)
        return out

    def _deduped_structured(self):
        """The inner parameter list with duplicate occurrences removed but
        param-group structure (per-group lr/decay) preserved."""
        seen, out = set(), []
        for entry in self._inner_opt._parameter_list or []:
            if isinstance(entry, dict):
                kept = []
                for q in entry["params"]:
                    if id(q) not in seen:
                        seen.add(id(q))
                        kept.append(q)
                if kept:
                    e = dict(entry)
                    e["params"] = kept
                    out.append(e)
            elif id(entry) not in seen:
                seen.add(id(entry))
                out.append(entry)
        return out

    def step(self):
        # a shared param listed twice (tied embedding registered by two
        # pipeline stages) must be updated ONCE and its grad norm counted
        # once — run the inner step over the deduplicated list
        inner = self._inner_opt
        flat = sum(len(e["params"]) if isinstance(e, dict) else 1
                   for e in inner._parameter_list or [])
        if len(self._obtain_optimizer_parameters_list()) != flat:
            saved = inner._parameter_list
            inner._parameter_list = self._deduped_structured()
            try:
                inner.step()
            finally:
                inner._parameter_list = saved
        else:
            inner.step()

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad(set_to_zero)

    def minimize(self, loss, **kw):
        return self._inner_opt.minimize(loss, **kw)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, state):
        return self._inner_opt.set_state_dict(state)


class DygraphShardingOptimizer(HybridParallelOptimizer):
    """ZeRO stage-1 (reference :54). On TPU the param-to-rank assignment is a
    sharding spec over the `sharding` mesh axis applied to optimizer states
    (DistributedTrainStep sharding_stage=1); the post-step broadcast is
    implicit in GSPMD's output resharding."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        super().__init__(optimizer, hcg, strategy)
        self.sharding_stage = 1
        optimizer._sharding_stage = 1
