"""Hybrid-parallel optimizer wrappers.

Reference: HybridParallelOptimizer (python/paddle/distributed/fleet/
meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:275) — wraps
the inner optimizer with TP-aware grad clip and DP/sharding grad sync;
DygraphShardingOptimizer (dygraph_sharding_optimizer.py:54) — ZeRO-1 param-
to-rank assignment + post-step broadcast.

TPU: grad sync and ZeRO sharding are placement properties of the compiled
train step (DistributedTrainStep), so these wrappers mainly carry API and
the global-norm clip semantics across the whole (replicated+sharded) param
set — which the compiled clip already computes globally.
"""

from __future__ import annotations


__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer"]


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    @property
    def _learning_rate(self):
        return self._inner_opt._learning_rate

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad(set_to_zero)

    def minimize(self, loss, **kw):
        return self._inner_opt.minimize(loss, **kw)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, state):
        return self._inner_opt.set_state_dict(state)


class DygraphShardingOptimizer(HybridParallelOptimizer):
    """ZeRO stage-1 (reference :54). On TPU the param-to-rank assignment is a
    sharding spec over the `sharding` mesh axis applied to optimizer states
    (DistributedTrainStep sharding_stage=1); the post-step broadcast is
    implicit in GSPMD's output resharding."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        super().__init__(optimizer, hcg, strategy)
        self.sharding_stage = 1
