"""Communication groups + collectives.

Reference: ProcessGroup (paddle/phi/core/distributed/collective/process_group.h:48)
with NCCL/Gloo backends, python Group objects (python/paddle/distributed/
communication/group.py), functional collectives (communication/*.py).

TPU-native redesign (SURVEY §5.8): there is no runtime comm library to wrap.
A Group names a set of mesh axes; collectives exist in two forms:

1. **Compiled form** (the performance path): `primitives.*` — thin wrappers
   over lax.psum/all_gather/ppermute/all_to_all for use INSIDE shard_map'd
   programs. XLA lowers these to ICI/DCN collectives.
2. **Eager form** (API parity with `dist.all_reduce(t)`): in the
   single-controller model every rank's tensor is a slice of a global,
   leading-axis-stacked array [nranks, ...]. The eager ops are jitted
   global-array transformations with identical per-rank semantics
   (all_reduce -> every slice becomes the reduction; all_gather -> the
   stacked array; etc.). On sharded global arrays XLA executes these as real
   cross-chip collectives; on replicated arrays they are local math.
"""

from __future__ import annotations

import numpy as np

from ..framework.core import Tensor, to_tensor
from . import env as _env

__all__ = [
    "Group",
    "new_group",
    "get_group",
    "all_reduce",
    "all_gather",
    "all_gather_object",
    "reduce",
    "reduce_scatter",
    "broadcast",
    "broadcast_object_list",
    "scatter",
    "scatter_object_list",
    "alltoall",
    "alltoall_single",
    "send",
    "recv",
    "isend",
    "irecv",
    "barrier",
    "record_collective_traffic",
    "ReduceOp",
    "P2POp",
    "batch_isend_irecv",
    "wait",
    "destroy_process_group",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


# Telemetry: every eager collective bumps per-op call/byte counters in the
# observability registry. The compiled-form `primitives` are deliberately
# uninstrumented — they execute inside traces, where emitting a host-side
# metric is exactly the GL006 hazard graftlint flags.
_obs_handles = None  # lazy HandleCache (metrics imported on first use)


def record_collective_traffic(op: str, nbytes: int, calls: int = 1):
    """Bump collective_{calls,bytes}_total{op=} directly — the byte-count
    form for callers that know the volume without holding the tensors
    (the MoE compiled-path a2a accounting, distributed/moe_comm.py)."""
    global _obs_handles
    if _obs_handles is None:
        from ..observability.metrics import HandleCache

        _obs_handles = HandleCache(lambda reg: (
            reg.counter("collective_calls_total",
                        "eager collective invocations", ("op",)),
            reg.counter("collective_bytes_total",
                        "payload bytes through eager collectives", ("op",)),
        ))
    calls_, bytes_ = _obs_handles.get()
    calls_.inc(calls, op=op)
    if nbytes:
        bytes_.inc(int(nbytes), op=op)


def _tensor_bytes(*tensors):
    nbytes = 0
    for t in tensors:
        v = getattr(t, "_value", t)
        shape = getattr(v, "shape", None)
        if shape is not None:
            nbytes += int(np.prod(shape)) * np.dtype(v.dtype).itemsize
    return nbytes


def _record_collective(op: str, *tensors):
    record_collective_traffic(op, _tensor_bytes(*tensors))


_groups: dict[int, "Group"] = {}
_next_gid = [0]


class Group:
    """A set of ranks; on TPU it corresponds to mesh axis positions.

    `axis_names` ties the group to mesh axes for the compiled path; for eager
    semantics only `nranks` matters.
    """

    def __init__(self, ranks=None, gid=None, axis_names=None, mesh=None):
        self.id = gid if gid is not None else _next_gid[0]
        _next_gid[0] = max(_next_gid[0], self.id) + 1
        if ranks is None:
            ranks = list(range(_env.get_world_size()))
        self.ranks = list(ranks)
        self.nranks = len(self.ranks)
        self.axis_names = tuple(axis_names) if axis_names else None
        self.mesh = mesh
        _groups[self.id] = self

    @property
    def rank(self):
        r = _env.get_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    @property
    def world_size(self):
        return self.nranks

    @property
    def process_group(self):
        return self

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(id={self.id}, nranks={self.nranks}, axes={self.axis_names})"


_default_group: Group | None = None


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        _default_group = Group(gid=0)
    return _default_group


def get_group(gid=0) -> Group:
    if gid in _groups:
        return _groups[gid]
    return _get_default_group()


def new_group(ranks=None, backend=None, timeout=None) -> Group:
    """reference: python/paddle/distributed/collective.py new_group."""
    return Group(ranks=ranks)


def destroy_process_group(group=None):
    global _default_group
    # sweep this rank's residual store keys for the group's communicators
    # (bounded leak otherwise — see eager_multiproc.cleanup_group_keys)
    from . import eager_multiproc as mp

    if mp.nprocs() > 1 and mp._group_seq:
        from .store import create_or_get_global_tcp_store

        try:
            mp.cleanup_group_keys(create_or_get_global_tcp_store(),
                                  gid=None if group is None else group.id)
        except Exception:
            pass
    if group is None:
        _groups.clear()
        _default_group = None
    else:
        _groups.pop(group.id, None)


def _grp(group):
    return group if group is not None else _get_default_group()


class _Task:
    """Completed-task handle (collectives dispatch synchronously into XLA's
    async runtime; Wait is a device sync — reference ProcessGroup::Task)."""

    def __init__(self, tensor=None):
        self._tensor = tensor

    def wait(self):
        if self._tensor is not None:
            # block until the XLA computation materializes
            _ = self._tensor._value.block_until_ready() if hasattr(self._tensor._value, "block_until_ready") else None
        return True

    def is_completed(self):
        return True


def _reduce_over_axis(val, op, axis):
    import jax.numpy as jnp

    fns = {
        ReduceOp.SUM: jnp.sum, "sum": jnp.sum,
        ReduceOp.MAX: jnp.max, "max": jnp.max,
        ReduceOp.MIN: jnp.min, "min": jnp.min,
        ReduceOp.PROD: jnp.prod, "prod": jnp.prod,
        ReduceOp.AVG: jnp.mean, "avg": jnp.mean,
    }
    if op not in fns:
        raise ValueError(f"unsupported reduce op {op!r}")
    return fns[op](val, axis=axis)


def _reduce_stacked(val, op, n):
    import jax.numpy as jnp

    if op in (ReduceOp.SUM, "sum"):
        red = jnp.sum(val, axis=0, keepdims=True)
    elif op in (ReduceOp.MAX, "max"):
        red = jnp.max(val, axis=0, keepdims=True)
    elif op in (ReduceOp.MIN, "min"):
        red = jnp.min(val, axis=0, keepdims=True)
    elif op in (ReduceOp.PROD, "prod"):
        red = jnp.prod(val, axis=0, keepdims=True)
    elif op in (ReduceOp.AVG, "avg"):
        red = jnp.mean(val, axis=0, keepdims=True)
    else:
        raise ValueError(f"unknown reduce op {op}")
    return jnp.broadcast_to(red, val.shape)


def _is_stacked(tensor, group):
    return tensor.ndim >= 1 and tensor.shape[0] == group.nranks


def _mp_active(group, allow_subgroup=False):
    """The cross-process eager backend when jax.distributed has N > 1
    controllers (multi-controller CPU/TPU pods), else None. Subgroup eager
    collectives are refused rather than silently wrong, except where the
    caller has a subgroup implementation (allow_subgroup)."""
    from . import eager_multiproc as mp

    n = mp.nprocs()
    if n <= 1:
        return None
    if group.nranks not in (n,) and not allow_subgroup:
        raise NotImplementedError(
            "eager collectives over subgroups are not supported in "
            "multi-process mode; use the compiled shard_map primitives")
    return mp


def _op_name(op):
    return op if isinstance(op, str) else str(op)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Every rank slice becomes the group reduction. For a stacked global
    array [nranks, ...] this reduces over the rank axis; XLA turns it into an
    ICI all-reduce when the axis is sharded. Updates `tensor` in place and
    returns a task, like the reference. Under multi-controller
    (jax.process_count() > 1) each process contributes its local tensor and
    the reduction runs over the global device set."""
    _record_collective("all_reduce", tensor)
    import jax.numpy as jnp

    g = _grp(group)
    if g.nranks == 1:
        return _Task(tensor)
    mp = _mp_active(g, allow_subgroup=True)
    if mp is not None:
        if g.nranks == mp.nprocs():
            tensor._value = jnp.asarray(
                mp.allreduce_value(np.asarray(tensor._value), _op_name(op)))
        else:
            # subgroup (new_group semantics / the mp group of a dp x mp
            # topology): member-only reduce over the TCPStore — non-members
            # are not involved, so member-only call patterns are safe
            from .store import create_or_get_global_tcp_store

            tensor._value = jnp.asarray(mp.store_allreduce_group(
                create_or_get_global_tcp_store(), np.asarray(tensor._value),
                g.ranks, _op_name(op), gid=g.id))
        return _Task(tensor)
    if _is_stacked(tensor, g):
        tensor._value = _reduce_stacked(tensor._value, op, g.nranks)
    # replicated tensor in single-controller: every rank already holds the
    # same value; reduction over identical copies is the value itself for
    # SUM only when contributions differ per process — multi-host handles
    # that inside compiled steps, not here.
    return _Task(tensor)


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    _record_collective("reduce", tensor)
    import jax.numpy as jnp

    g = _grp(group)
    if g.nranks == 1:
        return _Task(tensor)
    mp = _mp_active(g)
    if mp is not None:
        red = mp.allreduce_value(np.asarray(tensor._value), _op_name(op))
        if mp.rank() == dst:
            tensor._value = jnp.asarray(red)
        return _Task(tensor)
    if _is_stacked(tensor, g):
        red = _reduce_stacked(tensor._value, op, g.nranks)
        # only dst's slice carries the result; others keep their input
        idx = g.get_group_rank(dst) if dst in g.ranks else dst
        tensor._value = tensor._value.at[idx].set(red[idx])
    return _Task(tensor)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    """reference: dist.all_gather(list, t) — after the call the list holds
    every rank's tensor. Global-array view: slices of the stacked array;
    multi-controller: one compiled all-gather over the processes."""
    _record_collective("all_gather", tensor)
    g = _grp(group)
    if g.nranks == 1:
        tensor_list.append(Tensor(tensor._value))
        return _Task()
    mp = _mp_active(g)
    if mp is not None:
        import jax.numpy as jnp

        rows = mp.allgather_values(np.asarray(tensor._value))
        for i in range(rows.shape[0]):
            tensor_list.append(Tensor(jnp.asarray(rows[i])))
        return _Task()
    if _is_stacked(tensor, g) and tensor.ndim >= 1:
        for i in range(g.nranks):
            tensor_list.append(Tensor(tensor._value[i]))
    else:
        for _ in range(g.nranks):
            tensor_list.append(Tensor(tensor._value))
    return _Task()


def all_gather_object(object_list, obj, group=None):
    _record_collective("all_gather_object")
    g = _grp(group)
    if g.nranks == 1:
        object_list.append(obj)
        return _Task()
    mp = _mp_active(g)
    if mp is not None:
        object_list.extend(mp.allgather_objects(obj))
        return _Task()
    for _ in range(g.nranks):
        object_list.append(obj)
    return _Task()


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    """Each rank gets one shard of the reduction. Input: list of [nranks,...]
    stacked tensors (or tensors per destination)."""
    _record_collective("reduce_scatter", *tensor_list)
    import jax.numpy as jnp

    g = _grp(group)
    vals = [t._value if isinstance(t, Tensor) else jnp.asarray(t) for t in tensor_list]
    if g.nranks == 1:
        tensor._value = vals[0]
        return _Task(tensor)
    mp = _mp_active(g)
    if mp is not None:
        # rank r's output = reduction over processes of their tensor_list[r]
        rows = mp.allgather_values(np.stack([np.asarray(v) for v in vals]))
        mine = rows[:, mp.rank()]  # [nprocs, ...]
        red = {"sum": np.sum, "max": np.max, "min": np.min,
               "prod": np.prod, "avg": np.mean}[_op_name(op)](mine, axis=0)
        tensor._value = jnp.asarray(red)
        return _Task(tensor)
    stacked = jnp.stack(vals, axis=0)  # [nranks(dst), nranks(src)?...]
    if vals[0].ndim >= 1 and vals[0].shape[0] == g.nranks:
        # each list entry is itself stacked per-source: reduce over source
        # (axis 1 of [dst, src, ...]) so entry j keeps dst j's result
        red = _reduce_over_axis(stacked, op, axis=1)
        tensor._value = red if red.shape == tensor._value.shape else red.reshape(tensor._value.shape)
    else:
        red = _reduce_stacked(stacked, op, g.nranks)[0]
        tensor._value = jnp.broadcast_to(red, tensor._value.shape)
    return _Task(tensor)


def broadcast(tensor, src, group=None, sync_op=True):
    _record_collective("broadcast", tensor)
    import jax.numpy as jnp

    g = _grp(group)
    if g.nranks == 1:
        return _Task(tensor)
    mp = _mp_active(g)
    if mp is not None:
        tensor._value = jnp.asarray(
            mp.broadcast_value(np.asarray(tensor._value), src))
        return _Task(tensor)
    if _is_stacked(tensor, g):
        idx = g.get_group_rank(src) if src in g.ranks else src
        tensor._value = jnp.broadcast_to(tensor._value[idx:idx + 1], tensor._value.shape)
    return _Task(tensor)


def broadcast_object_list(object_list, src=0, group=None):
    _record_collective("broadcast_object_list")
    g = _grp(group)
    mp = _mp_active(g)
    if mp is not None:
        object_list[:] = mp.broadcast_objects(list(object_list), src)
    return _Task()


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    _record_collective("scatter", *(tensor_list or [tensor]))
    import jax.numpy as jnp

    g = _grp(group)
    if g.nranks == 1:
        if tensor_list:
            tensor._value = tensor_list[0]._value
        return _Task(tensor)
    mp = _mp_active(g)
    if mp is not None:
        payload = ([np.asarray(t._value) for t in tensor_list]
                   if mp.rank() == src and tensor_list else None)
        rows = mp.allgather_objects(payload)
        tensor._value = jnp.asarray(rows[src][mp.rank()])
        return _Task(tensor)
    if tensor_list:
        stacked = jnp.stack([t._value for t in tensor_list], axis=0)
        r = max(g.rank, 0)
        tensor._value = stacked[r]
    return _Task(tensor)


def scatter_object_list(out_object_list, in_object_list=None, src=0, group=None):
    _record_collective("scatter_object_list")
    g = _grp(group)
    if g.nranks == 1:
        if in_object_list:
            out_object_list.append(in_object_list[0])
        return _Task()
    mp = _mp_active(g)
    if mp is not None:
        payload = in_object_list if mp.rank() == src else None
        rows = mp.allgather_objects(payload)
        out_object_list.append(rows[src][mp.rank()])
        return _Task()
    if in_object_list:
        out_object_list.append(in_object_list[0])
    return _Task()


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """rank i sends in[j] to rank j: transpose of the (src, dst) grid.
    Counted under the canonical op="all_to_all" label (shared with
    alltoall_single and the MoE global_scatter/gather paths — which add
    the kind="a2a" comm_task intervals at THEIR level, so per-desc
    exposure reports never double-attribute the same wall time to a
    nested pair; ISSUE-14 satellite)."""
    _record_collective("all_to_all", *in_tensor_list)
    import jax.numpy as jnp

    g = _grp(group)
    n = g.nranks
    vals = [t._value for t in in_tensor_list]
    if n == 1:
        for v in vals:
            out_tensor_list.append(Tensor(v))
        return _Task()
    mp = _mp_active(g)
    if mp is not None:
        rows = mp.allgather_values(np.stack([np.asarray(v) for v in vals]))
        for j in range(n):  # out[j] = what process j put at slot my_rank
            out_tensor_list.append(Tensor(jnp.asarray(rows[j, mp.rank()])))
        return _Task()
    # single-controller stacked view: in_tensor_list[j][i] is what rank i
    # sends to rank j when entries are stacked; plain view: identity permute
    if vals and vals[0].ndim >= 1 and vals[0].shape[0] == n:
        stacked = jnp.stack(vals, axis=0)  # [dst, src, ...]
        swapped = jnp.swapaxes(stacked, 0, 1)
        for j in range(n):
            out_tensor_list.append(Tensor(swapped[j]))
    else:
        for v in vals:
            out_tensor_list.append(Tensor(v))
    return _Task()


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None, out_split_sizes=None, group=None, sync_op=True):
    """Single-tensor all-to-all. Stacked global view [src, dst_chunks...]:
    rank i's row is the concat of chunks for each destination, so the global
    transform is the (src, dst) chunk-grid transpose — identical to what
    lax.all_to_all compiles to over a mesh axis. Counted as
    op="all_to_all"; interval attribution lives with the MoE-level
    wrappers (moe_utils global_scatter/gather) so nested calls never
    double-report the same wall time."""
    _record_collective("all_to_all", in_tensor)
    import jax.numpy as jnp

    g = _grp(group)
    n = g.nranks
    v = in_tensor._value
    if in_split_sizes is not None or out_split_sizes is not None:
        raise NotImplementedError(
            "unequal split sizes are not supported by the eager "
            "alltoall_single; use equal chunks or the compiled primitives"
        )
    if n == 1:
        out_tensor._value = v
        return _Task(out_tensor)
    mp = _mp_active(g)
    if mp is not None:
        out_tensor._value = jnp.asarray(
            mp.alltoall_single_value(np.asarray(v), n))
        return _Task(out_tensor)
    if n > 1 and v.ndim >= 1 and v.shape[0] % (n * n) == 0:
        # full stacked view: [src(n) * dst(n) * per, ...]
        per = v.shape[0] // (n * n)
        grid = v.reshape(n, n, per, *v.shape[1:])  # [src, dst, per, ...]
        out_tensor._value = jnp.swapaxes(grid, 0, 1).reshape(v.shape)
    elif n > 1:
        # the stacked-view heuristic cannot represent this shape; a silent
        # identity here would be wrong data, not a degraded mode
        raise ValueError(
            f"eager alltoall_single needs a [src*dst*k, ...] stacked view "
            f"(leading dim divisible by nranks^2={n * n}); got shape "
            f"{tuple(v.shape)}. Use the compiled primitives inside "
            f"shard_map for per-rank tensors.")
    else:
        out_tensor._value = v
    return _Task(out_tensor)


# -- p2p: host-side mailbox for single-controller API parity ----------------- #
# FIFO channels keyed (group id, src, dst). The single controller plays every
# rank, so recv matches on src and falls back to any destination — a
# send(dst=j) / recv(src=i) pair always pairs up regardless of which "rank"
# the caller is emulating (reference: ncclSend/ncclRecv rendezvous).

_mailbox: dict = {}


def send(tensor, dst=0, group=None, sync_op=True):
    _record_collective("send", tensor)
    g = _grp(group)
    mp = _mp_active(g)
    if mp is not None:
        from .store import create_or_get_global_tcp_store

        mp.p2p_send(create_or_get_global_tcp_store(), tensor._value,
                    mp.rank(), dst)
        return _Task()
    src = max(g.rank, 0)
    _mailbox.setdefault((g.id, src, dst), []).append(tensor._value)
    return _Task()


def recv(tensor, src=0, group=None, sync_op=True):
    _record_collective("recv", tensor)
    import jax.numpy as jnp

    g = _grp(group)
    mp = _mp_active(g)
    if mp is not None:
        from .store import create_or_get_global_tcp_store

        tensor._value = jnp.asarray(
            mp.p2p_recv(create_or_get_global_tcp_store(), src, mp.rank()))
        return _Task(tensor)
    me = max(g.rank, 0)
    # single-controller: the process plays every rank, so src/dst stamps on
    # both sides reflect the controller's rank, not the emulated one. Match
    # progressively: exact channel, then same-src any-dst, then any pending
    # message in the group (FIFO pairing, like an in-order rendezvous).
    box = _mailbox.get((g.id, src, me))
    if not box:
        box = next(
            (b for (gid, s, _d), b in _mailbox.items() if gid == g.id and s == src and b),
            None,
        )
    if not box:
        box = next(
            (b for (gid, _s, _d), b in _mailbox.items() if gid == g.id and b),
            None,
        )
    if box:
        tensor._value = box.pop(0)
    return _Task(tensor)


isend = send
irecv = recv


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """reference: python/paddle/distributed/communication/batch_isend_irecv.py."""
    tasks = []
    for op in p2p_op_list:
        tasks.append(op.op(op.tensor, op.peer, group=op.group))
    return tasks


def barrier(group=None):
    _record_collective("barrier")
    import jax

    g = _grp(group)
    if g.nranks == 1:
        return _Task()
    mp = _mp_active(g)
    if mp is not None:
        mp.barrier()
        return _Task()
    jax.effects_barrier() if hasattr(jax, "effects_barrier") else None
    return _Task()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and hasattr(tensor._value, "block_until_ready"):
        tensor._value.block_until_ready()


# --------------------------------------------------------------------------- #
# compiled-form primitives (use inside shard_map)
# --------------------------------------------------------------------------- #


class primitives:
    """Collectives for use inside shard_map'd programs; `axis` is a mesh axis
    name (or tuple). These ARE the ICI collectives after XLA lowering —
    the compiled counterpart of NCCLCommContext::AllReduce
    (paddle/phi/core/distributed/nccl_comm_context.cc:184)."""

    @staticmethod
    def all_reduce(x, axis="mp", op="sum"):
        import jax

        if op == "sum":
            return jax.lax.psum(x, axis)
        if op == "max":
            return jax.lax.pmax(x, axis)
        if op == "min":
            return jax.lax.pmin(x, axis)
        if op == "avg":
            return jax.lax.pmean(x, axis)
        raise ValueError(op)

    @staticmethod
    def all_gather(x, axis="mp", concat_axis=0, tiled=True):
        import jax

        return jax.lax.all_gather(x, axis, axis=concat_axis, tiled=tiled)

    @staticmethod
    def reduce_scatter(x, axis="mp", scatter_axis=0):
        import jax

        return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)

    @staticmethod
    def all_to_all(x, axis="mp", split_axis=0, concat_axis=0):
        import jax

        return jax.lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True)

    @staticmethod
    def ppermute(x, axis, perm):
        import jax

        return jax.lax.ppermute(x, axis, perm)

    @staticmethod
    def axis_index(axis):
        import jax

        return jax.lax.axis_index(axis)
