"""Analytic half of the mesh planner's hybrid cost model.

Predicts per-config step time as

    total = compute + bubble + exposed_comm

* **compute** — a roofline max(FLOPs / (ndev * peak * mfu), hbm_bytes / hbm_bw)
  over the decoder FLOPs formula bench.py uses for its MFU denominator
  (6ND + attention quadratic), with a 4/3 recompute multiplier (recompute
  re-runs the forward inside the backward: 8N vs 6N per token).
* **bubble** — the 1F1B pipeline bubble `compute * (pp-1)/n_micro`
  (arxiv 1909.09756 hand-tuned exactly this trade on TPU-v3 pods).
* **exposed_comm** — per-axis collective byte volumes over ICI, plus a
  per-collective launch latency `alpha` (the term that dominates at small
  message sizes), discounted by the MEASURED `overlap_fraction` from the
  step-timeline JSONL when BENCH history is available — the measured half
  of the hybrid (arxiv 2011.03641: pod-scale loss is mostly exposed
  collectives, which is precisely what overlap_fraction tracks).

The table below is THE peak table: bench.py's `_peak_flops()` resolves
through `PEAK_BF16_FLOPS`, so the bench MFU denominator and the planner's
compute term can never disagree about what a chip can do.

Byte-volume conventions (documented in docs/PLANNER.md):
- ring all-reduce moves `2*(g-1)/g * bytes` per participant, reduce-scatter
  and all-gather `(g-1)/g * bytes`;
- grads are counted at 4 B/elem (f32 reduction), params and activations at
  2 B/elem (bf16 compute);
- sharding stage 1 all-reduces grads over the combined dp*sharding group;
  stages 2/3 reduce-scatter grads + all-gather updated params over
  `sharding` (stage 3 adds the fwd+bwd param all-gathers) with the dp
  all-reduce on top;
- mp all-reduces move the activation block 4x per layer per microbatch
  (attn out + mlp out, forward and backward); pp p2p moves it twice per
  microbatch per stage boundary.
"""

from __future__ import annotations

import json
import os

__all__ = ["CHIP_SPECS", "PEAK_BF16_FLOPS", "chip_specs", "CostModel",
           "measured_overlap_fraction"]

# chip kind -> (peak bf16 FLOP/s, HBM bytes/s, ICI bytes/s) per chip
# (public spec sheets; ICI is the per-chip aggregate link bandwidth)
CHIP_SPECS = {
    "TPU v2": (22.5e12, 0.70e12, 0.10e12),
    "TPU v3": (61.0e12, 0.90e12, 0.14e12),  # per chip (2 cores)
    "TPU v4": (137.5e12, 1.20e12, 0.27e12),  # per chip (megacore)
    "TPU v5 lite": (197e12, 0.82e12, 0.20e12),
    "TPU v5e": (197e12, 0.82e12, 0.20e12),
    "TPU v5": (229.5e12, 2.77e12, 0.60e12),
    "TPU v5p": (229.5e12, 2.77e12, 0.60e12),
    "TPU v6 lite": (459e12, 1.64e12, 0.36e12),
    "TPU v6e": (459e12, 1.64e12, 0.36e12),
    "TPU7x": (2307e12, 7.40e12, 1.20e12),
}

# chip kind -> peak bf16 FLOP/s (bench.py imports this as its _PEAK table)
PEAK_BF16_FLOPS = {k: v[0] for k, v in CHIP_SPECS.items()}

# CPU smoke runs / unknown chips: assume v4-class (bench.py's fallback)
_DEFAULT_KIND = "TPU v4"


def chip_specs(device=None):
    """(peak_flops, hbm_Bps, ici_Bps, kind) for a jax device; `None` or an
    unknown kind falls back to v4-class numbers so CPU smoke planning still
    ranks (the ranking, not the absolute seconds, is what survives the
    fallback)."""
    kind = getattr(device, "device_kind", "") if device is not None else ""
    for k, v in CHIP_SPECS.items():
        if kind.startswith(k) or k in kind:
            return v[0], v[1], v[2], kind
    v = CHIP_SPECS[_DEFAULT_KIND]
    return v[0], v[1], v[2], kind or "unknown"


def measured_overlap_fraction(paths=None):
    """The measured half of the hybrid: aggregate comm/compute
    `overlap_fraction` out of step-timeline JSONL records (bench.py
    --emit-metrics) and/or BENCH_*.json perf lines.

    `paths`: a path, a list of paths, or None (read the os.pathsep-separated
    PADDLE_TPU_PLAN_OVERLAP_JSONL env). Returns (fraction, source) or
    (None, None) when no history is available — the caller falls back to
    the conservative all-comm-exposed default.
    """
    if paths is None:
        env = os.environ.get("PADDLE_TPU_PLAN_OVERLAP_JSONL", "")
        paths = [p for p in env.split(os.pathsep) if p]
    elif isinstance(paths, str):
        paths = [paths]
    overlaps, fracs = [], []
    for path in paths:
        if not path or not os.path.exists(path):
            continue
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if isinstance(rec.get("overlap"), dict):
                    overlaps.append(rec["overlap"])
                elif "overlap_fraction" in rec:
                    f_ = float(rec["overlap_fraction"])
                    # overlap_stats reports 1.0 for a ZERO-comm step
                    # ("nothing was exposed"); a bare perf line carries no
                    # comm_s to tell that sentinel from genuinely perfect
                    # overlap, and taking it at face value would rank
                    # pod-scale meshes as if collectives were free — skip it
                    if f_ < 1.0:
                        fracs.append(f_)
    if overlaps:
        from ...observability.spans import aggregate_overlap

        agg = aggregate_overlap(overlaps)
        if agg["comm_s"] > 0:
            return agg["fraction"], f"step_timeline:{len(overlaps)}_records"
    if fracs:
        return (round(sum(fracs) / len(fracs), 6),
                f"bench_lines:{len(fracs)}_records")
    return None, None


class CostModel:
    """Analytic roofline + measured-overlap discount over a tuner candidate
    grid. Stateless per prediction; construct once per (chip, history) pair.

    Parameters
    ----------
    device : jax Device | None
        Chip to read the spec table for (None: v4-class fallback; never
        touches the backend, so planning works before/without jax init).
    peak_flops, hbm_bandwidth, ici_bandwidth : float | None
        Explicit overrides of the spec-table numbers.
    mfu : float
        Achievable fraction of peak for the compute term (calibration knob;
        0.4 tracks the measured gpt3 ladder). Affects absolute predictions,
        not the ranking.
    alpha : float
        Per-collective launch latency in seconds. This is what separates
        the latency-bound regime (tiny messages: collective COUNT dominates)
        from the bandwidth-bound one (byte volume dominates).
    overlap_fraction : float | None
        Fraction of comm covered by compute. None: resolve from
        `overlap_paths` / PADDLE_TPU_PLAN_OVERLAP_JSONL via
        `measured_overlap_fraction`, defaulting to 0.0 (all comm exposed).
    """

    def __init__(self, device=None, peak_flops=None, hbm_bandwidth=None,
                 ici_bandwidth=None, mfu=0.4, alpha=5e-6,
                 overlap_fraction=None, overlap_paths=None, a2a_chunks=None):
        peak, hbm, ici, kind = chip_specs(device)
        self.peak_flops = peak_flops or peak
        self.hbm_bandwidth = hbm_bandwidth or hbm
        self.ici_bandwidth = ici_bandwidth or ici
        self.chip = kind
        self.mfu = mfu
        self.alpha = alpha
        # MoE dispatch/combine chunking: more chunks = more a2a launches
        # (the alpha/latency term) buying overlap; the byte volume is
        # chunk-invariant. None resolves the SAME env knob the runtime
        # schedule honors (PADDLE_TPU_MOE_A2A_CHUNKS, default 2, clamp
        # [1, 8]) so predictions cost the schedule that will actually run.
        if a2a_chunks is None:
            try:
                a2a_chunks = int(
                    os.environ.get("PADDLE_TPU_MOE_A2A_CHUNKS", "2"))
            except ValueError:
                a2a_chunks = 2
        self.a2a_chunks = max(1, min(int(a2a_chunks), 8))
        if overlap_fraction is not None:
            self.overlap_fraction = float(overlap_fraction)
            self.overlap_source = "explicit"
        else:
            frac, src = measured_overlap_fraction(overlap_paths)
            self.overlap_fraction = 0.0 if frac is None else frac
            self.overlap_source = src or "default_all_exposed"

    # ------------------------------------------------------------------ #

    def predict(self, tuner_cfg, cfg):
        """Cost breakdown dict for one candidate config (JSON-native: no
        infinities — infeasible-memory configs are the prunes' job, this
        reports `mem_ok` and lets the planner decide)."""
        from ..auto_tuner.tuner import (estimate_memory_bytes,
                                        params_per_device)

        model = tuner_cfg.get("model_cfg", {})
        h = model.get("hidden_size", 0)
        L = max(model.get("num_layers", 1), 1)
        vocab = model.get("vocab_size", 0)
        seq = model.get("seq_length", 1024)
        dp, mp = cfg["dp_degree"], cfg["mp_degree"]
        pp, sh = cfg["pp_degree"], cfg["sharding_degree"]
        ep = cfg.get("ep_degree", 1)
        stage = cfg.get("sharding_stage", 1) if sh > 1 else 0
        mbs = cfg["micro_batch_size"]
        gbs = cfg.get("global_batch_size",
                      tuner_cfg.get("global_batch_size", 8))
        ndev = dp * mp * pp * sh * ep
        n_micro = max(gbs // max(dp * sh * mbs, 1), 1)

        # -- compute roofline ------------------------------------------- #
        tokens = gbs * seq
        body = 12.0 * L * h * h          # transformer block params
        emb = float(vocab * h)           # vocab embedding params
        flops = 6.0 * (body + emb) * tokens + 12.0 * L * h * seq * tokens
        mult = 4.0 / 3.0 if cfg.get("use_recompute") else 1.0
        flops_s = flops * mult / (ndev * self.peak_flops * self.mfu)
        # per-device params via the ONE encoding of the placement split
        # rules (shared with estimate_memory_bytes — see params_per_device)
        body_dev, emb_dev = params_per_device(model, cfg)
        params_dev = body_dev + emb_dev
        # HBM traffic: read bf16 params + f32 master/moments, write them
        # back (~28 B/param-shard) + one activation block per layer held
        acts_dev = n_micro * mbs * seq * h * (L / pp)
        hbm_bytes = 28.0 * params_dev + 2.0 * acts_dev
        hbm_s = hbm_bytes / self.hbm_bandwidth
        compute_s = max(flops_s, hbm_s)
        bubble_s = compute_s * (pp - 1) / n_micro if pp > 1 else 0.0

        # -- per-axis collective volumes -------------------------------- #
        comm_bytes, comm_count = {}, {}
        act_block = mbs * seq * h * 2.0  # bf16 activation microbatch block
        if stage >= 2:
            comm_bytes["sharding_rs"] = (sh - 1) / sh * params_dev * 4.0
            ag = (sh - 1) / sh * params_dev * 2.0  # updated-param gather
            if stage >= 3:
                ag += 2.0 * (sh - 1) / sh * params_dev * 2.0  # fwd+bwd
            comm_bytes["sharding_ag"] = ag
            comm_count["sharding_rs"] = 1
            comm_count["sharding_ag"] = 1 if stage < 3 else 3
            dp_group = dp
        else:
            # stage 0/1: grads all-reduced over the combined replica group
            dp_group = dp * sh
        if dp_group > 1:
            comm_bytes["dp_allreduce"] = \
                2.0 * (dp_group - 1) / dp_group * params_dev * 4.0
            comm_count["dp_allreduce"] = 2  # bucketed, a handful of launches
        if mp > 1:
            comm_bytes["mp_allreduce"] = (4.0 * (L / pp) * n_micro * act_block
                                          * 2.0 * (mp - 1) / mp)
            comm_count["mp_allreduce"] = int(4 * (L // pp or 1) * n_micro)
        if pp > 1:
            comm_bytes["pp_p2p"] = 2.0 * n_micro * act_block
            comm_count["pp_p2p"] = 2 * n_micro
        if ep > 1:
            # MoE dispatch + combine all-to-alls (ISSUE-14): per MoE layer
            # per microbatch, top-k routed copies of the activation block
            # reshard token->expert and back; a2a moves (ep-1)/ep of the
            # payload off-chip. The launch count scales with the chunk
            # schedule (the latency-bound alpha regime — chunking buys
            # overlap at the price of more launches), the byte volume does
            # not.
            topk = model.get("moe_top_k", 2)
            moe_layers = max(model.get("moe_layers", L), 1)
            comm_bytes["ep_a2a"] = (2.0 * moe_layers * n_micro * topk
                                    * act_block * (ep - 1) / ep)
            comm_count["ep_a2a"] = int(2 * moe_layers * n_micro
                                       * self.a2a_chunks)
        comm_s_by_axis = {
            k: v / self.ici_bandwidth + self.alpha * comm_count.get(k, 1)
            for k, v in comm_bytes.items()
        }
        comm_s = sum(comm_s_by_axis.values())
        exposed_s = comm_s * (1.0 - self.overlap_fraction)

        mem = estimate_memory_bytes(tuner_cfg, cfg)
        cap = tuner_cfg.get("max_mem_usage_bytes")
        return {
            "total_s": round(compute_s + bubble_s + exposed_s, 9),
            "compute_s": round(compute_s, 9),
            "bubble_s": round(bubble_s, 9),
            "comm_s": round(comm_s, 9),
            "exposed_comm_s": round(exposed_s, 9),
            "comm_s_by_axis": {k: round(v, 9)
                               for k, v in comm_s_by_axis.items()},
            "comm_bytes_by_axis": {k: round(v, 1)
                                   for k, v in comm_bytes.items()},
            "mem_estimate_bytes": round(mem, 1),
            "mem_ok": bool(cap is None or mem <= cap),
            "n_micro": n_micro,
            "overlap_fraction": self.overlap_fraction,
            "overlap_source": self.overlap_source,
            "chip": self.chip,
            "mfu_assumed": self.mfu,
        }

    def step_time(self, tuner_cfg, cfg) -> float:
        return self.predict(tuner_cfg, cfg)["total_s"]
