"""Mesh planner: rank the full candidate grid analytically, measure only a
top-K shortlist.

The existing auto-tuner (`auto_tuner/tuner.py`) times real steps for every
surviving grid point — sound at 8 devices, unaffordable at pod scale. The
planner in front of it:

1. `rank_candidates` — run the static prunes over the full grid, predict
   every survivor's step time with the analytic `CostModel`, sort.
2. `shortlist` — keep the top K (default 5) and hand ONLY those to the
   existing `tune()` measurement loop.
3. `plan_and_tune` — measure the shortlist, record predicted-vs-measured
   error per trial into the Recorder history (the model is falsifiable:
   tools/plan_report.py prints the table), and emit the winning `MeshPlan`.
4. `analytic_plan` — the measurement-free fast path an elastic restart
   uses to adopt a mesh for a changed device count without burning a
   cluster on trials (ResilientTrainer calls this).

Counters/spans flow through the observability registry (catalog rows in
docs/OBSERVABILITY.md).
"""

from __future__ import annotations

from ...observability import metrics as _metrics
from ...observability import spans as _spans
from ..auto_tuner.tuner import AutoTuner, Recorder, tune
from .cost_model import CostModel
from .layout import MeshPlan

__all__ = ["DEFAULT_TOP_K", "rank_candidates", "shortlist", "plan_and_tune",
           "analytic_plan"]

DEFAULT_TOP_K = 5

_pm = _metrics.HandleCache(lambda reg: {
    "candidates": reg.counter(
        "planner_candidates_total",
        "mesh candidates considered by the analytic ranking"),
    "pruned": reg.counter(
        "planner_pruned_total",
        "mesh candidates rejected by static pruning", labelnames=("reason",)),
    "shortlisted": reg.counter(
        "planner_shortlisted_total",
        "mesh candidates kept for measurement"),
    "measured": reg.counter(
        "planner_measured_trials_total",
        "shortlist trials actually timed by tune()"),
    "replans": reg.counter(
        "planner_replans_total",
        "analytic re-plans triggered by a changed device count"),
    "err": reg.gauge(
        "planner_prediction_error_pct",
        "abs(predicted-measured)/measured of the latest measured trial"),
})


def _cfg_key(cfg):
    """Identity of a candidate across planner/tuner bookkeeping."""
    return (cfg["dp_degree"], cfg["mp_degree"], cfg["pp_degree"],
            cfg["sharding_degree"], cfg.get("ep_degree", 1),
            cfg.get("sharding_stage", 1),
            cfg["micro_batch_size"], bool(cfg.get("use_recompute")))


def rank_candidates(tuner_cfg, cost_model=None):
    """(ranked, pruned): ranked = [(cfg, breakdown)] sorted by predicted
    step time over every statically-feasible grid point; pruned =
    [(cfg, prune_rule_name, reason)]. No measurement happens here."""
    cm = cost_model or CostModel()
    tuner = AutoTuner(dict(tuner_cfg, task_limit=10 ** 9))
    survivors = []
    with _spans.span("planner/rank"):
        while True:
            cfg = tuner.search_once()
            if cfg is None:
                break
            survivors.append(cfg)
        ranked = sorted(
            ((cfg, cm.predict(tuner_cfg, cfg)) for cfg in survivors),
            key=lambda t: t[1]["total_s"])
    pruned = list(tuner.pruned)
    pm = _pm.get()
    pm["candidates"].inc(len(survivors) + len(pruned))
    for _cfg, rule, _r in pruned:
        pm["pruned"].inc(reason=rule)
    return ranked, pruned


def shortlist(tuner_cfg, top_k=DEFAULT_TOP_K, cost_model=None):
    """Top-K analytically-ranked candidates: [(cfg, breakdown)]."""
    ranked, _pruned = rank_candidates(tuner_cfg, cost_model)
    kept = ranked[:top_k]
    _pm.get()["shortlisted"].inc(len(kept))
    return kept


def analytic_plan(tuner_cfg, cost_model=None, model_cfg=None) -> MeshPlan:
    """Measurement-free fast path: the analytic top-1 as a MeshPlan.
    Raises if the grid has no feasible candidate (a device count the model
    cannot factorize onto is a config error, not a plan)."""
    ranked, pruned = rank_candidates(tuner_cfg, cost_model)
    if not ranked:
        raise ValueError(
            f"no feasible mesh candidate for num_devices="
            f"{tuner_cfg.get('num_devices')}; pruned: "
            + "; ".join(f"{r}" for _c, _n, r in pruned[:5]))
    cfg, breakdown = ranked[0]
    return MeshPlan.from_candidate(
        cfg, breakdown, model_cfg=model_cfg or tuner_cfg.get("model_cfg"),
        source="analytic")


def plan_and_tune(model_builder, loss_fn, optimizer_builder, tuner_cfg,
                  top_k=DEFAULT_TOP_K, cost_model=None, devices=None,
                  steps=2, recorder=None):
    """The hybrid loop: analytic shortlist -> measured trials -> MeshPlan.

    Returns (plan, best_cfg, recorder). The recorder history carries, per
    measured trial, `predicted_step_time` and `prediction_error_pct`
    (signed, (pred-meas)/meas*100) so the analytic model is falsifiable
    against exactly the trials it selected; the planner's pruned configs
    land in the history as `pruned=<reason>` rows (via tune()) for
    shortlist reports. Configs the analytic ranking REJECTED (beyond
    top-K) are recorded with `pruned="analytic rank > K"`.
    """
    cm = cost_model or CostModel()
    recorder = recorder or Recorder()
    ranked, pruned = rank_candidates(tuner_cfg, cm)
    if not ranked:
        raise ValueError(
            f"no feasible mesh candidate for num_devices="
            f"{tuner_cfg.get('num_devices')}; pruned: "
            + "; ".join(f"{r}" for _c, _n, r in pruned[:5]))
    kept, rejected = ranked[:top_k], ranked[top_k:]
    pm = _pm.get()
    pm["shortlisted"].inc(len(kept))
    predicted = {_cfg_key(cfg): bd for cfg, bd in ranked}
    measure_cfg = dict(tuner_cfg, candidates=[dict(cfg) for cfg, _ in kept])
    # only THIS call's trials get attributed: a caller-supplied recorder
    # may carry an earlier sweep whose entries must not be re-stamped (or
    # re-counted into planner_measured_trials_total)
    n_prior = len(recorder.history)
    with _spans.span("planner/measure"):
        best, recorder = tune(model_builder, loss_fn, optimizer_builder,
                              measure_cfg, devices=devices, steps=steps,
                              recorder=recorder)
    for entry in recorder.history[n_prior:]:
        if "dp_degree" not in entry:
            continue
        bd = predicted.get(_cfg_key(entry))
        if bd is None:
            continue
        entry["predicted_step_time"] = bd["total_s"]
        meas = entry.get("step_time")
        if meas:
            pm["measured"].inc()
            err = (bd["total_s"] - meas) / meas * 100.0
            entry["prediction_error_pct"] = round(err, 2)
            pm["err"].set(abs(err))
    for cfg, bd in rejected:
        recorder.add_cfg(**cfg, mem_estimate=bd["mem_estimate_bytes"],
                         predicted_step_time=bd["total_s"],
                         pruned=f"analytic rank > {top_k}")
    # a caller-supplied recorder may carry history from an earlier sweep;
    # get_best can then name a config outside this grid — predict it fresh
    best_bd = predicted.get(_cfg_key(best)) if best is not None else None
    if best is not None:
        plan = MeshPlan.from_candidate(
            {k: best[k] for k in ("dp_degree", "mp_degree", "pp_degree",
                                  "sharding_degree", "ep_degree",
                                  "sharding_stage",
                                  "micro_batch_size", "use_recompute",
                                  "global_batch_size") if k in best},
            best_bd if best_bd is not None else cm.predict(tuner_cfg, best),
            model_cfg=tuner_cfg.get("model_cfg"),
            measured_step_time_s=best["step_time"], source="measured")
    else:
        # every shortlist trial errored (OOM storm): fall back to the
        # analytic winner so the caller still gets an adoptable plan
        plan = MeshPlan.from_candidate(
            kept[0][0], kept[0][1],
            model_cfg=tuner_cfg.get("model_cfg"), source="analytic")
    return plan, best, recorder


def note_replan(old_devices, new_devices):
    """Counter hook for ResilientTrainer's elastic adoption path."""
    _pm.get()["replans"].inc()
