"""Canonical layout plans: SpecLayout + the serialized MeshPlan artifact.

`SpecLayout` names the per-param-group PartitionSpecs the
DistributedTrainStep placement actually uses over the hybrid mesh axes
[dp, pp, sharding, sep, mp] — vocab-parallel embeddings, column/row TP
linears, norms, the (dp, sharding)-sharded batch — with the stage-3 FSDP
split folded in the same way `fsdp_spec` folds it (shard the largest free
dim, respect dims already taken by TP).

`MeshPlan` is the canonical artifact the planner emits and the
ResilientTrainer adopts across elastic restarts: mesh shape, knobs
(mbs/recompute/stage), per-group layouts, and the cost breakdown that
justified the choice — serialized to JSON losslessly (docs/PLANNER.md
documents the schema).
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import asdict, dataclass, field

__all__ = ["SpecLayout", "MeshPlan", "spec_to_json", "spec_from_json"]

PLAN_FILENAME = "mesh_plan.json"
_PLAN_VERSION = 1


def spec_to_json(spec):
    """PartitionSpec -> JSON-native list (None | str | [str, ...] entries)."""
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def spec_from_json(entries):
    from jax.sharding import PartitionSpec as P

    return P(*[tuple(e) if isinstance(e, list) else e for e in entries])


@dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs for decoder params/activations on the hybrid
    mesh. `fsdp` mirrors sharding stage 3: the `sharding` axis claims the
    largest dim TP left free (exactly what `shard_params_for_stage3` +
    `fsdp_spec` compute per-tensor at train-step construction)."""

    dp_axis: str = "dp"
    pp_axis: str = "pp"
    sharding_axis: str = "sharding"
    ep_axis: str = "ep"
    mp_axis: str = "mp"
    fsdp: bool = False
    batch_sharded: bool = True  # batch also split over `sharding` (ZeRO dp)

    def vocab_embedding(self):
        """[vocab, h]: vocab over mp (VocabParallelEmbedding); FSDP takes h."""
        from jax.sharding import PartitionSpec as P

        return P(self.mp_axis, self.sharding_axis if self.fsdp else None)

    def column_parallel(self):
        """[in, out] with out-features over mp; FSDP takes the in dim."""
        from jax.sharding import PartitionSpec as P

        return P(self.sharding_axis if self.fsdp else None, self.mp_axis)

    def row_parallel(self):
        """[in, out] with in-features over mp; FSDP takes the out dim."""
        from jax.sharding import PartitionSpec as P

        return P(self.mp_axis, self.sharding_axis if self.fsdp else None)

    def expert_stacked(self):
        """[E, ...] expert-stacked MoE weights (ExpertFFN w1/b1/w2/b2):
        the expert dim shards over `ep` (expert parallelism, ISSUE-14);
        FSDP takes the next free dim like the TP layouts do."""
        from jax.sharding import PartitionSpec as P

        return P(self.ep_axis, self.sharding_axis if self.fsdp else None)

    def norm(self):
        """1-D scale/bias: FSDP shards the only dim, else replicated."""
        from jax.sharding import PartitionSpec as P

        return P(self.sharding_axis) if self.fsdp else P()

    def replicated(self):
        from jax.sharding import PartitionSpec as P

        return P()

    def activations(self):
        """[batch, seq, h]: batch over (dp, sharding) — the train step's
        `batch_axes` — seq/h unsharded at rest (mp constraints are applied
        inside the layers, not at the batch boundary)."""
        from jax.sharding import PartitionSpec as P

        if self.batch_sharded:
            return P((self.dp_axis, self.sharding_axis), None, None)
        return P(self.dp_axis, None, None)

    def groups(self) -> dict:
        """group name -> PartitionSpec, the planner's canonical set."""
        return {
            "vocab_embedding": self.vocab_embedding(),
            "column_parallel": self.column_parallel(),
            "row_parallel": self.row_parallel(),
            "expert_stacked": self.expert_stacked(),
            "norm": self.norm(),
            "replicated": self.replicated(),
            "activations": self.activations(),
        }


@dataclass
class MeshPlan:
    """The canonical plan artifact. All fields JSON-native; `layouts` holds
    serialized PartitionSpecs (see spec_to_json) so the file round-trips
    losslessly and diffs cleanly in review."""

    mesh: dict            # axis -> size over AXIS_ORDER
    num_devices: int
    global_batch_size: int
    micro_batch_size: int
    use_recompute: bool
    sharding_stage: int
    layouts: dict         # group -> serialized spec
    cost: dict            # CostModel.predict breakdown
    predicted_step_time_s: float
    measured_step_time_s: float | None = None
    source: str = "analytic"     # "analytic" | "measured"
    model_cfg: dict = field(default_factory=dict)
    version: int = _PLAN_VERSION

    # ------------------------------------------------------------------ #

    @classmethod
    def from_candidate(cls, cfg, breakdown, *, model_cfg=None,
                       measured_step_time_s=None, source="analytic"):
        """Build a plan from a tuner candidate dict + its cost breakdown."""
        sh = cfg["sharding_degree"]
        stage = cfg.get("sharding_stage", 1) if sh > 1 else 0
        layout = SpecLayout(fsdp=stage >= 3 and sh > 1, batch_sharded=sh > 1)
        ep = int(cfg.get("ep_degree", 1))
        mesh = {"dp": cfg["dp_degree"], "pp": cfg["pp_degree"],
                "sharding": sh, "sep": 1, "ep": ep, "mp": cfg["mp_degree"]}
        return cls(
            mesh=mesh,
            num_devices=int(cfg["dp_degree"] * cfg["pp_degree"]
                            * sh * ep * cfg["mp_degree"]),
            global_batch_size=int(cfg.get("global_batch_size", 8)),
            micro_batch_size=int(cfg["micro_batch_size"]),
            use_recompute=bool(cfg.get("use_recompute", False)),
            sharding_stage=int(stage),
            layouts={k: spec_to_json(v)
                     for k, v in layout.groups().items()},
            cost=dict(breakdown),
            predicted_step_time_s=float(breakdown["total_s"]),
            measured_step_time_s=(None if measured_step_time_s is None
                                  else float(measured_step_time_s)),
            source=source,
            model_cfg=dict(model_cfg or {}),
        )

    def tuner_candidate(self) -> dict:
        """Back to the tuner's candidate-dict shape (plan -> re-measure)."""
        return {
            "dp_degree": self.mesh["dp"], "mp_degree": self.mesh["mp"],
            "pp_degree": self.mesh["pp"],
            "sharding_degree": self.mesh["sharding"],
            "ep_degree": self.mesh.get("ep", 1),
            "sharding_stage": self.sharding_stage or 1,
            "micro_batch_size": self.micro_batch_size,
            "use_recompute": self.use_recompute,
            "global_batch_size": self.global_batch_size,
        }

    def partition_specs(self) -> dict:
        """group name -> live PartitionSpec objects."""
        return {k: spec_from_json(v) for k, v in self.layouts.items()}

    def build_mesh(self, devices=None):
        """Materialize the plan's mesh (sets the global mesh, same contract
        as env.build_mesh)."""
        from .. import env as _env

        return _env.build_mesh(
            dp=self.mesh["dp"], pp=self.mesh["pp"],
            sharding=self.mesh["sharding"], sep=self.mesh.get("sep", 1),
            ep=self.mesh.get("ep", 1), mp=self.mesh["mp"], devices=devices)

    def describe(self) -> str:
        m = self.mesh
        ep = m.get("ep", 1)
        return (f"dp{m['dp']}xpp{m['pp']}xsharding{m['sharding']}"
                + (f"xep{ep}" if ep > 1 else "")
                + f"xmp{m['mp']} stage{self.sharding_stage} "
                f"mbs{self.micro_batch_size} "
                f"rc={'on' if self.use_recompute else 'off'} "
                f"predicted {self.predicted_step_time_s:.6f}s "
                f"({self.source})")

    # -- JSON round trip ------------------------------------------------ #

    def to_dict(self) -> dict:
        return asdict(self)

    def to_json(self, indent=2) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "MeshPlan":
        d = dict(d)
        d.pop("version", None)
        return cls(**d, version=_PLAN_VERSION)

    @classmethod
    def from_json(cls, s: str) -> "MeshPlan":
        return cls.from_dict(json.loads(s))

    def save(self, path: str):
        """Atomic write (tmp + rename in the target dir — the same
        crash-safety stance as the checkpoint COMMIT protocol: a torn plan
        file must never be adoptable)."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".plan.tmp")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(self.to_json())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    @classmethod
    def load(cls, path: str) -> "MeshPlan":
        with open(path) as f:
            return cls.from_json(f.read())
