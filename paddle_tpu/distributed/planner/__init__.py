"""Mesh planner subsystem: analytic+measured hybrid cost model, canonical
layout plans, elastic plan adoption (ROADMAP item 3; docs/PLANNER.md).

- cost_model.py: the analytic roofline (compute + pipeline bubble +
  per-axis collective volumes discounted by the MEASURED overlap_fraction
  from step-timeline history) and the chip spec table bench.py's MFU
  denominator resolves through.
- planner.py: rank the full candidate grid analytically, hand only a
  top-K shortlist to the auto-tuner's measurement loop, record
  predicted-vs-measured error per trial.
- layout.py: SpecLayout (canonical per-param-group PartitionSpecs) and the
  MeshPlan JSON artifact ResilientTrainer adopts across elastic restarts.
"""

from .cost_model import (
    CHIP_SPECS,
    PEAK_BF16_FLOPS,
    CostModel,
    chip_specs,
    measured_overlap_fraction,
)
from .layout import PLAN_FILENAME, MeshPlan, SpecLayout
from .planner import (
    DEFAULT_TOP_K,
    analytic_plan,
    note_replan,
    plan_and_tune,
    rank_candidates,
    shortlist,
)

__all__ = [
    "CHIP_SPECS",
    "PEAK_BF16_FLOPS",
    "CostModel",
    "chip_specs",
    "measured_overlap_fraction",
    "PLAN_FILENAME",
    "MeshPlan",
    "SpecLayout",
    "DEFAULT_TOP_K",
    "analytic_plan",
    "note_replan",
    "plan_and_tune",
    "rank_candidates",
    "shortlist",
]
