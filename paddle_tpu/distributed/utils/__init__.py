from .moe_utils import global_gather, global_scatter  # noqa: F401

__all__ = ["global_scatter", "global_gather"]
