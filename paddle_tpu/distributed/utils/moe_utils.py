"""MoE collective helpers — global_scatter / global_gather.

Reference: python/paddle/distributed/utils/moe_utils.py — ragged NCCL
all-to-alls moving count-prefixed token buffers between expert-parallel ranks.

TPU-native stance: the MoE layer (incubate/distributed/models/moe) routes with
dense dispatch/combine einsums whose sharding constraints compile to XLA
all-to-alls, so ragged runtime exchanges are unnecessary on the hot path.
These functions exist for API parity: they implement the same global
(src, expert)-grid transpose on the capacity-padded static layout.

Layout contract (static-shape analog of the reference's count arrays):
`x` is [num_ranks * num_local_expert * capacity, d_model] — rank-major rows,
i.e. row block (r, e) holds the tokens this rank routes to global expert
r * num_local_expert + e, padded to `capacity`.
"""

from __future__ import annotations

import numpy as np

from ..collective import _grp, alltoall_single


def _check_uniform_counts(x, local_count, global_count, group):
    """The static capacity-padded layout implies uniform counts; ragged
    counts would silently land tokens in wrong expert rows — refuse loudly."""
    import jax

    n = _grp(group).nranks
    rows = x.shape[0]
    for name, c in (("local_count", local_count), ("global_count", global_count)):
        if c is None:
            continue
        raw = c._value if hasattr(c, "_value") else c
        if isinstance(raw, jax.core.Tracer):
            continue  # traced counts: stay trace-safe, skip the eager check
        arr = np.asarray(raw).ravel()
        if arr.size == 0:
            continue
        if not (arr == arr[0]).all() or int(arr.sum()) != rows:
            raise NotImplementedError(
                f"{name} must be uniform with sum == x.shape[0] ({rows}) for "
                "the TPU capacity-padded layout; ragged counts are handled by "
                "the dense-dispatch MoE layer, not these compatibility shims"
            )


def global_scatter(x, local_count, global_count, group=None, use_calc_stream=True):
    """Exchange token blocks so each rank receives the tokens routed to its
    local experts (reference: moe_utils.py global_scatter). With the static
    capacity-padded layout the exchange is exactly one equal-split all-to-all;
    `local_count`/`global_count` are accepted for signature parity (counts are
    implied by the padded layout)."""
    _check_uniform_counts(x, local_count, global_count, group)
    out = x.clone() if hasattr(x, "clone") else x
    alltoall_single(out, x, group=group)
    return out


def global_gather(x, local_count, global_count, group=None, use_calc_stream=True):
    """Inverse of global_scatter (reference: moe_utils.py global_gather) —
    returns expert outputs to the ranks that own the tokens. The equal-split
    all-to-all is self-inverse on the (src, dst) chunk grid."""
    _check_uniform_counts(x, local_count, global_count, group)
    out = x.clone() if hasattr(x, "clone") else x
    alltoall_single(out, x, group=group)
    return out
