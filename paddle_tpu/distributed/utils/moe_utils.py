"""MoE collective helpers — global_scatter / global_gather.

Reference: python/paddle/distributed/utils/moe_utils.py — ragged NCCL
all-to-alls moving count-prefixed token buffers between expert-parallel ranks.

TPU-native stance: the MoE layer (incubate/distributed/models/moe) routes
with dense dispatch/combine einsums whose sharding constraints compile to
XLA all-to-alls, so ragged runtime exchanges are unnecessary on the hot
path. These functions carry the REFERENCE semantics for eager callers:
ragged per-expert counts are handled by padding every (rank, expert) block
to the max count (the TPU-idiomatic static shape), riding ONE equal-split
all-to-all, and compacting on the receive side — GShard capacity padding
applied to the fastmoe wire format.

Count contract (reference moe_utils.py): with n ranks and L local experts
per rank (E = n*L global experts), `local_count[i]` is the number of rows
this rank sends to global expert i (x's rows sorted by target expert) and
`global_count[r*L + e]` is the number of rows received from rank r for
local expert e.
"""

from __future__ import annotations

import numpy as np

from ..collective import _grp, alltoall_single


def _concrete_counts(c):
    import jax

    if c is None:
        return None
    raw = c._value if hasattr(c, "_value") else c
    if isinstance(raw, jax.core.Tracer):
        return None
    return np.asarray(raw).ravel().astype(np.int64)


def _block_indices(counts, cap):
    """Row indices of the compact rows inside a [E*cap, d] padded layout."""
    if counts.sum() == 0:
        return np.zeros((0,), np.int32)
    return np.concatenate(
        [np.arange(int(c), dtype=np.int32) + i * cap
         for i, c in enumerate(counts)])


def _ragged_exchange(x, send_counts, recv_counts, group):
    """Pad (rank, expert) blocks to the max count, one equal-split
    all-to-all, compact with the receive counts."""
    import jax.numpy as jnp

    from ...framework.core import Tensor
    from .. import eager_multiproc as _mp
    from ..collective import ReduceOp, all_reduce

    if int(send_counts.sum()) != x.shape[0]:
        raise ValueError(
            f"count sum {int(send_counts.sum())} != rows {x.shape[0]} — "
            "tokens would be silently dropped")
    d = x.shape[1]
    cap = int(max(send_counts.max(initial=0), recv_counts.max(initial=0), 1))
    # every rank must pad to the same capacity: one tiny MAX reduce (the
    # reference exchanges its count arrays the same way)
    capt = Tensor(jnp.asarray(cap, jnp.int32))
    all_reduce(capt, op=ReduceOp.MAX, group=group)
    cap = int(np.asarray(capt._value))
    E = send_counts.size
    sidx = _block_indices(send_counts, cap)
    pad = jnp.zeros((E * cap, d), x._value.dtype)
    if sidx.size:
        pad = pad.at[jnp.asarray(sidx)].set(x._value[:sidx.size])
    buf = Tensor(pad)
    alltoall_single(buf, Tensor(pad), group=group)
    ridx = _block_indices(recv_counts, cap)
    return Tensor(buf._value[jnp.asarray(ridx)]) if ridx.size else \
        Tensor(jnp.zeros((0, d), x._value.dtype))


def global_scatter(x, local_count, global_count, group=None, use_calc_stream=True):
    """Exchange token blocks so each rank receives the tokens routed to its
    local experts (reference: moe_utils.py global_scatter). Concrete counts
    always take the capacity-padded exchange — its collective sequence (one
    MAX reduce + one equal-split all-to-all) is identical on every rank
    regardless of how ragged each rank's counts are, so ranks can never
    diverge onto mismatched collectives. Uniform counts are just the
    cap == count special case.

    Timed as a kind="a2a" comm_task interval, and counted under
    op="all_to_all" in collective_{calls,bytes}_total by the inner
    alltoall_single (bytes reflect the capacity-padded wire buffer) — the
    eager MoE dispatch is real measured comm in flight/step records
    (ISSUE-14 satellite; the compiled fast path registers its volume via
    distributed/moe_comm.py instead)."""
    from .. import comm_watchdog

    sc = _concrete_counts(local_count)
    rc = _concrete_counts(global_count)
    with comm_watchdog.comm_task("moe/global_scatter", kind="a2a"):
        return _dispatch_exchange(x, sc, rc, group)


def global_gather(x, local_count, global_count, group=None, use_calc_stream=True):
    """Inverse of global_scatter (reference: moe_utils.py global_gather) —
    returns expert outputs to the ranks that own the tokens. Send blocks are
    counted by `global_count`, receive blocks by `local_count`. Same
    kind="a2a" interval + op="all_to_all" counting as global_scatter."""
    from .. import comm_watchdog

    sc = _concrete_counts(local_count)
    rc = _concrete_counts(global_count)
    with comm_watchdog.comm_task("moe/global_gather", kind="a2a"):
        return _dispatch_exchange(x, rc, sc, group)


def _dispatch_exchange(x, send_counts, recv_counts, group):
    from .. import eager_multiproc as _mp

    if send_counts is None or recv_counts is None:
        # traced counts cannot steer a static-shape exchange — the compiled
        # MoE path uses dense dispatch instead (incubate MoELayer); this
        # raw equal-split exchange serves the capacity-padded layout
        out = x.clone() if hasattr(x, "clone") else x
        alltoall_single(out, x, group=group)
        return out
    if _mp.nprocs() > 1:
        # multi-controller: ALWAYS the padded exchange, so every rank runs
        # the identical collective sequence however ragged its own counts
        return _ragged_exchange(x, send_counts, recv_counts, group)
    # single controller holds the global stacked view; uniform counts ride
    # the raw equal-split all-to-all, ragged ones have no meaningful
    # single-process layout
    uniform = (send_counts.size
               and (send_counts == send_counts[0]).all()
               and int(send_counts.sum()) == x.shape[0])
    if uniform:
        out = x.clone() if hasattr(x, "clone") else x
        alltoall_single(out, x, group=group)
        return out
    raise NotImplementedError(
        "ragged global_scatter/global_gather needs multi-controller "
        "execution (jax.distributed); single-controller MoE uses the "
        "dense-dispatch MoELayer / fused_moe path")
