"""MoE collective helpers — global_scatter / global_gather.

Reference: python/paddle/distributed/utils/moe_utils.py — ragged NCCL
all-to-alls moving count-prefixed token buffers between expert-parallel ranks.

TPU-native stance: the MoE layer (incubate/distributed/models/moe) routes with
dense dispatch/combine einsums whose sharding constraints compile to XLA
all-to-alls, so ragged runtime exchanges are unnecessary on the hot path.
These functions exist for API parity: they implement the same global
(src, expert)-grid transpose on the capacity-padded static layout.

Layout contract (static-shape analog of the reference's count arrays):
`x` is [num_ranks * num_local_expert * capacity, d_model] — rank-major rows,
i.e. row block (r, e) holds the tokens this rank routes to global expert
r * num_local_expert + e, padded to `capacity`.
"""

from __future__ import annotations

from ..collective import _grp, alltoall_single


def global_scatter(x, local_count, global_count, group=None, use_calc_stream=True):
    """Exchange token blocks so each rank receives the tokens routed to its
    local experts (reference: moe_utils.py global_scatter). With the static
    capacity-padded layout the exchange is exactly one equal-split all-to-all;
    `local_count`/`global_count` are accepted for signature parity (counts are
    implied by the padded layout)."""
    out = x.clone() if hasattr(x, "clone") else x
    alltoall_single(out, x, group=group)
    return out


def global_gather(x, local_count, global_count, group=None, use_calc_stream=True):
    """Inverse of global_scatter (reference: moe_utils.py global_gather) —
    returns expert outputs to the ranks that own the tokens. The equal-split
    all-to-all is self-inverse on the (src, dst) chunk grid."""
    out = x.clone() if hasattr(x, "clone") else x
    alltoall_single(out, x, group=group)
    return out
