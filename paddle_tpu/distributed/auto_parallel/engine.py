"""Auto-parallel static Engine (reference:
python/paddle/distributed/auto_parallel/static/engine.py — Engine :160,
fit :1533, evaluate :1723, predict :1837, prepare :1986, save/load :2324;
strategy.py Strategy).

TPU formulation: the reference Engine parallelizes a serial program through
completion/partitioning/reshard passes and drives it with its own executor.
Here the whole pipeline collapses onto DistributedTrainStep: the Strategy's
degrees pick the hybrid mesh (or the auto-tuner picks one when
strategy.auto_mode == "full"), GSPMD is the completion+partitioner, and
fit/evaluate/predict run the compiled step over numpy/DataLoader batches.
"""

from __future__ import annotations

import numpy as np

__all__ = ["Engine", "Strategy"]


class _Config:
    def __init__(self, **kw):
        self.__dict__.update(kw)


class Strategy:
    """reference strategy.py:191 — the subset that maps to mesh shape +
    sharding stage + amp + recompute."""

    def __init__(self):
        self.auto_mode = "semi"  # "semi" | "full" (full = auto-tune)
        self.mp_degree = 1
        self.pp_degree = 1
        self.dp_degree = None  # None: all remaining devices
        self.sharding = _Config(enable=False, degree=1, stage=1)
        self.amp = _Config(enable=False, dtype="bfloat16", level="O2")
        self.recompute = _Config(enable=False)
        self.gradient_merge = _Config(enable=False, k_steps=1)


class Engine:
    """reference engine.py:160."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        import paddle_tpu.nn as nn

        if model is not None and not isinstance(model, nn.Layer) and not callable(model):
            raise TypeError("model must be an nn.Layer or callable")
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy or Strategy()
        self._step = None
        self._mesh = None
        self.history = {"loss": []}

    # ------------------------------------------------------------------ #

    def _build_mesh(self):
        import jax

        from .. import env as _env

        s = self._strategy
        ndev = jax.device_count()
        if s.auto_mode == "full":
            dp, pp, shard, mp = self.plan(ndev)
            s.dp_degree, s.pp_degree, s.mp_degree = dp, pp, mp
            s.sharding.enable = shard > 1
            s.sharding.degree = shard
            return _env.build_mesh(dp=dp, pp=pp, sharding=shard, mp=mp)
        mp, pp = s.mp_degree, s.pp_degree
        shard = s.sharding.degree if s.sharding.enable else 1
        dp = s.dp_degree or max(ndev // (mp * pp * shard), 1)
        if dp * mp * pp * shard > ndev:
            raise ValueError(
                f"strategy mesh {dp}x{pp}x{shard}x{mp} exceeds {ndev} devices")
        return _env.build_mesh(dp=dp, pp=pp, sharding=shard, mp=mp)

    def plan(self, ndev, model_cfg=None):
        """Plan search for auto_mode="full" (round-3 VERDICT missing #5):
        enumerate (dp, pp, sharding, mp) factorizations of the device count,
        prune by the auto_tuner memory model, score the rest with an
        analytic step-cost model, return the argmin.

        This is the TPU analog of the reference static Engine's
        completion + partitioner + cost model
        (python/paddle/distributed/auto_parallel/static/engine.py:99,
        completion.py, cost_model): sharding PROPAGATION is GSPMD's job
        here, so the plan space is exactly the mesh factorization, and the
        cost model only has to rank factorizations."""
        costs = self.candidate_costs(ndev, model_cfg)
        if not costs:
            raise RuntimeError(
                "no feasible parallel plan within the memory cap")
        return min(costs, key=costs.get)

    def candidate_costs(self, ndev, model_cfg=None):
        """Analytic per-step cost (arbitrary units) for every feasible
        (dp, pp, sharding, mp) factorization — the cost model behind
        plan(), exposed so its RANKING can be validated against measured
        step times (tests/test_engine.py round-5 validation)."""
        from ..auto_tuner.tuner import _divisors, estimate_memory_bytes

        cfg = model_cfg or self._infer_model_cfg()
        h = cfg.get("hidden_size", 1024)
        L = cfg.get("num_layers", 12)
        seq = cfg.get("seq_length", 1024)
        vocab = cfg.get("vocab_size", 50304)
        micro_b = cfg.get("micro_batch_size", 1)
        tuner_cfg = {"model_cfg": cfg,
                     "max_mem_usage_bytes": cfg.get("max_mem_usage_bytes")}

        costs = {}
        for mp in _divisors(ndev):
            for pp in _divisors(ndev // mp):
                for shard in _divisors(ndev // (mp * pp)):
                    dp = ndev // (mp * pp * shard)
                    cand = {"mp_degree": mp, "pp_degree": pp,
                            "sharding_degree": shard, "sharding_stage": 1,
                            "dp_degree": dp, "micro_batch_size": micro_b}
                    if tuner_cfg["max_mem_usage_bytes"]:
                        from ..auto_tuner.tuner import prune_by_memory

                        if prune_by_memory(tuner_cfg, cand):
                            continue
                    # analytic per-step cost (arbitrary units):
                    # compute: flops per device
                    flops = (72 * micro_b * seq * L * h * h
                             + 6 * micro_b * seq * h * vocab) \
                        / (dp * shard * mp * pp)
                    # mp: 4 all-reduces of [b, s, h] per layer per step,
                    # ring cost ∝ (mp-1)/mp
                    comm = 0.0
                    if mp > 1:
                        comm += (4 * L / pp) * micro_b * seq * h \
                            * (mp - 1) / mp * 40
                    # pp: bubble fraction (p-1)/m with m microbatches
                    bubble = (pp - 1) / max(cfg.get("microbatches", 4), 1)
                    # dp/sharding: grad sync of param bytes once per step
                    n_params = 12 * L * h * h + vocab * h
                    if dp * shard > 1:
                        comm += n_params / (mp * pp) \
                            * (dp * shard - 1) / (dp * shard) * 4
                    costs[(dp, pp, shard, mp)] = flops * (1 + bubble) + comm
        return costs

    def _infer_model_cfg(self):
        cfg = getattr(self._model, "config", None)
        out = {}
        for k in ("hidden_size", "num_layers", "vocab_size"):
            v = getattr(cfg, k, None)
            if v:
                out[k] = v
        return out

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        """Build the compiled step (reference prepare :1986 — completion/
        partition/reshard collapse into DistributedTrainStep's GSPMD)."""
        from ..train_step import DistributedTrainStep

        if self._step is not None:
            return
        self._mesh = self._build_mesh()
        s = self._strategy
        loss = self._loss

        def loss_fn(out, lb):
            return loss(out, lb)

        self._step = DistributedTrainStep(
            self._model, loss_fn, self._optimizer, mesh=self._mesh,
            sharding_stage=(s.sharding.stage if s.sharding.enable else 0),
            amp_level=(s.amp.level if s.amp.enable else None),
            amp_dtype=s.amp.dtype,
        )

    # ------------------------------------------------------------------ #

    def _batches(self, data, batch_size):
        import paddle_tpu as paddle
        from paddle_tpu.io import DataLoader

        if isinstance(data, DataLoader):
            yield from data
            return
        if isinstance(data, (tuple, list)) and len(data) == 2:
            x, y = data
            x, y = np.asarray(x), np.asarray(y)
            # trailing partial batch included: a dropped remainder would be
            # silent missing predictions / skewed eval loss (costs one extra
            # compile for the odd shape)
            for lo in range(0, len(x), batch_size):
                yield (paddle.to_tensor(x[lo:lo + batch_size]),
                       paddle.to_tensor(y[lo:lo + batch_size]))
            return
        # Dataset-style: delegate to DataLoader
        yield from DataLoader(data, batch_size=batch_size, shuffle=False)

    def fit(self, train_data=None, train_sample_split=None, batch_size=1,
            epochs=1, steps_per_epoch=None, log_freq=10, valid_data=None,
            **kw):
        """reference fit :1533."""
        self.prepare()
        for _ep in range(epochs):
            for i, (x, y) in enumerate(self._batches(train_data, batch_size)):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                loss = self._step(x, y)
                self.history["loss"].append(float(loss))
        self._step.sync_weights()
        return self.history

    def evaluate(self, valid_data=None, batch_size=1, steps=None, **kw):
        """reference evaluate :1723. Works without an optimizer (eval-only
        engines run the model eagerly under the global mesh)."""
        losses = []
        if self._optimizer is not None:
            self.prepare()
        elif self._mesh is None:
            self._mesh = self._build_mesh()
        was_training = self._model.training
        self._model.eval()  # dropout/BN must be in eval mode either path
        try:
            for i, (x, y) in enumerate(self._batches(valid_data, batch_size)):
                if steps is not None and i >= steps:
                    break
                if self._optimizer is not None:
                    losses.append(float(self._step.evaluate(x, y)))
                else:
                    losses.append(float(self._loss(self._model(x), y)))
        finally:
            if was_training:
                self._model.train()
        return {"loss": float(np.mean(losses)) if losses else None}

    def predict(self, test_data=None, batch_size=1, steps=None, **kw):
        """reference predict :1837 — inference-only: no optimizer/loss
        needed, no train step built."""
        import paddle_tpu as paddle

        if self._mesh is None:
            self._mesh = self._build_mesh()
        was_training = self._model.training
        self._model.eval()
        outs = []
        try:
            for i, batch in enumerate(self._batches(test_data, batch_size)):
                if steps is not None and i >= steps:
                    break
                x = batch[0] if isinstance(batch, (tuple, list)) else batch
                outs.append(self._model(x).numpy())
        finally:
            if was_training:
                self._model.train()
        return outs

    # ------------------------------------------------------------------ #

    def save(self, path, training=True):
        """reference save :2324 — distributed checkpoint of model (+opt)."""
        from ...framework.io import save as fsave

        if self._step is not None:
            self._step.sync_weights()
            # write the device-side moments back so the .pdopt checkpoint
            # carries the real optimizer state, not init zeros
            sync_opt = getattr(self._step, "sync_optimizer", None)
            if sync_opt is not None:
                sync_opt()
        fsave(self._model.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, strict=True, load_optimizer=True):
        """reference load :2409."""
        import os

        from ...framework.io import load as fload

        self._model.set_state_dict(fload(path + ".pdparams"))
        if load_optimizer and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    @property
    def main_program(self):  # API parity: the jitted step IS the program
        return self._step

    def cost(self, mode="train"):
        """Analytic memory estimate for the current strategy (reference
        Engine.cost backed by the cost model)."""
        from ..auto_tuner.tuner import estimate_memory_bytes

        s = self._strategy
        cfg = {
            "dp_degree": s.dp_degree or 1,
            "mp_degree": s.mp_degree, "pp_degree": s.pp_degree,
            "sharding_degree": s.sharding.degree if s.sharding.enable else 1,
            "sharding_stage": s.sharding.stage,
            "micro_batch_size": 1,
            "use_recompute": s.recompute.enable,
            "global_batch_size": 1,
        }
        model_cfg = {}
        cfgobj = getattr(self._model, "config", None)
        if cfgobj is not None:
            model_cfg = {
                "hidden_size": getattr(cfgobj, "hidden_size", 0),
                "num_layers": getattr(cfgobj, "num_layers", 0),
                "vocab_size": getattr(cfgobj, "vocab_size", 0),
                "seq_length": getattr(cfgobj, "max_position_embeddings", 1024),
            }
        return estimate_memory_bytes({"model_cfg": model_cfg}, cfg)
