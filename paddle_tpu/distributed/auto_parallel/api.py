"""Semi-auto parallel API — shard_tensor / reshard / placements.

Reference: python/paddle/distributed/auto_parallel/api.py (shard_tensor :220,
reshard :796, to_static :2946) + placements
(python/paddle/distributed/auto_parallel/placement_type.py) + C++ DistTensor
(paddle/phi/core/distributed/auto_parallel/dist_tensor.h:39) with 160
registered SPMD rules and the reshard function library
(paddle/phi/core/distributed/auto_parallel/reshard/).

TPU-native collapse: a "DistTensor" is a jax.Array with a NamedSharding — the
SPMD rule library IS GSPMD (XLA propagates shardings through every op), and
every reshard pair (p2r/r2p/s2r/nd-mesh...) is jax.device_put to the new
sharding, which XLA lowers to the right collective. Eager ops between dist
tensors run distributed automatically (jax computation-follows-sharding),
which is exactly the reference's dygraph semi-auto semantics.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...framework.core import Tensor, to_tensor
from .process_mesh import ProcessMesh

__all__ = ["Shard", "Replicate", "Partial", "shard_tensor", "reshard",
           "dtensor_from_fn", "shard_layer", "shard_optimizer"]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    """Shard tensor dim `dim` over the corresponding mesh axis
    (reference: paddle.distributed.Shard)."""

    def __init__(self, dim):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("replicate")


class Partial(Placement):
    """Pending-reduction placement (reference: paddle.distributed.Partial).
    NamedSharding cannot express partial values; tensors carry it as metadata
    and materialize replicated — reshard(Partial→Replicate/Shard) is where
    the reduction would fire (GSPMD emits it inside jit; eagerly the value is
    already the full sum because eager ops never produce partials here)."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, o):
        return isinstance(o, Partial) and o.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))


def _to_spec(placements, ndim, mesh: ProcessMesh):
    """placements (one per mesh axis) -> PartitionSpec over tensor dims."""
    entries = [None] * ndim
    for axis_idx, pl in enumerate(placements):
        if isinstance(pl, Shard):
            d = pl.dim
            name = mesh.dim_names[axis_idx]
            if entries[d] is None:
                entries[d] = name
            elif isinstance(entries[d], tuple):
                entries[d] = entries[d] + (name,)
            else:
                entries[d] = (entries[d], name)
    return P(*entries)


def _placed(value, mesh: ProcessMesh, placements):
    jm = mesh.jax_mesh()
    spec = _to_spec(placements, np.ndim(value), mesh)
    return jax.device_put(value, NamedSharding(jm, spec))


def _attach(t: Tensor, mesh, placements):
    t.process_mesh = mesh
    t.placements = list(placements)
    t.dist_attr = _to_spec(placements, len(t.shape), mesh)
    t.is_dist_tensor = True
    return t


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None, place=None,
                 stop_gradient=None):
    """reference: api.py:220 — build a dist tensor from data + placements."""
    t = data if isinstance(data, Tensor) else to_tensor(data, dtype=dtype)
    if len(placements) != mesh.ndim:
        raise ValueError(
            f"need one placement per mesh dim ({mesh.ndim}), got {len(placements)}")
    out = Tensor(_placed(t._value, mesh, placements),
                 stop_gradient=t.stop_gradient if stop_gradient is None else stop_gradient)
    return _attach(out, mesh, placements)


def reshard(dist_tensor, mesh: ProcessMesh, placements):
    """reference: api.py:796 + the C++ reshard function library — here one
    device_put: XLA/runtime picks the collective (all-gather for s2r,
    slice for r2s, all-to-all for cross-dim moves)."""
    t = dist_tensor if isinstance(dist_tensor, Tensor) else to_tensor(dist_tensor)
    out = Tensor(_placed(t._value, mesh, placements), stop_gradient=t.stop_gradient)
    return _attach(out, mesh, placements)


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    """reference: api.py dtensor_from_fn — build then place."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """reference: api.py shard_layer — apply shard_fn(sublayer_name, layer,
    mesh) to every sublayer; default replicates every parameter."""

    def default_fn(name, l, mesh):
        for pname, p in l._parameters.items():
            if p is None:
                continue
            placed = shard_tensor(p, mesh, [Replicate()] * mesh.ndim)
            p._value = placed._value
            _attach(p, mesh, placed.placements)

    fn = shard_fn or default_fn
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


class _ShardOptimizer:
    """reference: api.py shard_optimizer — optimizer whose states follow the
    parameter placements. Eagerly the states are created from the (already
    placed) params, so moment tensors inherit shardings automatically; this
    wrapper exists for API parity and master-weight pass-through."""

    def __init__(self, optimizer, shard_fn=None):
        self._inner = optimizer
        self._shard_fn = shard_fn

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        return self._inner.step()

    def clear_grad(self, *a, **kw):
        return self._inner.clear_grad(*a, **kw)


def shard_optimizer(optimizer, shard_fn=None):
    return _ShardOptimizer(optimizer, shard_fn)
