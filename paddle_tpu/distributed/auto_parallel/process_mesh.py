"""ProcessMesh — the auto-parallel device mesh.

Reference: python/paddle/distributed/auto_parallel/process_mesh.py:85
(ProcessMesh holds an N-D array of process ranks + dim names; every dist
tensor/op carries one).

TPU-native: a ProcessMesh IS a jax.sharding.Mesh over the local devices —
"process ids" index jax.devices(). The global default mesh (set_mesh) is what
`shard_tensor` uses when placements reference it.
"""

from __future__ import annotations

import numpy as np

__all__ = ["ProcessMesh", "get_mesh", "set_mesh"]

_global_mesh = None


class ProcessMesh:
    def __init__(self, mesh=None, dim_names=None, shape=None, process_ids=None):
        if mesh is None and shape is not None:
            mesh = np.asarray(process_ids or range(int(np.prod(shape)))).reshape(shape)
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        assert arr.ndim == len(dim_names)
        self._mesh = arr
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    @property
    def mesh(self):
        return self._mesh

    @property
    def shape(self):
        return list(self._mesh.shape)

    @property
    def ndim(self):
        return self._mesh.ndim

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return self._mesh.flatten().tolist()

    def get_dim_size(self, name):
        return self._mesh.shape[self._dim_names.index(name)]

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self._dim_names == other._dim_names
                and np.array_equal(self._mesh, other._mesh))

    def __hash__(self):
        return hash((tuple(self._dim_names), self._mesh.tobytes()))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"

    def get_group(self, dim_name=None):
        from .. import collective as coll

        return coll.get_group(0)

    def jax_mesh(self):
        """Materialize as a jax Mesh (devices indexed by process id)."""
        if self._jax_mesh is None:
            import jax
            from jax.sharding import Mesh

            devs = jax.devices()
            picked = np.asarray([devs[i % len(devs)] for i in self._mesh.flatten()],
                                dtype=object).reshape(self._mesh.shape)
            self._jax_mesh = Mesh(picked, tuple(self._dim_names))
        return self._jax_mesh


def set_mesh(mesh: ProcessMesh):
    """reference: paddle.distributed.auto_parallel.set_mesh."""
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> ProcessMesh | None:
    return _global_mesh
