from .api import (  # noqa: F401
    Partial,
    Replicate,
    Shard,
    dtensor_from_fn,
    reshard,
    shard_layer,
    shard_optimizer,
    shard_tensor,
)
from .process_mesh import ProcessMesh, get_mesh, set_mesh  # noqa: F401

__all__ = [
    "ProcessMesh", "get_mesh", "set_mesh",
    "Shard", "Replicate", "Partial",
    "shard_tensor", "reshard", "dtensor_from_fn",
    "shard_layer", "shard_optimizer",
]

from .engine import Engine, Strategy  # noqa: E402,F401
__all__ += ["Engine", "Strategy"]
