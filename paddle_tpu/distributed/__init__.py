"""paddle_tpu.distributed (reference: python/paddle/distributed/).

Architecture (SURVEY §5.8 TPU-native mapping):
- env.py: process bootstrap (jax.distributed = TCPStore rendezvous) + the
  global hybrid mesh [dp, pp, sharding, sep, mp]
- collective.py: Group objects + eager collectives (global-array semantics)
  + `primitives` (lax.psum/all_gather/ppermute/...) for shard_map bodies
- fleet/: strategy, topology, facade, TP/SP layers, pipeline partitioning,
  recompute
- train_step.py: DistributedTrainStep — hybrid parallelism as compiled GSPMD
- parallel.py: DataParallel + group_sharded (ZeRO) API
- launch/: multi-host process launcher
"""

from . import collective, env, fleet, parallel, rpc, sharding
from .collective import (
    P2POp,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    batch_isend_irecv,
    broadcast,
    broadcast_object_list,
    destroy_process_group,
    get_group,
    irecv,
    isend,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    scatter_object_list,
    send,
    wait,
)
from .env import (
    ParallelEnv,
    build_mesh,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
)
from .parallel import DataParallel, group_sharded_parallel
from .train_step import DistributedTrainStep
from . import auto_parallel, checkpoint, planner, resilience
from .planner import MeshPlan
from .resilience import ResilientTrainer, run_with_recovery
from .auto_parallel import (
    Partial,
    ProcessMesh,
    Replicate,
    Shard,
    dtensor_from_fn,
    reshard,
    shard_layer,
    shard_optimizer,
    shard_tensor,
)

__all__ = [
    "auto_parallel", "checkpoint", "ProcessMesh", "Shard", "Replicate", "Partial",
    "shard_tensor", "reshard", "dtensor_from_fn", "shard_layer", "shard_optimizer",
    "init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
    "is_initialized", "build_mesh", "new_group", "get_group", "ReduceOp",
    "all_reduce", "all_gather", "all_gather_object", "reduce",
    "reduce_scatter", "broadcast", "broadcast_object_list", "scatter",
    "scatter_object_list", "alltoall", "alltoall_single", "send", "recv",
    "isend", "irecv", "barrier", "wait", "P2POp", "batch_isend_irecv",
    "destroy_process_group", "fleet", "collective", "DataParallel",
    "group_sharded_parallel", "DistributedTrainStep", "sharding",
    "resilience", "ResilientTrainer", "run_with_recovery",
    "planner", "MeshPlan",
]


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """paddle.distributed.spawn — on the single-controller TPU runtime,
    in-process SPMD replaces process-per-device; run func once."""
    func(*args)
    return None
