"""paddle.distributed.sharding (reference: python/paddle/distributed/sharding/
group_sharded.py:50)."""

from ..parallel import group_sharded_parallel, save_group_sharded_model  # noqa: F401
