"""Launcher implementation (reference: python/paddle/distributed/launch/main.py)."""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch"]


def _parse():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None, help="coordinator ip:port (rank-0 host)")
    p.add_argument("--nnodes", default="1", help="number of hosts (N or N:M)")
    p.add_argument("--rank", type=int, default=int(os.environ.get("PADDLE_TRAINER_ID", 0)))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="controller processes per host (TPU: 1)")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--job_id", default="default")
    p.add_argument("--devices", default=None, help="accepted for parity; TPU devices are auto-discovered")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def launch():
    args = _parse()
    nnodes = int(str(args.nnodes).split(":")[0])
    os.makedirs(args.log_dir, exist_ok=True)

    procs = []
    for local in range(args.nproc_per_node):
        env = dict(os.environ)
        env["PADDLE_TRAINER_ID"] = str(args.rank * args.nproc_per_node + local)
        env["PADDLE_TRAINERS_NUM"] = str(nnodes * args.nproc_per_node)
        env["PADDLE_LOCAL_RANK"] = str(local)
        env["PADDLE_JOB_ID"] = args.job_id
        if args.master:
            env["PADDLE_MASTER"] = args.master
            env["JAX_COORDINATOR_ADDRESS"] = args.master
        log_path = os.path.join(args.log_dir, f"workerlog.{local}")
        with open(log_path, "ab") as logf:
            proc = subprocess.Popen(
                [sys.executable, args.training_script, *args.training_script_args],
                env=env, stdout=logf if args.nproc_per_node > 1 else None,
                stderr=subprocess.STDOUT if args.nproc_per_node > 1 else None,
            )
        procs.append(proc)

    def _terminate(signum, frame):
        for p in procs:
            p.terminate()
        sys.exit(1)

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)

    exit_code = 0
    try:
        while procs:
            for p in list(procs):
                ret = p.poll()
                if ret is None:
                    continue
                procs.remove(p)
                if ret != 0:
                    # a failed trainer kills the pod (reference watcher behavior)
                    exit_code = ret
                    for q in procs:
                        q.terminate()
                    procs.clear()
                    break
            time.sleep(0.5)
    finally:
        for p in procs:
            p.terminate()
    sys.exit(exit_code)
