"""Launcher (reference: python/paddle/distributed/launch/main.py CLI +
controllers/collective.py:22-150 CollectiveController/Pod + watcher.py log
watcher + fleet/elastic/manager.py restart semantics).

One controller process per host (TPU model: the process drives all local
chips through PJRT; jax.distributed handles multi-host rendezvous). The
controller spawns the worker pod, a watcher thread tails worker logs for
fatal patterns and monitors liveness, and on worker failure the pod is torn
down and — when --max_restart allows — respawned with PADDLE_RESTART_COUNT
incremented (elastic level 1: in-place pod restart; the reference's etcd
scale-in/out is the same loop keyed on a store watch)."""

from __future__ import annotations

import argparse
import os
import re
import signal
import subprocess
import sys
import threading
import time

__all__ = ["launch", "Pod", "LogWatcher"]

_FATAL_PATTERNS = re.compile(
    r"(FatalError|Check failed|core dumped|Segmentation fault|NumericError)")


class LogWatcher(threading.Thread):
    """Tails worker log files, surfacing fatal patterns (reference:
    launch/controllers/watcher.py)."""

    def __init__(self, paths, on_fatal=None, interval=0.5):
        super().__init__(daemon=True)
        self.paths = list(paths)
        self.on_fatal = on_fatal
        self.interval = interval
        self.fatal_lines: list[str] = []
        # start at the current size: logs open in append mode, and a stale
        # fatal line from a previous launcher run must not kill a fresh pod
        self._offsets = {}
        for p in self.paths:
            try:
                self._offsets[p] = os.path.getsize(p)
            except OSError:
                self._offsets[p] = 0
        self._stop_evt = threading.Event()  # NB: Thread reserves _stop

    def stop(self):
        self._stop_evt.set()

    def scan_once(self):
        for p in self.paths:
            try:
                with open(p, "rb") as f:
                    f.seek(self._offsets[p])
                    chunk = f.read()
            except OSError:
                continue
            # only consume complete lines — a fatal pattern split across a
            # read boundary must still match on the next scan
            cut = chunk.rfind(b"\n")
            if cut < 0:
                continue
            self._offsets[p] += cut + 1
            for line in chunk[:cut].decode(errors="replace").splitlines():
                if _FATAL_PATTERNS.search(line):
                    self.fatal_lines.append(f"{p}: {line}")
                    if self.on_fatal is not None:
                        self.on_fatal(p, line)

    def run(self):
        while not self._stop_evt.is_set():
            self.scan_once()
            time.sleep(self.interval)
        self.scan_once()


class Pod:
    """The set of worker processes on this host (reference Pod in
    launch/controllers/collective.py)."""

    def __init__(self, args, restart_count=0):
        self.args = args
        self.restart_count = restart_count
        self.procs: list[subprocess.Popen] = []
        self.log_paths: list[str] = []
        self.wd_report_paths: list[str] = []
        self.flight_paths: list[str] = []

    def spawn(self):
        args = self.args
        nnodes = int(str(args.nnodes).split(":")[0])
        os.makedirs(args.log_dir, exist_ok=True)
        for local in range(args.nproc_per_node):
            env = dict(os.environ)
            env["PADDLE_TRAINER_ID"] = str(
                args.rank * args.nproc_per_node + local)
            env["PADDLE_TRAINERS_NUM"] = str(nnodes * args.nproc_per_node)
            env["PADDLE_LOCAL_RANK"] = str(local)
            env["PADDLE_JOB_ID"] = args.job_id
            env["PADDLE_RESTART_COUNT"] = str(self.restart_count)
            if args.master:
                env["PADDLE_MASTER"] = args.master
                env["JAX_COORDINATOR_ADDRESS"] = args.master
            log_path = os.path.join(
                args.log_dir, f"workerlog.{local}.r{self.restart_count}")
            self.log_paths.append(log_path)
            # comm-watchdog post-mortem channel: the worker's spill thread
            # appends timeout reports here (comm_watchdog.enable), and the
            # launcher folds the file into the worker log on death so
            # hang-induced restarts are diagnosable after the fact
            wd_path = log_path + ".wd"
            try:
                # stale report from a previous launcher run in the same
                # log_dir must not be pinned on this pod's death (the
                # LogWatcher guards the .log channel the same way)
                os.unlink(wd_path)
            except OSError:
                pass
            env["PADDLE_WD_REPORT_FILE"] = wd_path
            self.wd_report_paths.append(wd_path)
            # flight-recorder post-mortem channel: ResilientTrainer (and the
            # SIGTERM/excepthook handlers it installs) dump the last-N-steps
            # telemetry ring here; folded into the worker log on death like
            # the watchdog spill
            fl_path = log_path + ".flight"
            try:
                os.unlink(fl_path)
            except OSError:
                pass
            env["PADDLE_FLIGHT_FILE"] = fl_path
            self.flight_paths.append(fl_path)
            if args.max_restart > 0:
                # restartable pods escalate hangs: the spill thread's
                # FatalError line trips the LogWatcher → teardown → respawn
                env["PADDLE_WD_FATAL"] = "1"
            logf = open(log_path, "ab")
            proc = subprocess.Popen(
                [sys.executable, args.training_script,
                 *args.training_script_args],
                env=env, stdout=logf, stderr=subprocess.STDOUT,
            )
            proc._logf = logf  # closed in terminate()/watch()
            self.procs.append(proc)
            print(f"[launch] worker {local} (restart {self.restart_count}) "
                  f"logging to {log_path}", file=sys.stderr)

    def terminate(self, grace=3.0):
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + grace
        for p in self.procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            if p.poll() is None:
                p.kill()
            try:  # reap: the restart loop keeps this process alive, so an
                p.wait(timeout=5)  # unreaped child would linger as a zombie
            except Exception:
                pass
        self._close_logs()

    def _close_logs(self):
        for p in self.procs:
            f = getattr(p, "_logf", None)
            if f is not None and not f.closed:
                f.close()

    def dump_watchdog_reports(self):
        """Post-mortem: drain each worker's comm-watchdog spill file AND its
        flight-recorder dump into its log (and the launcher's stderr) before
        respawning, so the stuck-step report and the last-N-steps telemetry
        ring survive the restart that destroys the worker process."""
        channels = [
            ("comm-watchdog", self.wd_report_paths),
            ("flight-recorder", self.flight_paths),
        ]
        for kind, paths in channels:
            for local, (log_path, src_path) in enumerate(
                    zip(self.log_paths, paths)):
                try:
                    with open(src_path) as f:
                        report = f.read().strip()
                except OSError:
                    continue
                if not report:
                    continue
                banner = (f"\n[launch] {kind} post-mortem for worker "
                          f"{local} (restart {self.restart_count}):"
                          f"\n{report}\n")
                try:
                    with open(log_path, "a") as f:
                        f.write(banner)
                except OSError:
                    pass
                print(banner, file=sys.stderr)

    def watch(self, fatal_evt=None):
        """Block until the pod finishes, a worker fails, or the log watcher
        flags a fatal line (covers workers that log the error but HANG in a
        collective instead of exiting — the failure mode the reference
        watcher exists for); returns the pod exit code (first nonzero
        worker code, 1 on fatal-log teardown, 0 when all succeed)."""
        procs = list(self.procs)
        while procs:
            if fatal_evt is not None and fatal_evt.is_set():
                self.terminate()
                return 1
            for p in list(procs):
                ret = p.poll()
                if ret is None:
                    continue
                procs.remove(p)
                if ret != 0:
                    self.terminate()
                    return ret
            time.sleep(0.3)
        self._close_logs()
        return 0


def _parse():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None, help="coordinator ip:port (rank-0 host)")
    p.add_argument("--nnodes", default="1",
                   help="number of hosts, N or N:M (elastic range)")
    p.add_argument("--rank", type=int, default=int(os.environ.get("PADDLE_TRAINER_ID", 0)))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="controller processes per host (TPU: 1)")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--job_id", default="default")
    p.add_argument("--max_restart", type=int,
                   default=int(os.environ.get("PADDLE_MAX_RESTART", "0")),
                   help="elastic: respawn the pod up to N times on failure")
    p.add_argument("--elastic_level", type=int, default=None,
                   help="-1/0 off, 1 in-place pod restart (implies "
                        "max_restart>=1 when set)")
    p.add_argument("--devices", default=None, help="accepted for parity; TPU devices are auto-discovered")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = p.parse_args()
    # N:M elastic range implies restartability (reference --nnodes=2:4)
    if ":" in str(args.nnodes) and args.max_restart == 0:
        args.max_restart = 3
    return args


def launch():
    args = _parse()
    if args.elastic_level and args.elastic_level > 0 and args.max_restart == 0:
        args.max_restart = 3  # reference elastic default

    restart = 0
    current: list[Pod] = []

    def _terminate(signum, frame):
        for pod in current:
            pod.terminate()
        sys.exit(1)

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)

    while True:
        pod = Pod(args, restart_count=restart)
        current[:] = [pod]
        pod.spawn()
        fatal_evt = threading.Event()
        watcher = LogWatcher(pod.log_paths,
                             on_fatal=lambda p, line: fatal_evt.set())
        watcher.start()
        code = pod.watch(fatal_evt)
        watcher.stop()
        watcher.join(timeout=5)
        for line in watcher.fatal_lines:
            print(f"[launch] fatal log: {line}", file=sys.stderr)
        if code != 0:
            pod.dump_watchdog_reports()
        if code == 0:
            sys.exit(0)
        if restart >= args.max_restart:
            print(f"[launch] pod failed (exit {code}), restarts exhausted "
                  f"({restart}/{args.max_restart})", file=sys.stderr)
            sys.exit(code)
        restart += 1
        print(f"[launch] pod failed (exit {code}); restart "
              f"{restart}/{args.max_restart}", file=sys.stderr)
        time.sleep(1.0)
