"""python -m paddle_tpu.distributed.launch — multi-host process launcher.

Reference: python/paddle/distributed/launch/main.py:23 + CollectiveController
(controllers/collective.py:22) which builds the pod, exports
PADDLE_TRAINER_ENDPOINTS/PADDLE_MASTER/rank envs (:126-150) and spawns one
process per device.

TPU model: ONE controller process per host (not per chip); jax.distributed
handles rendezvous via the coordinator address. The launcher therefore spawns
a single local trainer per host, wiring the same env-var contract so
reference-style launch scripts work unchanged.
"""

from .main import launch

if __name__ == "__main__":
    launch()
