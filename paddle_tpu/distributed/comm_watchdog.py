"""Collective hang watchdog — native monitor thread flagging stuck steps.

Reference: CommTaskManager (paddle/phi/core/distributed/comm_task_manager.h:37)
with per-collective timeout tracking (comm_task.h:127 IsTimeout) — the
practical distributed deadlock detector.

TPU-native: collectives are compiled into programs, so the tracked unit is a
blocking region (a dispatched train step, an eager collective, a host sync).
Wrap suspect regions in `comm_task(...)`; the native thread
(native/watchdog.cc) flags any region exceeding its deadline and the report
surfaces on the next poll — exactly the "log stuck rings" behavior.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import re
import threading
import time

from ..framework import native

__all__ = ["enable", "disable", "comm_task", "record_task", "drain_report",
           "peek_report",
           "report_events", "timeout_count", "inflight", "add_task_observer",
           "remove_task_observer"]

_wd = None
_lock = threading.Lock()
_spill = None  # (thread, stop_event)

# Report plumbing: the native buffer is drain-only (watchdog_drain_report
# clears it), but two consumers need the text — the destructive spill/trainer
# path AND the flight recorder's non-destructive peek. Every native drain is
# pumped into a bounded Python-side history; drain_report() consumes from a
# cursor (each caller sees fresh text exactly once, preserving the old
# append-to-file semantics), peek_report()/report_events() read the whole
# retained history without advancing anything.
_report_history: list[str] = []
_report_cursor = 0  # history entries already handed out by drain_report
_REPORT_HISTORY_CAP = 1 << 20  # bytes retained for peek

# comm_task interval observers: fn(desc, start_ns, end_ns, kind), fired on
# region exit whether or not the native watchdog is enabled — the
# StepTimeline's source for per-step collective/blocking intervals. `kind`
# classifies the region for the overlap accounting (spans.overlap_stats):
# "comm" regions are communication whose exposure matters; other kinds
# ("step" for the trainer's whole-step watchdog region) are deadline
# tracking only and stay out of the comm interval union.
_task_observers: list = []


def add_task_observer(fn):
    _task_observers.append(fn)
    return fn


def record_task(desc: str, t0_ns: int, t1_ns: int, kind: str = "comm"):
    """Feed one already-timed (or estimated — MoE compiled-path a2a,
    distributed/moe_comm.py) interval to the task observers without
    entering a tracked region: the timeline-stitching side of comm_task
    for callers whose interval boundaries the host cannot wrap."""
    for fn in list(_task_observers):
        try:
            fn(desc, int(t0_ns), int(t1_ns), kind)
        except Exception as e:  # noqa: BLE001
            import sys

            print(f"[comm_watchdog] task observer failed: {e!r}",
                  file=sys.stderr)


def remove_task_observer(fn):
    try:
        _task_observers.remove(fn)
    except ValueError:
        pass


def _pump_locked():
    """Drain the native buffer into the history (caller holds _lock)."""
    global _report_cursor
    if _wd is None:
        return
    lib, h = _wd
    buf = ctypes.create_string_buffer(1 << 16)
    n = lib.watchdog_drain_report(h, buf, len(buf))
    if n > 0:
        _report_history.append(buf.raw[:n].decode(errors="replace"))
        # bound retained memory: trim oldest entries past the cap. Entries
        # not yet handed out by drain_report are trimmed too (a peek-only
        # consumer must not grow the history without bound on a long job
        # with many timeouts) — under cap pressure the oldest text is gone
        # for both channels, newest-first retention being the useful half.
        total = sum(len(s) for s in _report_history)
        while total > _REPORT_HISTORY_CAP and len(_report_history) > 1:
            total -= len(_report_history.pop(0))
            _report_cursor = max(0, _report_cursor - 1)


def _spill_once(path, fatal):
    report = drain_report()
    if not report:
        return
    try:
        with open(path, "a") as f:
            f.write(report)
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:
        # the drain already emptied the native buffer — losing the report
        # here would erase the only record of the hang; stderr (→ worker
        # log) is the fallback channel
        import sys

        print(f"[comm_watchdog] report file {path} unwritable ({e}); "
              f"report follows:\n{report}", file=sys.stderr, flush=True)
    if fatal:
        # a hung step can't log its own death — this line, written by the
        # spill thread, is what the launcher's LogWatcher pattern-matches to
        # tear the wedged pod down and restart it (launch/main.py)
        import sys

        print("FatalError: comm watchdog deadline exceeded\n" + report,
              file=sys.stderr, flush=True)


def _spill_loop(stop, path, fatal, interval=0.5):
    while not stop.wait(interval):
        if _wd is None:
            return
        _spill_once(path, fatal)


def enable(timeout_seconds=None, report_file=None):
    """Start the watchdog (idempotent). Default timeout from
    FLAGS_pg_timeout-equivalent env PADDLE_PG_TIMEOUT (seconds, default 1800).

    When `report_file` (or env PADDLE_WD_REPORT_FILE — set per worker by the
    launcher) is given, a spill thread appends every timeout report to that
    file as it happens, so a worker that hangs and is later killed still
    leaves its post-mortem on disk. With PADDLE_WD_FATAL=1 the spill also
    prints a FatalError line to stderr, which the launcher's log watcher
    treats as grounds to tear down and restart the hung pod."""
    global _wd, _spill
    with _lock:
        if _wd is None:
            lib = native.load()
            if lib is None:
                return False
            if timeout_seconds is None:
                timeout_seconds = float(
                    os.environ.get("PADDLE_PG_TIMEOUT", "1800"))
            _wd = (lib, lib.watchdog_create(int(timeout_seconds * 1000)))
        # the spill thread starts whenever a report file is configured and
        # none is running yet — including on a repeat enable() after an
        # earlier caller enabled the watchdog without one
        report_file = report_file or os.environ.get("PADDLE_WD_REPORT_FILE")
        if report_file and _spill is None:
            fatal = os.environ.get("PADDLE_WD_FATAL") == "1"
            stop = threading.Event()
            t = threading.Thread(target=_spill_loop,
                                 args=(stop, report_file, fatal),
                                 daemon=True, name="wd-spill")
            t.start()
            _spill = (t, stop)
        return True


def disable():
    global _wd, _spill
    with _lock:
        spill, _spill = _spill, None
        if spill is not None:
            spill[1].set()
    # join OUTSIDE the lock: the spill thread's drain_report needs the lock
    if spill is not None:
        spill[0].join(timeout=2)
    with _lock:
        if _wd is not None:
            _pump_locked()  # keep unread report text peekable post-disable
            lib, h = _wd
            _wd = None
            if spill is None or not spill[0].is_alive():
                lib.watchdog_destroy(h)
            # else: the spill thread is wedged (e.g. fsync on a hung mount);
            # leak the native handle rather than free it under the thread


@contextlib.contextmanager
def comm_task(desc: str, timeout_seconds=None, kind: str = "comm"):
    """Track a blocking region; near-free when the watchdog is off and no
    task observer is registered. Observers see every region's (desc, start,
    end, kind) interval regardless of whether the native watchdog is
    enabled — deadline enforcement needs the native thread, timeline
    stitching does not. `kind="comm"` (default) marks communication whose
    exposed time the overlap accounting charges; pass `kind="step"` (or any
    other tag) for deadline-only regions like a whole train step."""
    with _lock:
        wd = _wd
        if wd is None:
            tid = None
        else:
            lib, h = wd
            tid = lib.watchdog_register(h, desc.encode(),
                                        int((timeout_seconds or 0) * 1000))
    t0 = time.perf_counter_ns() if _task_observers else None
    try:
        yield
    finally:
        if tid is not None:
            with _lock:
                # a concurrent disable() may have destroyed the handle while
                # this region ran — completing on it would be a use-after-free
                if _wd is wd:
                    lib.watchdog_complete(h, tid)
        # t0 None: no observer was registered at entry — an observer added
        # mid-region must not receive a garbage interval. record_task's
        # per-observer error isolation also keeps an observer failure from
        # masking the region's own exception (we are in a finally block).
        if _task_observers and t0 is not None:
            record_task(desc, t0, time.perf_counter_ns(), kind)


def drain_report() -> str:
    """Return report text not yet consumed by a previous drain (destructive
    with respect to other drain callers, like the native buffer was — the
    spill thread's append-to-file contract depends on it — but the text is
    retained for peek_report()/report_events())."""
    global _report_cursor
    # under _lock: disable() must not watchdog_destroy the handle while a
    # reader (the spill thread in particular) is inside the native call
    with _lock:
        _pump_locked()
        fresh = "".join(_report_history[_report_cursor:])
        _report_cursor = len(_report_history)
    return fresh


def peek_report() -> str:
    """Non-destructive view of every retained report line (flight recorder's
    channel — reading here never steals text from the spill path)."""
    with _lock:
        _pump_locked()
        return "".join(_report_history)


# native/watchdog.cc line shape:
#   [watchdog] task 3 'train_step/7' exceeded 500ms (1234ms elapsed)
_REPORT_LINE_RE = re.compile(
    r"\[watchdog\] task (\d+) '(.*)' exceeded (\d+)ms \((\d+)ms")


def report_events() -> list[dict]:
    """peek_report() parsed into structured events: one dict per timed-out
    task with task id, description, deadline and observed elapsed time."""
    events = []
    for line in peek_report().splitlines():
        m = _REPORT_LINE_RE.search(line)
        if m:
            events.append({
                "task_id": int(m.group(1)),
                "desc": m.group(2),
                "timeout_ms": int(m.group(3)),
                "elapsed_ms": int(m.group(4)),
            })
    return events


def timeout_count() -> int:
    with _lock:
        if _wd is None:
            return 0
        lib, h = _wd
        return int(lib.watchdog_timeout_count(h))


def inflight() -> int:
    with _lock:
        if _wd is None:
            return 0
        lib, h = _wd
        return int(lib.watchdog_inflight(h))
