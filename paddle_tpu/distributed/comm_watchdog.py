"""Collective hang watchdog — native monitor thread flagging stuck steps.

Reference: CommTaskManager (paddle/phi/core/distributed/comm_task_manager.h:37)
with per-collective timeout tracking (comm_task.h:127 IsTimeout) — the
practical distributed deadlock detector.

TPU-native: collectives are compiled into programs, so the tracked unit is a
blocking region (a dispatched train step, an eager collective, a host sync).
Wrap suspect regions in `comm_task(...)`; the native thread
(native/watchdog.cc) flags any region exceeding its deadline and the report
surfaces on the next poll — exactly the "log stuck rings" behavior.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import threading

from ..framework import native

__all__ = ["enable", "disable", "comm_task", "drain_report", "timeout_count",
           "inflight"]

_wd = None
_lock = threading.Lock()
_spill = None  # (thread, stop_event)


def _spill_once(path, fatal):
    report = drain_report()
    if not report:
        return
    try:
        with open(path, "a") as f:
            f.write(report)
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:
        # the drain already emptied the native buffer — losing the report
        # here would erase the only record of the hang; stderr (→ worker
        # log) is the fallback channel
        import sys

        print(f"[comm_watchdog] report file {path} unwritable ({e}); "
              f"report follows:\n{report}", file=sys.stderr, flush=True)
    if fatal:
        # a hung step can't log its own death — this line, written by the
        # spill thread, is what the launcher's LogWatcher pattern-matches to
        # tear the wedged pod down and restart it (launch/main.py)
        import sys

        print("FatalError: comm watchdog deadline exceeded\n" + report,
              file=sys.stderr, flush=True)


def _spill_loop(stop, path, fatal, interval=0.5):
    while not stop.wait(interval):
        if _wd is None:
            return
        _spill_once(path, fatal)


def enable(timeout_seconds=None, report_file=None):
    """Start the watchdog (idempotent). Default timeout from
    FLAGS_pg_timeout-equivalent env PADDLE_PG_TIMEOUT (seconds, default 1800).

    When `report_file` (or env PADDLE_WD_REPORT_FILE — set per worker by the
    launcher) is given, a spill thread appends every timeout report to that
    file as it happens, so a worker that hangs and is later killed still
    leaves its post-mortem on disk. With PADDLE_WD_FATAL=1 the spill also
    prints a FatalError line to stderr, which the launcher's log watcher
    treats as grounds to tear down and restart the hung pod."""
    global _wd, _spill
    with _lock:
        if _wd is None:
            lib = native.load()
            if lib is None:
                return False
            if timeout_seconds is None:
                timeout_seconds = float(
                    os.environ.get("PADDLE_PG_TIMEOUT", "1800"))
            _wd = (lib, lib.watchdog_create(int(timeout_seconds * 1000)))
        # the spill thread starts whenever a report file is configured and
        # none is running yet — including on a repeat enable() after an
        # earlier caller enabled the watchdog without one
        report_file = report_file or os.environ.get("PADDLE_WD_REPORT_FILE")
        if report_file and _spill is None:
            fatal = os.environ.get("PADDLE_WD_FATAL") == "1"
            stop = threading.Event()
            t = threading.Thread(target=_spill_loop,
                                 args=(stop, report_file, fatal),
                                 daemon=True, name="wd-spill")
            t.start()
            _spill = (t, stop)
        return True


def disable():
    global _wd, _spill
    with _lock:
        spill, _spill = _spill, None
        if spill is not None:
            spill[1].set()
    # join OUTSIDE the lock: the spill thread's drain_report needs the lock
    if spill is not None:
        spill[0].join(timeout=2)
    with _lock:
        if _wd is not None:
            lib, h = _wd
            _wd = None
            if spill is None or not spill[0].is_alive():
                lib.watchdog_destroy(h)
            # else: the spill thread is wedged (e.g. fsync on a hung mount);
            # leak the native handle rather than free it under the thread


@contextlib.contextmanager
def comm_task(desc: str, timeout_seconds=None):
    """Track a blocking region; no-op when the watchdog is off."""
    with _lock:
        wd = _wd
        if wd is None:
            tid = None
        else:
            lib, h = wd
            tid = lib.watchdog_register(h, desc.encode(),
                                        int((timeout_seconds or 0) * 1000))
    if tid is None:
        yield
        return
    try:
        yield
    finally:
        with _lock:
            # a concurrent disable() may have destroyed the handle while
            # this region ran — completing on it would be a use-after-free
            if _wd is wd:
                lib.watchdog_complete(h, tid)


def drain_report() -> str:
    # under _lock: disable() must not watchdog_destroy the handle while a
    # reader (the spill thread in particular) is inside the native call
    with _lock:
        if _wd is None:
            return ""
        lib, h = _wd
        buf = ctypes.create_string_buffer(1 << 16)
        n = lib.watchdog_drain_report(h, buf, len(buf))
    return buf.raw[:n].decode(errors="replace")


def timeout_count() -> int:
    with _lock:
        if _wd is None:
            return 0
        lib, h = _wd
        return int(lib.watchdog_timeout_count(h))


def inflight() -> int:
    with _lock:
        if _wd is None:
            return 0
        lib, h = _wd
        return int(lib.watchdog_inflight(h))
