"""Collective hang watchdog — native monitor thread flagging stuck steps.

Reference: CommTaskManager (paddle/phi/core/distributed/comm_task_manager.h:37)
with per-collective timeout tracking (comm_task.h:127 IsTimeout) — the
practical distributed deadlock detector.

TPU-native: collectives are compiled into programs, so the tracked unit is a
blocking region (a dispatched train step, an eager collective, a host sync).
Wrap suspect regions in `comm_task(...)`; the native thread
(native/watchdog.cc) flags any region exceeding its deadline and the report
surfaces on the next poll — exactly the "log stuck rings" behavior.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import threading

from ..framework import native

__all__ = ["enable", "disable", "comm_task", "drain_report", "timeout_count",
           "inflight"]

_wd = None
_lock = threading.Lock()


def enable(timeout_seconds=None):
    """Start the watchdog (idempotent). Default timeout from
    FLAGS_pg_timeout-equivalent env PADDLE_PG_TIMEOUT (seconds, default 1800)."""
    global _wd
    with _lock:
        if _wd is not None:
            return True
        lib = native.load()
        if lib is None:
            return False
        if timeout_seconds is None:
            timeout_seconds = float(os.environ.get("PADDLE_PG_TIMEOUT", "1800"))
        _wd = (lib, lib.watchdog_create(int(timeout_seconds * 1000)))
        return True


def disable():
    global _wd
    with _lock:
        if _wd is not None:
            lib, h = _wd
            lib.watchdog_destroy(h)
            _wd = None


@contextlib.contextmanager
def comm_task(desc: str, timeout_seconds=None):
    """Track a blocking region; no-op when the watchdog is off."""
    if _wd is None:
        yield
        return
    lib, h = _wd
    tid = lib.watchdog_register(h, desc.encode(),
                                int((timeout_seconds or 0) * 1000))
    try:
        yield
    finally:
        lib.watchdog_complete(h, tid)


def drain_report() -> str:
    if _wd is None:
        return ""
    lib, h = _wd
    buf = ctypes.create_string_buffer(1 << 16)
    n = lib.watchdog_drain_report(h, buf, len(buf))
    return buf.raw[:n].decode(errors="replace")


def timeout_count() -> int:
    if _wd is None:
        return 0
    lib, h = _wd
    return int(lib.watchdog_timeout_count(h))


def inflight() -> int:
    if _wd is None:
        return 0
    lib, h = _wd
    return int(lib.watchdog_inflight(h))
