"""Checkpoint lifecycle: discovery, validation, rotation, async double-buffer.

The durable layout under a checkpoint root is

    root/
      step_100/        committed: COMMIT marker + 0.metadata + *.distcp
      step_200/
      step_300.tmp/    a save that died mid-write (ignored, swept by rotation)

`latest_checkpoint(root)` walks the step directories newest-first and returns
the first one that VALIDATES (commit marker present, metadata parseable,
every referenced shard file on disk with a matching crc32) — a truncated,
corrupt, or uncommitted checkpoint is skipped with a warning and the previous
good step is used, which is the whole recovery contract: a crash at any
point costs at most the steps since the last commit, never the run.

`CheckpointManager` drives periodic saves for a trainer: step-numbered
directories, keep-last-N rotation (oldest committed dirs removed AFTER the
new commit lands, markers first so a crash mid-delete can't fake a valid
checkpoint), and optional async double-buffered saves (device→host snapshot
on the train thread, write+commit+rotate on one background thread, at most
one save in flight).
"""

from __future__ import annotations

import collections
import os
import re
import shutil
import sys
import threading

from .load_state_dict import load_state_dict
from .metadata import COMMIT_FILE, CheckpointCorruptError, Metadata, \
    crc32_file, metadata_path
from .save_state_dict import _snapshot, _write_and_commit, save_state_dict

__all__ = [
    "CheckpointInfo", "latest_checkpoint", "validate_checkpoint",
    "checkpoint_steps", "CheckpointManager", "wait_async_save",
]

_STEP_RE = re.compile(r"^step_(\d+)$")

CheckpointInfo = collections.namedtuple("CheckpointInfo", ["path", "step"])


# --------------------------------------------------------------------------- #
# discovery / validation
# --------------------------------------------------------------------------- #

def checkpoint_steps(root):
    """All step-numbered checkpoint dirs under `root` (committed or not),
    sorted ascending by step: [(step, path)]."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for d in names:
        m = _STEP_RE.match(d)
        p = os.path.join(root, d)
        if m and os.path.isdir(p):
            out.append((int(m.group(1)), p))
    return sorted(out)


def validate_checkpoint(path, verify_checksums=True):
    """(ok, reason) — commit marker present, metadata loads, every referenced
    shard file exists and (when recorded) matches its crc32."""
    if not os.path.isfile(os.path.join(path, COMMIT_FILE)):
        return False, "no COMMIT marker (save was interrupted)"
    try:
        meta = Metadata.load(metadata_path(path))
    except (OSError, ValueError, KeyError, TypeError) as e:
        return False, f"metadata unreadable: {e!r}"
    files = {m.file_name
             for v in meta.state_dict_metadata.values() for m in v}
    for fname in sorted(files):
        fpath = os.path.join(path, fname)
        if not os.path.isfile(fpath):
            return False, f"shard file missing: {fname}"
        expected = meta.file_checksums.get(fname, "")
        if verify_checksums and expected:
            try:
                if crc32_file(fpath) != expected:
                    return False, f"shard file corrupt (crc mismatch): {fname}"
            except OSError as e:
                # EIO/EACCES/vanished-under-us are exactly the cases
                # discovery must fall back past, not crash on
                return False, f"shard file unreadable: {fname} ({e})"
    return True, ""


def latest_checkpoint(root, verify_checksums=True):
    """Newest VALID checkpoint under `root`, or None. Falls back past
    corrupt/partial/uncommitted steps (each skip is logged to stderr)."""
    for step, path in reversed(checkpoint_steps(root)):
        ok, reason = validate_checkpoint(path, verify_checksums)
        if ok:
            return CheckpointInfo(path, step)
        print(f"[checkpoint] skipping {path}: {reason}", file=sys.stderr)
    return None


# --------------------------------------------------------------------------- #
# async double-buffered saver
# --------------------------------------------------------------------------- #

class _SaveHandle(threading.Thread):
    def __init__(self, fn):
        super().__init__(daemon=True, name="ckpt-async-save")
        self._fn = fn
        self._exc = None

    def run(self):
        try:
            self._fn()
        except BaseException as e:  # surfaced on wait()/next submit
            self._exc = e

    def result(self, timeout=None):
        self.join(timeout)
        if self.is_alive():
            raise TimeoutError("async checkpoint save still in flight")
        if self._exc is not None:
            raise self._exc


class _AsyncSaver:
    """At most ONE save in flight. submit() first drains the previous save
    (re-raising its failure), so commits stay ordered and memory is bounded
    to two snapshots: the one being written and the one just taken."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending = None

    def submit(self, fn):
        with self._lock:
            self._drain()
            h = _SaveHandle(fn)
            h.start()
            self._pending = h
            return h

    def _drain(self):
        h, self._pending = self._pending, None
        if h is not None:
            h.result()

    def wait(self):
        with self._lock:
            self._drain()


_async_saver = _AsyncSaver()


def wait_async_save():
    """Drain the module-level saver used by bare `save_state_dict(...,
    async_save=True)` calls; re-raises its exception on failure. Each
    CheckpointManager owns a separate saver — use `manager.wait()` there."""
    _async_saver.wait()


# --------------------------------------------------------------------------- #
# manager
# --------------------------------------------------------------------------- #

class CheckpointManager:
    """Periodic checkpointing with rotation for a training loop.

        mgr = CheckpointManager(root, keep_last_n=3, async_save=True)
        start = mgr.restore_latest(state_dict)   # None on a fresh run
        ...
        mgr.save(state_dict, step)
        ...
        mgr.wait()                               # flush before exit
    """

    def __init__(self, root, keep_last_n=3, async_save=False):
        self.root = root
        self.keep_last_n = max(1, int(keep_last_n))
        self.async_save = async_save
        # own saver, not the module singleton: two managers (e.g. model vs
        # EMA roots) must not serialize behind each other or surface each
        # other's failures
        self._saver = _AsyncSaver()
        os.makedirs(root, exist_ok=True)

    def path_for(self, step):
        return os.path.join(self.root, f"step_{int(step)}")

    def save(self, state_dict, step):
        """Atomically commit `state_dict` as step `step`; rotation runs after
        the commit (on the saver thread when async)."""
        import jax

        path = self.path_for(step)
        if self.async_save and jax.process_count() == 1:
            plan = _snapshot(state_dict)
            return self._saver.submit(
                lambda: _write_and_commit(plan, path, 0,
                                          post_commit=self._rotate))
        return save_state_dict(state_dict, path, _post_commit=self._rotate)

    def wait(self):
        self._saver.wait()

    def latest(self, verify_checksums=True):
        return latest_checkpoint(self.root, verify_checksums)

    def restore_latest(self, state_dict):
        """Load the newest valid checkpoint into `state_dict` (in place,
        resharding onto each tensor's current placement). Returns the step
        restored from, or None when no valid checkpoint exists.

        Checksums are verified ONCE, by the load itself — discovery here
        checks structure only (COMMIT + metadata + file presence) so a
        multi-GB restore doesn't read and crc every shard file twice. A
        load-time corruption hit falls back to the next older candidate."""
        for step, path in reversed(checkpoint_steps(self.root)):
            ok, reason = validate_checkpoint(path, verify_checksums=False)
            if not ok:
                print(f"[checkpoint] skipping {path}: {reason}",
                      file=sys.stderr)
                continue
            # the load mutates tensors in place one-by-one; a corruption hit
            # on a LATER shard file must not leave a half-restored mix of
            # checkpoint and live values behind the fallback (or behind the
            # final "no valid checkpoint" fresh-start report). jax arrays are
            # immutable, so snapshotting is reference-holding, not copying.
            snapshot = [(k, v, getattr(v, "_value", None))
                        for k, v in state_dict.items()]
            try:
                load_state_dict(state_dict, path)
                return step
            except BaseException as e:
                # roll back on ANY mid-load failure — a KeyError (key absent
                # from this checkpoint) or a KeyboardInterrupt leaves the
                # same half-mutated mix corruption does
                for k, v, val in snapshot:
                    if val is not None:
                        v._value = val
                    state_dict[k] = v
                if not isinstance(e, CheckpointCorruptError):
                    raise
                print(f"[checkpoint] skipping {path}: {e}", file=sys.stderr)
        return None

    def _rotate(self):
        """Drop committed checkpoints beyond keep_last_n (oldest first) and
        sweep stale .tmp dirs. Runs post-commit, so an in-flight save can
        never be rotated away. COMMIT marker is removed before the rmtree:
        a crash mid-delete leaves an invalid husk, not a liar."""
        steps = checkpoint_steps(self.root)
        committed = [(s, p) for s, p in steps
                     if os.path.isfile(os.path.join(p, COMMIT_FILE))]
        for _, path in committed[:-self.keep_last_n]:
            self._remove(path)
        # only sweep .tmp dirs at or below the newest committed step: in
        # multi-process runs the commit barrier releases peers before this
        # post_commit hook runs, so a HIGHER-step .tmp may already be the
        # next save being written
        newest = committed[-1][0] if committed else -1
        for d in os.listdir(self.root):
            m = _STEP_RE.match(d[:-4]) if d.endswith(".tmp") else None
            if m and int(m.group(1)) <= newest:
                shutil.rmtree(os.path.join(self.root, d), ignore_errors=True)

    @staticmethod
    def _remove(path):
        try:
            os.unlink(os.path.join(path, COMMIT_FILE))
        except OSError:
            pass
        shutil.rmtree(path, ignore_errors=True)
