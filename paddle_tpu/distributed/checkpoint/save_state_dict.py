"""Sharded checkpoint save — crash-safe commit protocol.

Reference: python/paddle/distributed/checkpoint/save_state_dict.py:135 —
every rank writes the shards it owns plus rank-0 writes a metadata file
mapping global tensors → (offset, shape, file).

TPU-native: the single controller owns global jax.Arrays whose addressable
shards live on local devices; each PROCESS writes one `{pid}_0.distcp` npz
with its addressable unique shards (multi-host: each host persists only its
slice — no cross-host traffic), and process 0 writes `0.metadata`. Dedup of
replicated shards follows the reference's coordinator rule: the lowest
process id owning a shard writes it.

Commit protocol (crash safety): nothing is ever written into `path` itself.
All files land in `path + ".tmp"`; after shards and metadata are written and
fsync'd the coordinator drops a COMMIT marker and renames the directory to
`path` in one atomic step. A save killed at ANY instant leaves either the
previous committed checkpoint untouched, or a `.tmp` directory that
discovery (`latest_checkpoint`) ignores and the next save sweeps away — no
manual cleanup ever required. Shard files and metadata carry crc32 checksums
so on-disk corruption after commit is also detected at load.

`async_save=True` snapshots device arrays to host immediately (so the train
step can keep mutating them) and runs the write+commit phase on a background
thread, double-buffered: at most one save is in flight, and submitting the
next one first drains the previous. Multi-process runs fall back to
synchronous saves — the metadata all-gather doubles as the "all shards
written" barrier and must not race the training step's collectives.
"""

from __future__ import annotations

import os
import shutil

import jax
import numpy as np

from ...framework.core import Tensor
from .. import faults
from .metadata import (
    COMMIT_FILE,
    LocalTensorMetadata,
    Metadata,
    crc32_file,
    crc32_of,
    metadata_path,
)

__all__ = ["save_state_dict"]


def _shard_key(name, offset):
    return name + "|" + ",".join(map(str, offset))


def _snapshot(state_dict):
    """Device→host snapshot: shard arrays (np copies), metadata entries, and
    the shard file name this process will write. Runs on the caller's thread
    so an async save is immune to later in-place updates of the tensors."""
    pid = jax.process_index()
    fname = f"{pid}_0.distcp"
    shards = {}
    meta_entries = {}
    global_shapes = {}

    for name, t in state_dict.items():
        v = t._value if isinstance(t, Tensor) else jax.numpy.asarray(t)
        global_shapes[name] = tuple(v.shape)
        entries = []
        seen_offsets = set()
        if isinstance(v, jax.Array) and v.sharding is not None:
            # unique shards this process owns; replicas dedup to the lowest
            # owning process (the reference's coordinator-rank rule)
            for shard in v.addressable_shards:
                idx = shard.index
                offset = tuple(
                    0 if sl.start is None else int(sl.start) for sl in idx)
                if offset in seen_offsets:
                    continue
                # which processes hold this exact shard?
                owners = [
                    d.process_index
                    for d in v.sharding.device_set
                    if v.sharding.devices_indices_map(v.shape)[d] == idx
                ]
                if min(owners) != pid:
                    continue
                seen_offsets.add(offset)
                # copy=True is load-bearing: np.asarray of a jax.Array can be
                # a zero-copy VIEW of the XLA buffer, and with buffer
                # donation the next train step reuses that memory while the
                # async writer is still serializing it
                data = np.array(shard.data, copy=True)
                key = _shard_key(name, offset)
                shards[key] = data
                # checksum filled in by _write_and_commit — hashing belongs
                # on the (possibly background) write thread, not here on the
                # train thread
                entries.append(LocalTensorMetadata(
                    offset, tuple(data.shape), str(data.dtype), fname, key))
        else:
            data = np.array(v, copy=True)  # see copy=True note above
            key = _shard_key(name, (0,) * data.ndim)
            shards[key] = data
            entries.append(LocalTensorMetadata(
                (0,) * data.ndim, tuple(data.shape), str(data.dtype), fname,
                key))
        if entries:
            meta_entries[name] = entries
    return shards, meta_entries, global_shapes, fname


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_and_commit(plan, path, coordinator_rank, post_commit=None):
    shards, meta_entries, global_shapes, fname = plan
    pid = jax.process_index()
    nproc = jax.process_count()
    is_coord = pid == coordinator_rank or nproc == 1
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    # NO rmtree of a stale tmp here: with nproc>1 a peer may already be
    # writing its shard into tmp before the coordinator arrives, and
    # deleting the dir would eat that live shard. Same-named files simply
    # overwrite their stale versions; leftover strays from a crashed save
    # are swept by the coordinator after the gather (when every peer has
    # provably finished writing).
    os.makedirs(tmp, exist_ok=True)
    if is_coord:
        # a save that died between COMMIT and rename leaves a committed-
        # looking tmp; drop the marker first so the rebuilt tmp can never
        # be mistaken for complete before this save's own commit
        try:
            os.unlink(os.path.join(tmp, COMMIT_FILE))
        except OSError:
            pass

    faults.fault_point("ckpt.before_shards")
    # per-shard crcs (defense in depth for metadata whose file-level crc is
    # missing): computed here so the hashing cost lands on this (possibly
    # background) thread, off the train step's critical path
    for entries in meta_entries.values():
        for e in entries:
            e.checksum = crc32_of(shards[e.key])
    # stream the npz straight to disk (no in-memory container copy — the
    # snapshot alone is already one full host copy of the shards), then crc
    # the written file: the recorded checksum covers the exact on-disk bytes
    fpath = os.path.join(tmp, fname)
    with open(fpath, "wb") as f:
        np.savez(f, **shards)  # exact name (np.savez would append .npz)
        f.flush()
        os.fsync(f.fileno())
    file_crc = crc32_file(fpath)
    faults.fault_point("ckpt.mid_save")  # shards on disk, metadata absent

    file_checksums = {fname: file_crc}
    # merge metadata across processes: single-host writes directly; multi-host
    # uses the all-gather-object collective (process 0 persists). The gather
    # is also the barrier proving every process finished its shard file —
    # COMMIT must never cover a file still being written on another host.
    if nproc > 1:
        from ..collective import all_gather_object

        gathered = []
        all_gather_object(gathered, (meta_entries, global_shapes, file_checksums))
        merged, shapes, crcs = {}, {}, {}
        for me, gs, fc in gathered:
            shapes.update(gs)
            crcs.update(fc)
            for k, v in me.items():
                merged.setdefault(k, []).extend(v)
        meta_entries, global_shapes, file_checksums = merged, shapes, crcs

    if is_coord:
        # sweep strays from a previous crashed save of this same step: by
        # this point the gather proved every peer finished writing, and the
        # gathered file set is exactly what this save owns
        keep = set(file_checksums) | {os.path.basename(metadata_path(tmp))}
        for stray in os.listdir(tmp):
            if stray not in keep and stray != COMMIT_FILE:
                try:
                    os.unlink(os.path.join(tmp, stray))
                except OSError:
                    pass
        Metadata(meta_entries, global_shapes,
                 file_checksums=file_checksums).save(metadata_path(tmp))
        faults.fault_point("ckpt.before_commit")  # metadata written, no COMMIT
        with open(os.path.join(tmp, COMMIT_FILE), "w") as f:
            f.write('{"format": 1}\n')
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)
        faults.fault_point("ckpt.before_rename")  # committed, not yet visible
        if os.path.isdir(path):
            _replace_into(tmp, path)
        else:
            os.rename(tmp, path)
        _fsync_dir(parent)
    if nproc > 1:
        # post-commit barrier: without it a peer could start its NEXT save
        # into the same shared tmp dir while the coordinator is still
        # between the gather and the rename, committing foreign bytes under
        # this save's metadata
        from ..collective import all_gather_object

        all_gather_object([], ("commit_done", path))
    if is_coord and post_commit is not None:
        post_commit()


def _replace_into(tmp, path):
    """Overwrite an EXISTING checkpoint dir without deleting unrelated files
    a user may keep alongside it (the pre-hardening save wrote in place and
    preserved them — rmtree'ing the dir would be silent data loss). Not a
    single atomic rename, but ordered for the same guarantee: the old COMMIT
    falls first, the new one lands last, so the dir is never valid with
    mixed contents."""
    try:
        os.unlink(os.path.join(path, COMMIT_FILE))
    except OSError:
        pass
    for name in os.listdir(tmp):
        if name != COMMIT_FILE:
            os.replace(os.path.join(tmp, name), os.path.join(path, name))
    _fsync_dir(path)  # data entries durable BEFORE the marker lands...
    os.replace(os.path.join(tmp, COMMIT_FILE), os.path.join(path, COMMIT_FILE))
    _fsync_dir(path)  # ...and the marker durable before save() returns
    shutil.rmtree(tmp, ignore_errors=True)


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False, _post_commit=None):
    """Save `state_dict` to the directory `path` (atomically committed).

    With `async_save=True` (single-process only) returns a handle whose
    `.result()` waits for the commit; `checkpoint.wait_async_save()` drains
    the in-flight save globally.
    """
    plan = _snapshot(state_dict)
    if async_save and jax.process_count() == 1:
        from .manager import _async_saver

        return _async_saver.submit(
            lambda: _write_and_commit(plan, path, coordinator_rank,
                                      post_commit=_post_commit))
    _write_and_commit(plan, path, coordinator_rank, post_commit=_post_commit)
    return None
