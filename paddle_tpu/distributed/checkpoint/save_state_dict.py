"""Sharded checkpoint save.

Reference: python/paddle/distributed/checkpoint/save_state_dict.py:135 —
every rank writes the shards it owns plus rank-0 writes a metadata file
mapping global tensors → (offset, shape, file).

TPU-native: the single controller owns global jax.Arrays whose addressable
shards live on local devices; each PROCESS writes one `{pid}_0.distcp` npz
with its addressable unique shards (multi-host: each host persists only its
slice — no cross-host traffic), and process 0 writes `0.metadata`. Dedup of
replicated shards follows the reference's coordinator rule: the lowest
process id owning a shard writes it.
"""

from __future__ import annotations

import os

import jax
import numpy as np

from ...framework.core import Tensor
from .metadata import LocalTensorMetadata, Metadata, metadata_path

__all__ = ["save_state_dict"]


def _shard_key(name, offset):
    return name + "|" + ",".join(map(str, offset))


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False):
    os.makedirs(path, exist_ok=True)
    pid = jax.process_index()
    fname = f"{pid}_0.distcp"
    shards = {}
    meta_entries = {}
    global_shapes = {}

    for name, t in state_dict.items():
        v = t._value if isinstance(t, Tensor) else jax.numpy.asarray(t)
        global_shapes[name] = tuple(v.shape)
        entries = []
        seen_offsets = set()
        if isinstance(v, jax.Array) and v.sharding is not None:
            # unique shards this process owns; replicas dedup to the lowest
            # owning process (the reference's coordinator-rank rule)
            for shard in v.addressable_shards:
                idx = shard.index
                offset = tuple(
                    0 if sl.start is None else int(sl.start) for sl in idx)
                if offset in seen_offsets:
                    continue
                # which processes hold this exact shard?
                owners = [
                    d.process_index
                    for d in v.sharding.device_set
                    if v.sharding.devices_indices_map(v.shape)[d] == idx
                ]
                if min(owners) != pid:
                    continue
                seen_offsets.add(offset)
                data = np.asarray(shard.data)
                key = _shard_key(name, offset)
                shards[key] = data
                entries.append(LocalTensorMetadata(
                    offset, tuple(data.shape), str(data.dtype), fname, key))
        else:
            data = np.asarray(v)
            key = _shard_key(name, (0,) * data.ndim)
            shards[key] = data
            entries.append(LocalTensorMetadata(
                (0,) * data.ndim, tuple(data.shape), str(data.dtype), fname, key))
        if entries:
            meta_entries[name] = entries

    with open(os.path.join(path, fname), "wb") as f:
        np.savez(f, **shards)  # exact name (np.savez would append .npz)

    # merge metadata across processes: single-host writes directly; multi-host
    # uses the all-gather-object collective (process 0 persists)
    if jax.process_count() > 1:
        from ..collective import all_gather_object

        gathered = []
        all_gather_object(gathered, (meta_entries, global_shapes))
        merged, shapes = {}, {}
        for me, gs in gathered:
            shapes.update(gs)
            for k, v in me.items():
                merged.setdefault(k, []).extend(v)
        meta_entries, global_shapes = merged, shapes
    if pid == coordinator_rank or jax.process_count() == 1:
        Metadata(meta_entries, global_shapes).save(metadata_path(path))
