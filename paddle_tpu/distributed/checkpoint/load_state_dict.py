"""Sharded checkpoint load with reshard-on-load.

Reference: python/paddle/distributed/checkpoint/load_state_dict.py:476 — reads
the metadata, computes the overlap between saved shards and the shards the
current parallel config needs, and exchanges/reads exactly those pieces.

TPU-native: for each target tensor we assemble the needed region from saved
shard files and `jax.make_array_from_callback` places it under the CURRENT
sharding — a checkpoint written under one (dp, mp, pp...) config loads under
any other (the reshard happens in the addressing, no collective needed).
"""

from __future__ import annotations

import os

import jax
import numpy as np

from ...framework.core import Tensor
from .metadata import Metadata, metadata_path

__all__ = ["load_state_dict"]


def _assemble(meta_list, global_shape, files_cache, path, region=None):
    """Assemble (a region of) the global tensor from saved shards.

    region: tuple of slices (None = full tensor).
    """
    if region is None:
        region = tuple(slice(0, s) for s in global_shape)
    out_shape = tuple(sl.stop - sl.start for sl in region)
    out = None
    for m in meta_list:
        if out is None:
            out = np.zeros(out_shape, np.dtype(m.dtype))
        fpath = os.path.join(path, m.file_name)
        if fpath not in files_cache:
            files_cache[fpath] = np.load(fpath)
        data = files_cache[fpath][m.key]
        # overlap of [offset, offset+shape) with region
        src_sl, dst_sl = [], []
        empty = False
        for d, (off, size, rsl) in enumerate(
                zip(m.global_offset, m.local_shape, region)):
            lo = max(off, rsl.start)
            hi = min(off + size, rsl.stop)
            if lo >= hi:
                empty = True
                break
            src_sl.append(slice(lo - off, hi - off))
            dst_sl.append(slice(lo - rsl.start, hi - rsl.start))
        if empty:
            continue
        out[tuple(dst_sl)] = data[tuple(src_sl)]
    return out


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, offload=False):
    """Fill `state_dict`'s tensors in place from the checkpoint at `path`,
    resharding saved shards onto each tensor's current sharding."""
    meta = Metadata.load(metadata_path(path))
    files_cache = {}
    for name, t in state_dict.items():
        if name not in meta.state_dict_metadata:
            raise KeyError(f"{name} not found in checkpoint {path}")
        entries = meta.state_dict_metadata[name]
        gshape = meta.global_shapes[name]
        target = t._value if isinstance(t, Tensor) else None
        if isinstance(target, jax.Array) and target.sharding is not None \
                and not target.sharding.is_fully_replicated:
            sharding = target.sharding

            def cb(index, _entries=entries, _gshape=gshape):
                region = tuple(
                    slice(0 if sl.start is None else sl.start,
                          _gshape[d] if sl.stop is None else sl.stop)
                    for d, sl in enumerate(index))
                return _assemble(_entries, _gshape, files_cache, path, region)

            arr = jax.make_array_from_callback(tuple(gshape), sharding, cb)
        else:
            full = _assemble(entries, gshape, files_cache, path)
            arr = jax.numpy.asarray(full)
            # replicate onto the target's mesh only if the target is actually
            # multi-device; committing to a single device would poison later
            # mixed ops with sharded tensors
            if isinstance(target, jax.Array) and len(target.sharding.device_set) > 1:
                arr = jax.device_put(arr, target.sharding)
        if isinstance(t, Tensor):
            t._value = arr.astype(t._value.dtype) if t._value.dtype != arr.dtype else arr
        else:
            state_dict[name] = Tensor(arr)
    return state_dict
