"""Sharded checkpoint load with reshard-on-load.

Reference: python/paddle/distributed/checkpoint/load_state_dict.py:476 — reads
the metadata, computes the overlap between saved shards and the shards the
current parallel config needs, and exchanges/reads exactly those pieces.

TPU-native: for each target tensor we assemble the needed region from saved
shard files and `jax.make_array_from_callback` places it under the CURRENT
sharding — a checkpoint written under one (dp, mp, pp...) config loads under
any other (the reshard happens in the addressing, no collective needed).

Integrity: shard files are verified against the crc32 recorded in the
metadata the first time they are opened, and each shard array against its
per-shard crc32 as it is read — a truncated or bit-flipped file raises
CheckpointCorruptError naming the file, never loads silently. Legacy
checkpoints without checksums still load (nothing to verify against).
"""

from __future__ import annotations

import os

import jax
import numpy as np

from ...framework.core import Tensor
from .metadata import (
    CheckpointCorruptError,
    Metadata,
    crc32_file,
    crc32_of,
    metadata_path,
)

__all__ = ["load_state_dict"]


def _open_shard_file(path, fname, files_cache, file_checksums, files_crc_ok):
    """Verify + open a shard file once, caching the (lazy) npz handle. The
    crc pass streams the on-disk bytes in chunks and np.load then reads
    members lazily from disk — peak memory stays one assembled tensor, not
    the whole file. Files that pass the file-level crc are recorded in
    `files_crc_ok`: their bytes are already proven intact, so the per-shard
    crcs (a fallback for metadata lacking file checksums) can be skipped."""
    fpath = os.path.join(path, fname)
    if fpath in files_cache:
        return files_cache[fpath]
    expected = file_checksums.get(fname, "")
    try:
        if expected:
            got = crc32_file(fpath)
            if got != expected:
                raise CheckpointCorruptError(
                    f"checkpoint shard file corrupt (checksum mismatch): "
                    f"{fpath} (expected {expected}, got {got})")
            files_crc_ok.add(fname)
    except OSError as e:
        raise CheckpointCorruptError(
            f"checkpoint shard file missing/unreadable: {fpath} ({e})") from e
    try:
        npz = np.load(fpath)
    except FileNotFoundError as e:
        # reachable for legacy checkpoints with no file checksum to probe
        raise CheckpointCorruptError(
            f"checkpoint shard file missing: {fpath} ({e})") from e
    except Exception as e:
        raise CheckpointCorruptError(
            f"checkpoint shard file unparseable (truncated write?): {fpath} "
            f"({e})") from e
    files_cache[fpath] = npz
    return npz


def _assemble(meta_list, global_shape, files_cache, path, region=None,
              file_checksums=None, verified=None, files_crc_ok=None):
    """Assemble (a region of) the global tensor from saved shards.

    region: tuple of slices (None = full tensor). `verified` collects
    (file, key) pairs whose per-shard crc already passed — the reshard
    callback runs once per device and must not re-hash the same shard D
    times; `files_crc_ok` skips per-shard crcs entirely for files whose
    file-level crc already proved every byte.
    """
    if region is None:
        region = tuple(slice(0, s) for s in global_shape)
    out_shape = tuple(sl.stop - sl.start for sl in region)
    out = None
    files_crc_ok = files_crc_ok if files_crc_ok is not None else set()
    for m in meta_list:
        if out is None:
            out = np.zeros(out_shape, np.dtype(m.dtype))
        npz = _open_shard_file(path, m.file_name, files_cache,
                               file_checksums or {}, files_crc_ok)
        try:
            data = npz[m.key]
        except Exception as e:
            raise CheckpointCorruptError(
                f"shard '{m.key}' unreadable in "
                f"{os.path.join(path, m.file_name)} ({e})") from e
        vkey = (m.file_name, m.key)
        if m.checksum and m.file_name not in files_crc_ok \
                and (verified is None or vkey not in verified):
            if crc32_of(np.ascontiguousarray(data)) != m.checksum:
                raise CheckpointCorruptError(
                    f"shard '{m.key}' corrupt (checksum mismatch) in "
                    f"{os.path.join(path, m.file_name)}")
            if verified is not None:
                verified.add(vkey)
        # overlap of [offset, offset+shape) with region
        src_sl, dst_sl = [], []
        empty = False
        for d, (off, size, rsl) in enumerate(
                zip(m.global_offset, m.local_shape, region)):
            lo = max(off, rsl.start)
            hi = min(off + size, rsl.stop)
            if lo >= hi:
                empty = True
                break
            src_sl.append(slice(lo - off, hi - off))
            dst_sl.append(slice(lo - rsl.start, hi - rsl.start))
        if empty:
            continue
        out[tuple(dst_sl)] = data[tuple(src_sl)]
    return out


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, offload=False):
    """Fill `state_dict`'s tensors in place from the checkpoint at `path`,
    resharding saved shards onto each tensor's current sharding."""
    try:
        meta = Metadata.load(metadata_path(path))
    except OSError as e:
        raise CheckpointCorruptError(
            f"checkpoint metadata missing/unreadable: {metadata_path(path)} "
            f"({e}) — was this save interrupted before commit?") from e
    except (ValueError, KeyError, TypeError) as e:
        raise CheckpointCorruptError(
            f"checkpoint metadata corrupt: {metadata_path(path)} ({e!r})") from e
    files_cache = {}
    verified = set()
    files_crc_ok = set()
    for name, t in state_dict.items():
        if name not in meta.state_dict_metadata:
            raise KeyError(f"{name} not found in checkpoint {path}")
        entries = meta.state_dict_metadata[name]
        gshape = meta.global_shapes[name]
        target = t._value if isinstance(t, Tensor) else None
        if isinstance(target, jax.Array) and target.sharding is not None \
                and not target.sharding.is_fully_replicated:
            sharding = target.sharding

            def cb(index, _entries=entries, _gshape=gshape):
                region = tuple(
                    slice(0 if sl.start is None else sl.start,
                          _gshape[d] if sl.stop is None else sl.stop)
                    for d, sl in enumerate(index))
                return _assemble(_entries, _gshape, files_cache, path, region,
                                 file_checksums=meta.file_checksums,
                                 verified=verified,
                                 files_crc_ok=files_crc_ok)

            arr = jax.make_array_from_callback(tuple(gshape), sharding, cb)
        else:
            full = _assemble(entries, gshape, files_cache, path,
                             file_checksums=meta.file_checksums,
                             verified=verified,
                             files_crc_ok=files_crc_ok)
            arr = jax.numpy.asarray(full)
            # replicate onto the target's mesh only if the target is actually
            # multi-device; committing to a single device would poison later
            # mixed ops with sharded tensors
            if isinstance(target, jax.Array) and len(target.sharding.device_set) > 1:
                arr = jax.device_put(arr, target.sharding)
        if isinstance(t, Tensor):
            t._value = arr.astype(t._value.dtype) if t._value.dtype != arr.dtype else arr
        else:
            state_dict[name] = Tensor(arr)
    return state_dict
