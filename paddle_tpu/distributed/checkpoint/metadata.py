"""Checkpoint metadata: the map from global tensors to on-disk shards.

Reference: python/paddle/distributed/checkpoint/metadata.py — Metadata holds
{state_name: [LocalTensorMetadata]} where each local shard records its global
offset + local shape + the file that stores it.
"""

from __future__ import annotations

import dataclasses
import json
import os


@dataclasses.dataclass
class LocalTensorMetadata:
    global_offset: tuple  # start index of this shard in the global tensor
    local_shape: tuple
    dtype: str
    file_name: str
    key: str  # key inside the shard file


@dataclasses.dataclass
class LocalTensorIndex:
    tensor_key: str
    global_offset: tuple


@dataclasses.dataclass
class Metadata:
    state_dict_metadata: dict  # name -> [LocalTensorMetadata]
    global_shapes: dict        # name -> tuple
    flat_mapping: dict = dataclasses.field(default_factory=dict)

    def save(self, path):
        payload = {
            "state_dict_metadata": {
                k: [dataclasses.asdict(m) for m in v]
                for k, v in self.state_dict_metadata.items()
            },
            "global_shapes": {k: list(v) for k, v in self.global_shapes.items()},
            "flat_mapping": self.flat_mapping,
        }
        with open(path, "w") as f:
            json.dump(payload, f)

    @classmethod
    def load(cls, path):
        with open(path) as f:
            payload = json.load(f)
        return cls(
            state_dict_metadata={
                k: [LocalTensorMetadata(
                    tuple(m["global_offset"]), tuple(m["local_shape"]),
                    m["dtype"], m["file_name"], m["key"])
                    for m in v]
                for k, v in payload["state_dict_metadata"].items()
            },
            global_shapes={k: tuple(v) for k, v in payload["global_shapes"].items()},
            flat_mapping=payload.get("flat_mapping", {}),
        )


def metadata_path(dirname):
    return os.path.join(dirname, "0.metadata")
