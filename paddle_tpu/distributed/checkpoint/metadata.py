"""Checkpoint metadata: the map from global tensors to on-disk shards.

Reference: python/paddle/distributed/checkpoint/metadata.py — Metadata holds
{state_name: [LocalTensorMetadata]} where each local shard records its global
offset + local shape + the file that stores it.

Crash-safety additions: every shard records a crc32 of its array bytes and
the metadata records a crc32 of every shard FILE, so a torn or bit-flipped
write is detected at load/discovery time instead of being deserialized into
the model silently.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib

# the presence of this file inside a checkpoint directory marks the save as
# fully committed; saves that died mid-write never produce it
COMMIT_FILE = "COMMIT"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed checksum/structure validation. The message
    always names the offending file so the operator can see WHICH shard of
    WHICH step is bad."""


def crc32_of(data) -> str:
    """crc32 of any contiguous bytes-like object (bytes, or a C-contiguous
    numpy array via the buffer protocol — no .tobytes() copy needed)."""
    return "crc32:%08x" % (zlib.crc32(data) & 0xFFFFFFFF)


def crc32_file(path: str, chunk_size: int = 1 << 20) -> str:
    """Streamed file crc32 — verification must not require holding a
    multi-GB shard file in memory."""
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(chunk_size), b""):
            crc = zlib.crc32(chunk, crc)
    return "crc32:%08x" % (crc & 0xFFFFFFFF)


@dataclasses.dataclass
class LocalTensorMetadata:
    global_offset: tuple  # start index of this shard in the global tensor
    local_shape: tuple
    dtype: str
    file_name: str
    key: str  # key inside the shard file
    checksum: str = ""  # crc32 of the shard's array bytes ("" = legacy save)


@dataclasses.dataclass
class LocalTensorIndex:
    tensor_key: str
    global_offset: tuple


@dataclasses.dataclass
class Metadata:
    state_dict_metadata: dict  # name -> [LocalTensorMetadata]
    global_shapes: dict        # name -> tuple
    flat_mapping: dict = dataclasses.field(default_factory=dict)
    file_checksums: dict = dataclasses.field(default_factory=dict)  # fname -> crc32

    def save(self, path):
        payload = {
            "state_dict_metadata": {
                k: [dataclasses.asdict(m) for m in v]
                for k, v in self.state_dict_metadata.items()
            },
            "global_shapes": {k: list(v) for k, v in self.global_shapes.items()},
            "flat_mapping": self.flat_mapping,
            "file_checksums": self.file_checksums,
        }
        # fsync: the commit marker is only meaningful if the metadata it
        # covers has actually reached the disk first
        with open(path, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())

    @classmethod
    def load(cls, path):
        with open(path) as f:
            payload = json.load(f)
        return cls(
            state_dict_metadata={
                k: [LocalTensorMetadata(
                    tuple(m["global_offset"]), tuple(m["local_shape"]),
                    m["dtype"], m["file_name"], m["key"],
                    m.get("checksum", ""))
                    for m in v]
                for k, v in payload["state_dict_metadata"].items()
            },
            global_shapes={k: tuple(v) for k, v in payload["global_shapes"].items()},
            flat_mapping=payload.get("flat_mapping", {}),
            file_checksums=payload.get("file_checksums", {}),
        )


def metadata_path(dirname):
    return os.path.join(dirname, "0.metadata")
