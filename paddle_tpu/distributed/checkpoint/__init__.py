from .metadata import (  # noqa: F401
    COMMIT_FILE,
    CheckpointCorruptError,
    LocalTensorIndex,
    LocalTensorMetadata,
    Metadata,
)
from .load_state_dict import load_state_dict  # noqa: F401
from .save_state_dict import save_state_dict  # noqa: F401
from .manager import (  # noqa: F401
    CheckpointInfo,
    CheckpointManager,
    checkpoint_steps,
    latest_checkpoint,
    validate_checkpoint,
    wait_async_save,
)

__all__ = ["save_state_dict", "load_state_dict", "Metadata",
           "LocalTensorMetadata", "LocalTensorIndex", "CheckpointCorruptError",
           "COMMIT_FILE", "CheckpointInfo", "CheckpointManager",
           "checkpoint_steps", "latest_checkpoint", "validate_checkpoint",
           "wait_async_save"]
