"""paddle.distributed.rpc — remote procedure calls between workers.

Reference: python/paddle/distributed/rpc/rpc.py (brpc agent +
PythonFunc serialization + TCPStore rendezvous). TPU-native redesign: the
same API over a plain TCP request/response server per worker — the brpc
C++ agent exists to co-schedule with the PS runtime, which is out of scope
here; a thread-pooled socket server carries identical semantics:

- init_rpc(name, rank, world_size, master_endpoint): rendezvous through
  the native TCPStore (rank 0 hosts it at master_endpoint), register this
  worker's (name, rank, ip, port), exchange all worker infos, barrier.
- rpc_sync / rpc_async(to, fn, args, kwargs, timeout): pickle
  (fn, args, kwargs), send to the target worker over a fresh TCP
  connection, run there on a worker thread, return the pickled result
  (exceptions re-raise at the caller, like the reference).
- shutdown(): barrier (so no in-flight calls are dropped), then stop the
  server.

Like the reference, callables must be picklable (importable module-level
functions) and the transport trusts the cluster: only use on networks the
job controls.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import traceback
from collections import namedtuple
from concurrent.futures import Future, ThreadPoolExecutor

__all__ = [
    "init_rpc", "rpc_sync", "rpc_async", "shutdown", "get_worker_info",
    "get_all_worker_infos", "get_current_worker_info", "WorkerInfo",
]

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_MAX_RPC_TIMEOUT_S = 500000

_state = {
    "store": None,
    "server": None,
    "pool": None,
    "self": None,          # WorkerInfo
    "workers": {},         # name -> WorkerInfo
    "barrier_round": 0,
}


def _recv_exact(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc: peer closed connection")
        buf += chunk
    return buf


def _send_msg(conn, payload: bytes):
    conn.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(conn) -> bytes:
    (n,) = struct.unpack("<Q", _recv_exact(conn, 8))
    return _recv_exact(conn, n)


class _RpcServer:
    """Thread-pooled request/response server: one pickled
    (fn, args, kwargs) in, one pickled ("ok"|"err", payload) out.

    `host` should be the rendezvous-routed interface (init_rpc passes it);
    a wildcard bind would expose the unauthenticated pickle endpoint on
    every interface of the host."""

    def __init__(self, host="127.0.0.1", n_threads=8):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.bind((host, 0))
        except OSError:
            # interface detection can misfire (containers with asymmetric
            # routing): fall back to loopback rather than 0.0.0.0 — a
            # reachable-but-narrow bind beats an open one; cross-host
            # setups pin PADDLE_WORKER_IP explicitly
            self._sock.bind(("127.0.0.1", 0))
            host = "127.0.0.1"
        self.host = host
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._pool = ThreadPoolExecutor(max_workers=n_threads,
                                        thread_name_prefix="rpc-worker")
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True, name="rpc-accept")
        self._accept_thread.start()

    def _accept_loop(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self._pool.submit(self._serve_one, conn)

    def _serve_one(self, conn):
        try:
            with conn:
                # a peer that connects but never sends (crash, port scan)
                # must not pin this worker thread forever and hang stop()
                conn.settimeout(120)
                fn, args, kwargs = pickle.loads(_recv_msg(conn))
                # the RESPONSE send gets a far looser bound: settimeout is
                # total-duration, and a large result over a slow link is
                # legitimate — 120s there would abort it
                conn.settimeout(900)
                try:
                    out = ("ok", fn(*args, **kwargs))
                except BaseException as e:  # noqa: BLE001 — ship to caller
                    out = ("err", e)
                try:
                    payload = pickle.dumps(out)
                except Exception:
                    # unpicklable result/exception: the caller must still
                    # see WHAT happened, not an opaque connection error
                    payload = pickle.dumps(
                        ("err", RuntimeError(
                            "rpc: remote result/exception not picklable:\n"
                            + traceback.format_exc())))
                _send_msg(conn, payload)
        except Exception:
            pass  # connection-level failure: caller sees its own error

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._pool.shutdown(wait=True)


def _self_ip(master_addr):
    """The address peers can reach this worker at: the local interface that
    routes to the master (PADDLE_WORKER_IP overrides). A 127.0.0.1 default
    would register loopback and break cross-host calls."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect((master_addr, 1))  # UDP: no packets sent
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Reference: rpc.py:85 — TCPStore rendezvous + worker-info exchange +
    start-up barrier."""
    from ..store import TCPStore

    if _state["server"] is not None:
        raise RuntimeError("init_rpc already called; call shutdown() first")
    rank = int(os.environ["PADDLE_TRAINER_ID"]) if rank is None else rank
    world_size = (int(os.environ["PADDLE_TRAINERS_NUM"])
                  if world_size is None else world_size)
    master_endpoint = (master_endpoint if master_endpoint is not None
                       else os.environ["PADDLE_MASTER_ENDPOINT"])
    master_addr, master_port = master_endpoint.rsplit(":", 1)

    # bind the request server to the interface peers will actually dial
    # (the one that routes to the master) instead of 0.0.0.0 — the
    # unauthenticated-pickle endpoint must not listen on every interface
    # of a multi-homed host (same address that gets registered below)
    ip = os.environ.get("PADDLE_WORKER_IP") or _self_ip(master_addr)
    server = _RpcServer(host=ip)
    store = None
    try:
        store = TCPStore(master_addr, int(master_port),
                         is_master=(rank == 0), world_size=world_size)
        # register the address the server actually BOUND (the loopback
        # fallback may have overridden `ip`) — peers dial what we advertise
        me = WorkerInfo(name, rank, server.host, server.port)
        store.set(f"rpc/worker/{rank}", pickle.dumps(me))

        workers = {}
        for r in range(world_size):
            key = f"rpc/worker/{r}"
            store.wait([key])
            info = pickle.loads(store.get(key))
            if info.name in workers:
                raise RuntimeError(
                    f"duplicate rpc worker name {info.name!r}")
            workers[info.name] = info
    except BaseException:
        # a failed rendezvous must not leak the started server (accept
        # thread, pool, bound port) or the store connection — the caller
        # may retry init_rpc
        server.stop()
        if store is not None:
            try:
                store.close()
            except Exception:
                pass
        raise

    _state.update(store=store, server=server, self=me, workers=workers,
                  pool=ThreadPoolExecutor(max_workers=8,
                                          thread_name_prefix="rpc-client"))
    _barrier("rpc/init")


def _barrier(prefix):
    st = _state["store"]
    n = len(_state["workers"])
    rnd = _state["barrier_round"]
    _state["barrier_round"] += 1
    st.barrier(f"{prefix}/{rnd}", n, _state["self"].rank)


def _require_init():
    if _state["server"] is None:
        raise RuntimeError("rpc is not initialized; call init_rpc first")


def _call(to, fn, args, kwargs, timeout):
    _require_init()
    info = _state["workers"].get(to)
    if info is None:
        raise ValueError(f"unknown rpc worker {to!r}")
    payload = pickle.dumps((fn, tuple(args or ()), dict(kwargs or {})))
    t = _MAX_RPC_TIMEOUT_S if timeout is None or timeout <= 0 else timeout
    with socket.create_connection((info.ip, info.port), timeout=t) as conn:
        conn.settimeout(t)
        _send_msg(conn, payload)
        status, out = pickle.loads(_recv_msg(conn))
    if status == "err":
        raise out
    return out


def rpc_sync(to, fn, args=None, kwargs=None, timeout=-1):
    """Blocking call of `fn(*args, **kwargs)` on worker `to`
    (reference: rpc.py:160)."""
    return _call(to, fn, args, kwargs, timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=-1) -> Future:
    """Non-blocking variant returning a future with .wait()/.result()
    (reference: rpc.py:206)."""
    _require_init()
    fut = _state["pool"].submit(_call, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # paddle futures expose wait()
    return fut


def shutdown():
    """Barrier then stop (reference: rpc.py:305) — the barrier guarantees
    no worker tears down while peers still have calls in flight."""
    if _state["server"] is None:
        return
    # drain OUR outbound calls BEFORE the barrier: a queued rpc_async must
    # not find the peer's server already stopped after everyone passes it
    _state["pool"].shutdown(wait=True)
    _barrier("rpc/shutdown")
    # ack round: the store host (rank 0) must not close the store while a
    # slower rank's barrier WAIT request is still in flight — it waits for
    # every rank's explicit ack, which each rank posts only after its own
    # barrier wait returned
    st, me = _state["store"], _state["self"]
    n = len(_state["workers"])
    st.set(f"rpc/shutdown_ack/{me.rank}", b"1")
    if me.rank == 0:
        st.wait([f"rpc/shutdown_ack/{r}" for r in range(n)])
    _state["server"].stop()
    try:
        _state["store"].close()
    except Exception:
        pass
    _state.update(store=None, server=None, pool=None, self=None, workers={},
                  barrier_round=0)


def get_worker_info(name) -> WorkerInfo:
    _require_init()
    return _state["workers"][name]


def get_all_worker_infos():
    _require_init()
    return sorted(_state["workers"].values(), key=lambda w: w.rank)


def get_current_worker_info() -> WorkerInfo:
    _require_init()
    return _state["self"]
