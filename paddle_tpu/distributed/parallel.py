"""DataParallel + sharding API.

Reference: paddle.DataParallel (python/paddle/distributed/parallel.py:219)
backed by EagerReducer grad bucketing (paddle/fluid/distributed/collective/
reducer.cc); group_sharded_parallel (python/paddle/distributed/sharding/
group_sharded.py:50) choosing GroupSharded stage 2/3.

TPU: DP gradient averaging is what jnp.mean over a dp-sharded global batch
compiles to (an ICI all-reduce at the loss reduction) — the reducer's bucket
assembly/overlap machinery has no residual role. The wrappers keep API parity
and annotate stage metadata consumed by DistributedTrainStep.
"""

from __future__ import annotations

import paddle_tpu.nn as nn

from .train_step import shard_params_for_stage3

__all__ = ["DataParallel", "group_sharded_parallel", "save_group_sharded_model"]


class DataParallel(nn.Layer):
    _warned = False

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        if not DataParallel._warned:
            DataParallel._warned = True
            import warnings

            warnings.warn(
                "paddle_tpu DataParallel is a pass-through wrapper: grad "
                "averaging happens inside the compiled step (GSPMD inserts "
                "the all-reduce); comm_buffer_size / find_unused_parameters "
                "are accepted for API parity and ignored. In a "
                "multi-controller run, plain loss.backward(); opt.step() "
                "does NOT sync grads — drive training through "
                "DistributedTrainStep or fleet.distributed_optimizer.",
                stacklevel=2)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # grad averaging is inside the compiled step; identity for parity
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """reference: group_sharded.py:50 — level in {'os', 'os_g', 'p_g_os'}.

    os    -> ZeRO-1: optimizer states sharded     (stage 1)
    os_g  -> ZeRO-2: + gradient sharding          (stage 2)
    p_g_os-> ZeRO-3: + parameter sharding (FSDP)  (stage 3)

    Annotates the model/optimizer; DistributedTrainStep reads
    `optimizer._sharding_stage` and places state accordingly.
    """
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}.get(level)
    if stage is None:
        raise ValueError(f"level must be os|os_g|p_g_os, got {level!r}")
    if stage == 3:
        shard_params_for_stage3(model)
    optimizer._sharding_stage = stage
    optimizer._sharding_offload = bool(offload)
    model._sharding_stage = stage
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    from ..framework.io import save as fsave

    fsave(model.state_dict(), output + ".pdmodel")
    if optimizer is not None:
        fsave(optimizer.state_dict(), output + ".pdopt")
