"""Distributed environment + global mesh state.

Reference: python/paddle/distributed/parallel.py (init_parallel_env :978,
ParallelEnv) and the per-axis comm groups of HybridCommunicateGroup.

TPU-native model: one controller process per host; "world size" is the number
of devices (chips), not processes. Collectives are compiled XLA ops over a
global `jax.sharding.Mesh` whose named axes are the hybrid-parallel dims
[dp, pp, sharding, sep, mp] — the direct analog of the reference's
CommunicateTopology order (fleet/base/topology.py:73-80).
"""

from __future__ import annotations

import os
import threading

import numpy as np

_state = threading.local()

AXIS_ORDER = ("dp", "pp", "sharding", "sep", "ep", "mp")


def _jax():
    import jax

    return jax


def is_initialized() -> bool:
    return getattr(_state, "initialized", False)


def init_parallel_env(strategy=None):
    """reference: paddle.distributed.init_parallel_env (parallel.py:978).

    Multi-host: if PADDLE_MASTER/PADDLE_TRAINER_ENDPOINTS-style envs (or
    JAX_COORDINATOR_ADDRESS) are present, bootstrap jax.distributed — the
    TCPStore-equivalent rendezvous (reference: phi TCPStore tcp_store.h:121).
    """
    if is_initialized():
        return ParallelEnv()
    coord = (
        os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("PADDLE_MASTER")
        or os.environ.get("MASTER_ADDR")
    )
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", "1")))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", "0")))
    if coord and nproc > 1:
        port = os.environ.get("MASTER_PORT")
        addr = coord if ":" in coord else f"{coord}:{port or 8476}"
        _jax().distributed.initialize(
            coordinator_address=addr, num_processes=nproc, process_id=pid
        )
    _state.initialized = True
    return ParallelEnv()


def get_rank(group=None):
    """Device-rank of this controller's first addressable device within the
    group (process-level rank on multi-host)."""
    if group is not None:
        return group.rank
    return _jax().process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    try:
        return _jax().device_count()
    except Exception:
        return 1


def get_process_count():
    return _jax().process_count()


class ParallelEnv:
    """reference: paddle.distributed.ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def local_rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def dev_id(self):
        return 0


# --------------------------------------------------------------------------- #
# global mesh
# --------------------------------------------------------------------------- #

_mesh_lock = threading.Lock()
_global_mesh = None


def set_global_mesh(mesh):
    global _global_mesh
    with _mesh_lock:
        _global_mesh = mesh


def get_global_mesh():
    return _global_mesh


def build_mesh(dp=1, pp=1, sharding=1, sep=1, mp=1, ep=1, devices=None):
    """Build the hybrid mesh with named axes in reference topology order.

    Axis placement on hardware: trailing axes change fastest over the device
    list, so mp (highest-bandwidth collectives) lands on neighbouring chips —
    the same locality rule the reference uses when carving NCCL rings from
    the rank grid. `ep` (expert parallelism — the MoE dispatch/combine
    all-to-all axis, ISSUE-14) sits just outside mp for the same reason:
    a2a volume per token beats everything but mp's per-layer all-reduces.
    """
    jax = _jax()
    if devices is None:
        devices = np.array(jax.devices())
    else:
        devices = np.array(devices)
    total = dp * pp * sharding * sep * ep * mp
    if total > devices.size:
        raise ValueError(
            f"mesh {dp}x{pp}x{sharding}x{sep}x{ep}x{mp}={total} exceeds "
            f"{devices.size} devices"
        )
    devices = devices[:total].reshape(dp, pp, sharding, sep, ep, mp)
    from jax.sharding import Mesh

    mesh = Mesh(devices, AXIS_ORDER)
    set_global_mesh(mesh)
    return mesh


def mesh_shape(mesh=None) -> dict:
    """axis -> size of `mesh` (default: the global mesh) over AXIS_ORDER,
    with absent axes reported as 1 — the shape the planner's MeshPlan
    artifact stores, so a live mesh and a stored plan compare directly."""
    m = mesh if mesh is not None else get_global_mesh()
    if m is None:
        return {a: 1 for a in AXIS_ORDER}
    return {a: int(m.shape.get(a, 1)) for a in AXIS_ORDER}


def default_mesh():
    """Global mesh, defaulting to pure-dp over all devices."""
    m = get_global_mesh()
    if m is None:
        m = build_mesh(dp=len(_jax().devices()))
    return m


def constrain_array(a, spec):
    """with_sharding_constraint on a raw array against the global mesh,
    stripping axes that are Manual in the current shard_map context (a
    concrete all-Auto mesh sharding poisons downstream op types there).
    Shared by the mpu layers and MoE; returns `a` unchanged when no mesh."""
    import warnings

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = get_global_mesh()
    if mesh is None:
        return a

    def strip(entry, manual):
        if entry is None:
            return None
        if isinstance(entry, tuple):
            kept = tuple(e for e in entry if e not in manual)
            return kept if kept else None
        return None if entry in manual else entry

    try:
        # older jaxlibs (0.4.x) have no get_abstract_mesh — probing it with
        # a bare attribute access used to throw into the broad except below
        # and silently skip EVERY constraint (MoE ep layouts, TP hints) as
        # a no-op warning. Probe with getattr and fall through to the plain
        # global-mesh constraint instead.
        get_ctx = getattr(jax.sharding, "get_abstract_mesh", None)
        ctx = get_ctx() if get_ctx is not None else None
        if (ctx is not None and not ctx.empty
                and getattr(ctx, "manual_axes", None)):
            manual = set(ctx.manual_axes)
            spec = P(*[strip(s, manual) for s in spec])
            return jax.lax.with_sharding_constraint(a, NamedSharding(ctx, spec))
        if ctx is None:
            # 0.4.x manual-context detection: shard_map binds its mesh axes
            # in the axis env; naming a bound axis in a constraint spec
            # fails at lowering ("also found in manual_axes"), so strip
            # every bound axis (conservative — auto axes are bound too on
            # 0.4.x, losing only a hint, never correctness)
            from jax._src import core as _jcore  # pragma: no cover - version path

            bound = getattr(_jcore.get_axis_env(), "axis_sizes", None)
            if bound:
                spec = P(*[strip(s, set(bound)) for s in spec])
        return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))
    except Exception as e:  # pragma: no cover - diagnostic path
        warnings.warn(f"sharding constraint {spec} skipped: {e}")
        return a
