from .tuner import AutoTuner, Recorder, default_candidates, tune

__all__ = ["AutoTuner", "Recorder", "default_candidates", "tune"]
