"""Parallel-config auto-tuner (reference:
python/paddle/distributed/auto_tuner/tuner.py AutoTuner :21 + search.py
GridSearch, prune.py prune_by_mp/pp/sharding/mbs (+ *_history variants),
recorder.py Recorder, memory_cost_model.py).

TPU formulation: candidates are hybrid-mesh shapes (dp, mp, pp, sharding,
micro-batch, recompute) over a device count. Static pruning enforces the
mesh/model divisibility laws and an analytic HBM estimate; history pruning
skips configs strictly more memory-hungry than a known OOM. The cost model
is DIRECT MEASUREMENT: each surviving config builds a DistributedTrainStep
on a submesh and times real steps (the reference launches subprocess trials
for the same reason — compile-time cost models lie), which on the CPU test
mesh doubles as a correctness sweep of every parallel mode."""

from __future__ import annotations

import csv
import itertools
import os
import time

__all__ = ["AutoTuner", "Recorder", "default_candidates", "tune"]


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def default_candidates(tuner_cfg):
    """Grid of mesh shapes for `num_devices` (reference utils.py
    default_candidates): every (dp, mp, pp, sharding[, ep]) factorization
    plus micro-batch and recompute choices. The expert-parallel axis only
    enters the grid when the model declares experts
    (model_cfg["moe_num_experts"], or an explicit tuner_cfg["ep_degree"]
    candidate list) — dense models keep the exact pre-ep grid."""
    ndev = tuner_cfg["num_devices"]
    gbs = tuner_cfg.get("global_batch_size", 8)
    has_moe = tuner_cfg.get("model_cfg", {}).get("moe_num_experts", 0) > 1
    eps = tuner_cfg.get("ep_degree", _divisors(ndev) if has_moe else [1])
    cands = []
    for mp in tuner_cfg.get("mp_degree", _divisors(ndev)):
        for pp in tuner_cfg.get("pp_degree", _divisors(ndev)):
            for sharding in tuner_cfg.get("sharding_degree", _divisors(ndev)):
                for ep in eps:
                    if ndev % (mp * pp * sharding * ep):
                        continue
                    dp = ndev // (mp * pp * sharding * ep)
                    if dp not in tuner_cfg.get("dp_degree", _divisors(ndev)):
                        continue
                    for mbs in tuner_cfg.get("micro_batch_size", [1, 2, 4]):
                        for rc in tuner_cfg.get("use_recompute", [True]):
                            cands.append({
                                "dp_degree": dp, "mp_degree": mp,
                                "pp_degree": pp, "sharding_degree": sharding,
                                "ep_degree": ep,
                                "sharding_stage": tuner_cfg.get("sharding_stage", 1),
                                "micro_batch_size": mbs,
                                "use_recompute": rc,
                                "global_batch_size": gbs,
                            })
    return cands


# --------------------------------------------------------------------------- #
# pruning (reference prune.py)
# --------------------------------------------------------------------------- #


def prune_by_mp(tuner_cfg, cfg, history=None):
    """mp must divide hidden/heads/vocab (reference prune.py:129)."""
    mp = cfg["mp_degree"]
    model = tuner_cfg.get("model_cfg", {})
    for key in ("hidden_size", "num_heads", "vocab_size"):
        v = model.get(key)
        if v is not None and v % mp:
            return f"mp {mp} does not divide {key} {v}"
    return None


def prune_by_pp(tuner_cfg, cfg, history=None):
    """pp must divide the layer count and the microbatch count
    (reference prune.py:173)."""
    pp = cfg["pp_degree"]
    layers = tuner_cfg.get("model_cfg", {}).get("num_layers")
    if layers is not None and layers % pp:
        return f"pp {pp} does not divide num_layers {layers}"
    n_micro = cfg["global_batch_size"] // (
        cfg["dp_degree"] * cfg["sharding_degree"] * cfg["micro_batch_size"])
    if pp > 1 and n_micro < pp:
        return f"{n_micro} microbatches < pp {pp}"
    return None


def prune_by_ep(tuner_cfg, cfg, history=None):
    """ep must divide the expert count (expert-stacked weights shard on
    `ep`, planner/layout.py expert_stacked), and a dense model has no ep
    axis to use at all."""
    ep = cfg.get("ep_degree", 1)
    if ep <= 1:
        return None
    experts = tuner_cfg.get("model_cfg", {}).get("moe_num_experts", 0)
    if experts <= 1:
        return f"ep {ep} on a dense model (no moe_num_experts)"
    if experts % ep:
        return f"ep {ep} does not divide moe_num_experts {experts}"
    return None


def prune_by_mbs(tuner_cfg, cfg, history=None):
    """global batch must shard exactly (reference prune.py:307)."""
    denom = cfg["dp_degree"] * cfg["sharding_degree"] * cfg["micro_batch_size"]
    if cfg["global_batch_size"] % denom:
        return (f"global batch {cfg['global_batch_size']} not divisible by "
                f"dp*sharding*mbs {denom}")
    return None


def params_per_device(model_cfg, cfg):
    """(body_elems, emb_elems) on the worst-case device under the actual
    DistributedTrainStep placement — THE one encoding of the split rules,
    shared by the memory estimate below and the planner's HBM/comm terms
    (planner/cost_model.py) so they can never diverge:

    - the transformer body (12*L*h^2 params) is split by mp (TP column/row
      specs) and pp (layer partition); sharding stage 3 (FSDP) splits it
      by `sharding` as well;
    - the vocab embedding (vocab*h) is vocab-sharded by mp ONLY
      (VocabParallelEmbedding P("mp", None)); it lives on one pipeline
      stage, so pp does NOT divide it — worst case is the stage that owns
      it. Stage 3 adds the `sharding` split on its free h dim (fsdp_spec
      respects the TP-taken vocab dim).
    """
    h = model_cfg.get("hidden_size", 0)
    L = model_cfg.get("num_layers", 0)
    vocab = model_cfg.get("vocab_size", 0)
    mp, pp = cfg["mp_degree"], cfg["pp_degree"]
    sh = max(cfg["sharding_degree"], 1)
    stage = cfg.get("sharding_stage", 1) if sh > 1 else 0
    body_dev = 12 * L * h * h / (mp * pp)
    emb_dev = vocab * h / mp
    if stage >= 3:
        body_dev /= sh
        emb_dev /= sh
    return body_dev, emb_dev


def estimate_memory_bytes(tuner_cfg, cfg):
    """Per-device parameter+optimizer+activation estimate (reference
    memory_cost_model.py) over the `params_per_device` placement: bf16
    params are 2 B/elem; optimizer states (f32 master + two f32 moments)
    are 12 B/elem and are `sharding`-split at every stage >= 1 (ZeRO-1),
    while the params themselves stay unsplit below stage 3 (stage 3's
    split already happened in params_per_device)."""
    model = tuner_cfg.get("model_cfg", {})
    h = model.get("hidden_size", 0)
    L = model.get("num_layers", 0)
    seq = model.get("seq_length", 1024)
    if not h:
        return 0
    mp, pp = cfg["mp_degree"], cfg["pp_degree"]
    sh = max(cfg["sharding_degree"], 1)
    stage = cfg.get("sharding_stage", 1) if sh > 1 else 0
    body_dev, emb_dev = params_per_device(model, cfg)
    param_bytes = 2 * (body_dev + emb_dev)
    if stage >= 3:
        state_bytes = 12 * (body_dev + emb_dev)
    else:
        state_bytes = 12 * (body_dev + emb_dev) / sh
    act_layers = 1 if cfg.get("use_recompute") else L // pp
    act_bytes = (cfg["micro_batch_size"] * seq * h * 16 * act_layers / mp)
    return param_bytes + state_bytes + act_bytes


def prune_by_memory(tuner_cfg, cfg, history=None):
    cap = tuner_cfg.get("max_mem_usage_bytes")
    if cap:
        est = estimate_memory_bytes(tuner_cfg, cfg)
        if est > cap:
            return f"estimated {est / 1e9:.2f} GB > cap {cap / 1e9:.2f} GB"
    return None


def prune_by_history(tuner_cfg, cfg, history):
    """Skip configs at least as memory-hungry as a known OOM
    (reference prune_by_*_history)."""
    est = estimate_memory_bytes(tuner_cfg, cfg)
    for h in history or []:
        if h.get("error") == "oom" and est >= (h.get("mem_estimate") or 0):
            return f"memory {est / 1e9:.2f} GB >= known OOM config"
    return None


_PRUNES = [prune_by_mp, prune_by_pp, prune_by_ep, prune_by_mbs,
           prune_by_memory, prune_by_history]


# --------------------------------------------------------------------------- #
# recorder (reference recorder.py)
# --------------------------------------------------------------------------- #


class Recorder:
    def __init__(self, metric_name="step_time", direction="min"):
        self.metric_name = metric_name
        self.direction = direction
        self.history: list[dict] = []

    def add_cfg(self, **kw):
        self.history.append(dict(kw))

    def get_best(self):
        valid = [h for h in self.history
                 if h.get(self.metric_name) is not None and not h.get("error")]
        if not valid:
            return None, True
        key = lambda h: h[self.metric_name]
        best = (min if self.direction == "min" else max)(valid, key=key)
        return best, False

    def store_history(self, path="./history.csv"):
        if not self.history:
            return
        keys = sorted({k for h in self.history for k in h})
        with open(path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys)
            w.writeheader()
            for h in self.history:
                w.writerow(h)

    @staticmethod
    def _coerce(row):
        """csv.DictReader returns all-string rows; restore the types the
        history was recorded with, or every numeric comparison downstream
        (prune_by_history's `est >= mem_estimate`) raises TypeError.
        "" round-trips to None (store_history writes None as empty),
        True/False back to bool, numerics to int-then-float, everything
        else stays a string (error reasons, pruned reasons)."""
        out = {}
        for k, v in row.items():
            if v is None or v == "":
                out[k] = None
            elif v == "True":
                out[k] = True
            elif v == "False":
                out[k] = False
            else:
                try:
                    out[k] = int(v)
                except ValueError:
                    try:
                        out[k] = float(v)
                    except ValueError:
                        out[k] = v
        return out

    def load_history(self, path="./history.csv"):
        if not os.path.exists(path):
            return [], True
        with open(path) as f:
            return [self._coerce(r) for r in csv.DictReader(f)], False


# --------------------------------------------------------------------------- #
# tuner
# --------------------------------------------------------------------------- #


class AutoTuner:
    """reference tuner.py:21 — search_once/add_cfg over a pruned grid."""

    def __init__(self, tuner_cfg):
        self.tuner_cfg = dict(tuner_cfg)
        self.candidates = (tuner_cfg.get("candidates")
                           or default_candidates(self.tuner_cfg))
        self.task_limit = tuner_cfg.get("task_limit", 100)
        self.cur_task_id = 0
        self.history_cfgs: list[dict] = []
        # (cfg, prune-rule name, reason) — the rule is recorded at the
        # point it fires so reports never have to re-derive it
        self.pruned: list[tuple[dict, str, str]] = []
        self._iter = iter(self.candidates)

    def search_once(self):
        """Next unpruned config, or None when exhausted (reference :62)."""
        while self.cur_task_id < self.task_limit:
            try:
                cfg = next(self._iter)
            except StopIteration:
                return None
            reason = None
            for prune in _PRUNES:
                reason = prune(self.tuner_cfg, cfg, self.history_cfgs)
                if reason:
                    self.pruned.append((cfg, prune.__name__, reason))
                    break
            if reason:
                continue
            self.cur_task_id += 1
            return cfg
        return None

    def add_cfg(self, cfg):
        self.history_cfgs.append(cfg)


def tune(model_builder, loss_fn, optimizer_builder, tuner_cfg, devices=None,
         steps=2, recorder=None):
    """Run the measurement loop: for each surviving config build the hybrid
    mesh + DistributedTrainStep, time `steps` real steps, and return
    (best_cfg, recorder). `model_builder()` -> fresh model;
    `optimizer_builder(model)` -> optimizer. The reference launches each
    trial as a subprocess with a timeout; under the single controller a
    trial is a compile+measure in-process, with OOM/compile errors recorded
    and fed back into history pruning."""
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from .. import env as _env
    from ..train_step import DistributedTrainStep

    devices = devices if devices is not None else jax.devices()
    recorder = recorder or Recorder()
    tuner = AutoTuner(tuner_cfg)
    gbs = tuner_cfg.get("global_batch_size", 8)
    model_cfg = tuner_cfg.get("model_cfg", {})
    seq = model_cfg.get("seq_length", 128)
    vocab = model_cfg.get("vocab_size", 1024)

    # the sweep must not clobber the caller's mesh: every trial sets the
    # global mesh (build_mesh AND DistributedTrainStep both do), so snapshot
    # it here and restore it when the sweep ends, however it ends. The
    # per-trial `finally` below is unconditional-safe: it runs whether the
    # failure came from build_mesh, model_builder, or the timed loop — a
    # model_builder raise must not leave the PREVIOUS trial's mesh visible.
    prev_mesh = _env.get_global_mesh()
    try:
        while True:
            cfg = tuner.search_once()
            if cfg is None:
                break
            entry = dict(cfg)
            entry["mem_estimate"] = estimate_memory_bytes(tuner_cfg, cfg)
            try:
                paddle.seed(0)
                mesh = _env.build_mesh(
                    dp=cfg["dp_degree"], pp=cfg["pp_degree"],
                    sharding=cfg["sharding_degree"], mp=cfg["mp_degree"],
                    ep=cfg.get("ep_degree", 1), devices=devices)
                model = model_builder(cfg)
                optimizer = optimizer_builder(model)
                step = DistributedTrainStep(
                    model, loss_fn, optimizer, mesh=mesh,
                    sharding_stage=cfg.get("sharding_stage", 1)
                    if cfg["sharding_degree"] > 1 else 0)
                rng = np.random.default_rng(0)
                ids = paddle.to_tensor(rng.integers(0, vocab, (gbs, seq)))
                labels = paddle.to_tensor(rng.integers(0, vocab, (gbs, seq)))
                _ = float(step(ids, labels))  # compile + warmup
                t0 = time.perf_counter()
                for _i in range(steps):
                    loss = step(ids, labels)
                entry["loss"] = float(loss)
                entry["step_time"] = (time.perf_counter() - t0) / steps
            except Exception as e:  # OOM / infeasible compile
                msg = str(e).lower()
                entry["error"] = ("oom" if "resource exhausted" in msg
                                  or "out of memory" in msg else
                                  f"{type(e).__name__}")
            finally:
                _env.set_global_mesh(None)
            tuner.add_cfg(entry)
            recorder.add_cfg(**entry)
    finally:
        _env.set_global_mesh(prev_mesh)

    # pruned configs land in the history too, so shortlist reports can show
    # WHY a config was never measured (tools/plan_report.py prints these)
    for cfg, _rule, reason in tuner.pruned:
        recorder.add_cfg(**dict(cfg), pruned=reason)

    best, _err = recorder.get_best()
    return best, recorder
