"""Math ops: elementwise unary/binary, reductions, cumulative ops
(reference: python/paddle/tensor/math.py, ops.yaml entries lower straight to
jax.numpy — XLA replaces the phi per-dtype kernel registry)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.core import Tensor, register_tensor_method, run_op, to_tensor

__all__ = []  # filled programmatically below


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _export(name, fn):
    __all__.append(name)
    globals()[name] = fn
    return fn


# --------------------------------------------------------------------------- #
# unary elementwise
# --------------------------------------------------------------------------- #

def _make_unary(name, jfn):
    def op(x, name=None):
        return run_op(op.__name__, jfn, [_t(x)])

    op.__name__ = name
    op.__qualname__ = name
    return op


_UNARY = {
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda a: jax.lax.rsqrt(a),
    "abs": jnp.abs,
    "sign": jnp.sign,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "asin": jnp.arcsin,
    "acos": jnp.arccos,
    "atan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "asinh": jnp.arcsinh,
    "acosh": jnp.arccosh,
    "atanh": jnp.arctanh,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "round": jnp.round,
    "trunc": jnp.trunc,
    "frac": lambda a: a - jnp.trunc(a),
    "reciprocal": lambda a: 1.0 / a,
    "square": jnp.square,
    "neg": jnp.negative,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "sigmoid": jax.nn.sigmoid,
    "logit": jax.scipy.special.logit,
    "lgamma": jax.scipy.special.gammaln,
    "digamma": jax.scipy.special.digamma,
    "angle": jnp.angle,
    "conj": jnp.conj,
    "real": jnp.real,
    "imag": jnp.imag,
    "deg2rad": jnp.deg2rad,
    "rad2deg": jnp.rad2deg,
    "i0": lambda a: jax.scipy.special.i0(a),
    "i1": lambda a: jax.scipy.special.i1(a),
}

for _name, _jfn in _UNARY.items():
    _export(_name, _make_unary(_name, _jfn))

# paddle aliases
_export("arcsin", globals()["asin"])
_export("arccos", globals()["acos"])
_export("arctan", globals()["atan"])


# --------------------------------------------------------------------------- #
# binary elementwise
# --------------------------------------------------------------------------- #

def _make_binary(name, jfn):
    def op(x, y, name=None):
        return run_op(op.__name__, jfn, [_t(x), _t(y)])

    op.__name__ = name
    op.__qualname__ = name
    return op


_BINARY = {
    "add": jnp.add,
    "subtract": jnp.subtract,
    "multiply": jnp.multiply,
    "divide": jnp.divide,
    "floor_divide": jnp.floor_divide,
    "mod": jnp.mod,
    "remainder": jnp.mod,
    "floor_mod": jnp.mod,
    "pow": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "fmax": jnp.fmax,
    "fmin": jnp.fmin,
    "atan2": jnp.arctan2,
    "hypot": jnp.hypot,
    "logaddexp": jnp.logaddexp,
    "heaviside": jnp.heaviside,
    "copysign": jnp.copysign,
    "nextafter": jnp.nextafter,
    "ldexp": lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)),
    "gcd": jnp.gcd,
    "lcm": jnp.lcm,
    "inner": jnp.inner,
    "outer": jnp.outer,
    "kron": jnp.kron,
}

for _name, _jfn in _BINARY.items():
    _export(_name, _make_binary(_name, _jfn))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = float(scale), float(bias)

    def fn(a):
        out = a * jnp.asarray(s, a.dtype) + jnp.asarray(b, a.dtype) if bias_after_scale \
            else (a + jnp.asarray(b, a.dtype)) * jnp.asarray(s, a.dtype)
        return out

    return run_op("scale", fn, [_t(x)])


_export("scale", scale)


def multiplex(inputs, index, name=None):
    ts = [_t(i) for i in inputs]
    idx = _t(index)

    def fn(ind, *vals):
        stacked = jnp.stack(vals, axis=0)
        ind = ind.reshape(-1).astype(jnp.int32)
        return stacked[ind, jnp.arange(stacked.shape[1])]

    return run_op("multiplex", fn, [idx] + ts)


_export("multiplex", multiplex)


# --------------------------------------------------------------------------- #
# reductions
# --------------------------------------------------------------------------- #

def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _make_reduce(name, jfn, int_default=False):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        ax = _norm_axis(axis)
        d = None if dtype is None else jnp.dtype(dtype_mod.convert_dtype(dtype))

        def fn(a):
            kwargs = dict(axis=ax, keepdims=keepdim)
            out = jfn(a, **kwargs)
            if d is not None:
                out = out.astype(d)
            return out

        return run_op(op.__name__, fn, [_t(x)])

    op.__name__ = name
    op.__qualname__ = name
    return op


_export("sum", _make_reduce("sum", jnp.sum))
_export("prod", _make_reduce("prod", jnp.prod))
_export("max", _make_reduce("max", jnp.max))
_export("min", _make_reduce("min", jnp.min))
_export("amax", _make_reduce("amax", jnp.max))
_export("amin", _make_reduce("amin", jnp.min))
_export("mean", _make_reduce("mean", jnp.mean))
_export("nanmean", _make_reduce("nanmean", jnp.nanmean))
_export("nansum", _make_reduce("nansum", jnp.nansum))
_export("logsumexp", _make_reduce("logsumexp", jax.scipy.special.logsumexp))


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    ax = _norm_axis(axis)
    return run_op("all", lambda a: jnp.all(a, axis=ax, keepdims=keepdim), [_t(x)])


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    ax = _norm_axis(axis)
    return run_op("any", lambda a: jnp.any(a, axis=ax, keepdims=keepdim), [_t(x)])


_export("all", all)
_export("any", any)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return run_op(
        "count_nonzero",
        lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim).astype(jnp.int32),
        [_t(x)],
    )


_export("count_nonzero", count_nonzero)


# --------------------------------------------------------------------------- #
# cumulative
# --------------------------------------------------------------------------- #

def cumsum(x, axis=None, dtype=None, name=None):
    d = None if dtype is None else jnp.dtype(dtype_mod.convert_dtype(dtype))

    def fn(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.cumsum(a, dtype=d)
        return jnp.cumsum(a, axis=int(axis), dtype=d)

    return run_op("cumsum", fn, [_t(x)])


def cumprod(x, dim=None, dtype=None, name=None):
    d = None if dtype is None else jnp.dtype(dtype_mod.convert_dtype(dtype))

    def fn(a):
        if dim is None:
            return jnp.cumprod(a.reshape(-1), dtype=d)
        return jnp.cumprod(a, axis=int(dim), dtype=d)

    return run_op("cumprod", fn, [_t(x)])


def _cum_extreme(x, axis, pick_new, op_name, idx_dtype):
    """Running max/min with indices via an associative scan over (value, index)
    pairs; ties keep the earliest index, matching the reference kernels."""
    xx = _t(x)
    if axis is None:
        xx = run_op("flatten", lambda a: a.reshape(-1), [xx])
        ax = 0
    else:
        ax = int(axis)
    d = jnp.dtype(dtype_mod.convert_dtype(idx_dtype or "int64"))

    def fn(a):
        axn = ax % a.ndim
        iota = jax.lax.broadcasted_iota(jnp.int32, a.shape, axn)

        def comb(c1, c2):
            v1, i1 = c1
            v2, i2 = c2
            take_new = pick_new(v1, v2)
            return jnp.where(take_new, v2, v1), jnp.where(take_new, i2, i1)

        vals, idx = jax.lax.associative_scan(comb, (a, iota), axis=axn)
        return vals, idx.astype(d)

    return run_op(op_name, fn, [xx])


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, lambda v1, v2: v2 > v1, "cummax", dtype)


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, lambda v1, v2: v2 < v1, "cummin", dtype)


_export("cumsum", cumsum)
_export("cumprod", cumprod)
_export("cummax", cummax)
_export("cummin", cummin)


def clip(x, min=None, max=None, name=None):  # noqa: A002
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return run_op("clip", lambda a: jnp.clip(a, lo, hi), [_t(x)])


_export("clip", clip)


def isnan(x, name=None):
    return run_op("isnan", jnp.isnan, [_t(x)])


def isinf(x, name=None):
    return run_op("isinf", jnp.isinf, [_t(x)])


def isfinite(x, name=None):
    return run_op("isfinite", jnp.isfinite, [_t(x)])


_export("isnan", isnan)
_export("isinf", isinf)
_export("isfinite", isfinite)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return run_op(
        "nan_to_num",
        lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
        [_t(x)],
    )


_export("nan_to_num", nan_to_num)


def increment(x, value=1.0, name=None):
    out = run_op("increment", lambda a: a + jnp.asarray(value, a.dtype), [_t(x)])
    if isinstance(x, Tensor):
        x._inplace_update(out)
        return x
    return out


_export("increment", increment)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return run_op("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), [_t(x)])


_export("stanh", stanh)


def lerp(x, y, weight, name=None):
    if isinstance(weight, (int, float)):
        w = float(weight)
        return run_op("lerp", lambda a, b: a + w * (b - a), [_t(x), _t(y)])
    return run_op("lerp", lambda a, b, w: a + w * (b - a), [_t(x), _t(y), _t(weight)])


_export("lerp", lerp)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    return run_op(
        "addmm",
        lambda i, a, b: beta * i + alpha * (a @ b),
        [_t(input), _t(x), _t(y)],
    )


_export("addmm", addmm)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return run_op(
        "trace", lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), [_t(x)]
    )


_export("trace", trace)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    ins = [_t(x)]
    has_pre = prepend is not None
    has_app = append is not None
    if has_pre:
        ins.append(_t(prepend))
    if has_app:
        ins.append(_t(append))

    def fn(a, *rest):
        kw = {}
        i = 0
        if has_pre:
            kw["prepend"] = rest[i]
            i += 1
        if has_app:
            kw["append"] = rest[i]
        return jnp.diff(a, n=n, axis=axis, **kw)

    return run_op("diff", fn, ins)


_export("diff", diff)

# --------------------------------------------------------------------------- #
# operator dunders
# --------------------------------------------------------------------------- #

_add, _sub, _mul, _div = (
    globals()["add"],
    globals()["subtract"],
    globals()["multiply"],
    globals()["divide"],
)


def _install_operators():
    T = Tensor
    T.__add__ = lambda s, o: _add(s, o)
    T.__radd__ = lambda s, o: _add(o, s)
    T.__sub__ = lambda s, o: _sub(s, o)
    T.__rsub__ = lambda s, o: _sub(o, s)
    T.__mul__ = lambda s, o: _mul(s, o)
    T.__rmul__ = lambda s, o: _mul(o, s)
    T.__truediv__ = lambda s, o: _div(s, o)
    T.__rtruediv__ = lambda s, o: _div(o, s)
    T.__floordiv__ = lambda s, o: globals()["floor_divide"](s, o)
    T.__rfloordiv__ = lambda s, o: globals()["floor_divide"](o, s)
    T.__mod__ = lambda s, o: globals()["mod"](s, o)
    T.__rmod__ = lambda s, o: globals()["mod"](o, s)
    T.__pow__ = lambda s, o: globals()["pow"](s, o)
    T.__rpow__ = lambda s, o: globals()["pow"](o, s)
    T.__neg__ = lambda s: globals()["neg"](s)
    T.__abs__ = lambda s: globals()["abs"](s)

    import operator  # noqa: F401

    def _cmp(jfn, name):
        def op(s, o):
            return run_op(name, jfn, [_t(s), _t(o)])

        return op

    T.__eq__ = _cmp(jnp.equal, "equal")
    T.__ne__ = _cmp(jnp.not_equal, "not_equal")
    T.__lt__ = _cmp(jnp.less, "less_than")
    T.__le__ = _cmp(jnp.less_equal, "less_equal")
    T.__gt__ = _cmp(jnp.greater, "greater_than")
    T.__ge__ = _cmp(jnp.greater_equal, "greater_equal")
    # & | ^ ~ are bitwise (on bool dtype jnp bitwise == logical, matching
    # the reference's bitwise_and/or/xor/not operator mapping)
    T.__invert__ = lambda s: run_op(
        "bitwise_not",
        (lambda a: jnp.logical_not(a) if a.dtype == jnp.bool_ else ~a),
        [s],
    )
    T.__and__ = _cmp(lambda a, b: a & b, "bitwise_and")
    T.__rand__ = lambda s, o: run_op("bitwise_and", lambda a, b: a & b, [_t(o), s])
    T.__or__ = _cmp(lambda a, b: a | b, "bitwise_or")
    T.__ror__ = lambda s, o: run_op("bitwise_or", lambda a, b: a | b, [_t(o), s])
    T.__xor__ = _cmp(lambda a, b: a ^ b, "bitwise_xor")
    T.__rxor__ = lambda s, o: run_op("bitwise_xor", lambda a, b: a ^ b, [_t(o), s])


_install_operators()

# register every exported function as a Tensor method, paddle-style
_SKIP_METHODS = {"multiplex"}
for _name in list(__all__):
    if _name not in _SKIP_METHODS:
        register_tensor_method(_name, globals()[_name])
