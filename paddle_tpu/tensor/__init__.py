"""Functional tensor op surface (reference: python/paddle/tensor/)."""

from . import creation, extras, linalg, logic, manipulation, math, random, search, stat, tail
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .tail import *  # noqa: F401,F403

__all__ = (
    list(creation.__all__)
    + list(math.__all__)
    + list(manipulation.__all__)
    + list(linalg.__all__)
    + list(logic.__all__)
    + list(search.__all__)
    + list(stat.__all__)
    + list(random.__all__)
    + list(extras.__all__)
    + list(tail.__all__)
)

# generated `<op>_` in-place variants over the assembled namespace
from .extras import _register_inplace as _reg_inplace  # noqa: E402

__all__ += _reg_inplace(globals())
del _reg_inplace
