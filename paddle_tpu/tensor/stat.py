"""Statistics ops (reference: python/paddle/tensor/stat.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, register_tensor_method, run_op, to_tensor

__all__ = ["std", "var", "median", "nanmedian", "quantile", "nanquantile", "numel"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _ax(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return run_op(
        "std",
        lambda a: jnp.std(a, axis=_ax(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        [_t(x)],
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return run_op(
        "var",
        lambda a: jnp.var(a, axis=_ax(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        [_t(x)],
    )


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def fn(a):
        if mode == "avg":
            return jnp.median(a, axis=_ax(axis), keepdims=keepdim)
        # 'min' mode: lower of the two middle values
        ax = _ax(axis)
        if ax is None:
            flat = jnp.sort(a.reshape(-1))
            return flat[(flat.shape[0] - 1) // 2]
        srt = jnp.sort(a, axis=ax)
        idx = (a.shape[ax] - 1) // 2
        out = jnp.take(srt, idx, axis=ax)
        return jnp.expand_dims(out, ax) if keepdim else out

    return run_op("median", fn, [_t(x)])


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return run_op(
        "nanmedian",
        lambda a: jnp.nanmedian(a, axis=_ax(axis), keepdims=keepdim),
        [_t(x)],
    )


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qq = jnp.asarray(q)
    return run_op(
        "quantile",
        lambda a: jnp.quantile(a, qq, axis=_ax(axis), keepdims=keepdim, method=interpolation),
        [_t(x)],
    )


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qq = jnp.asarray(q)
    return run_op(
        "nanquantile",
        lambda a: jnp.nanquantile(a, qq, axis=_ax(axis), keepdims=keepdim, method=interpolation),
        [_t(x)],
    )


def numel(x, name=None):
    return Tensor(jnp.asarray(_t(x).size, jnp.int32))


for _name in __all__:
    register_tensor_method(_name, globals()[_name])
