"""Tensor-op tail (reference: python/paddle/tensor/ math.py/manipulation.py/
linalg.py exports not covered by the core modules) plus the generated
in-place variants (reference: the `<op>_` inplace APIs, whose tape semantics
ride Tensor._inplace_update's snapshot mechanism)."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, register_tensor_method, run_op, to_tensor
from ..framework.dtype import convert_dtype

__all__ = [
    "add_n", "as_complex", "as_real", "block_diag", "broadcast_shape",
    "cast", "cdist", "cholesky_inverse", "combinations",
    "cumulative_trapezoid", "trapezoid", "diag_embed", "diagonal",
    "diagonal_scatter", "dsplit", "hsplit", "vsplit", "tensor_split",
    "frexp", "gammaln", "gammainc", "gammaincc", "histogram_bin_edges",
    "i0e", "i1e", "index_fill", "isin", "isneginf", "isposinf", "isreal",
    "is_complex", "is_floating_point", "is_integer", "logcumsumexp",
    "lu_unpack", "masked_scatter", "matrix_transpose", "multi_dot",
    "multigammaln", "negative", "positive", "polar", "polygamma", "rank",
    "renorm", "reverse", "scatter_nd", "select_scatter", "slice_scatter",
    "sgn", "shape", "shard_index", "signbit", "sinc", "take",
    "top_p_sampling", "unflatten", "unstack", "vander",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _u(fn, name, *xs):
    return run_op(name, fn, [_t(x) for x in xs])


# --------------------------------------------------------------------------- #
# math / special
# --------------------------------------------------------------------------- #


def add_n(inputs, name=None):
    """reference math.py add_n — elementwise sum of a tensor list."""
    ts = [_t(x) for x in (inputs if isinstance(inputs, (list, tuple)) else [inputs])]
    return run_op("add_n", lambda *vs: sum(vs[1:], vs[0]), ts)


def negative(x, name=None):
    return _u(lambda v: -v, "negative", x)


def positive(x, name=None):
    return _u(lambda v: +v, "positive", x)


def gammaln(x, name=None):
    return _u(lambda v: jax.scipy.special.gammaln(v), "gammaln", x)


def gammainc(x, y, name=None):
    return _u(lambda a, b: jax.scipy.special.gammainc(a, b), "gammainc", x, y)


def gammaincc(x, y, name=None):
    return _u(lambda a, b: jax.scipy.special.gammaincc(a, b), "gammaincc", x, y)


def multigammaln(x, p, name=None):
    return _u(lambda v: jax.scipy.special.multigammaln(v, int(p)),
              "multigammaln", x)


def polygamma(x, n, name=None):
    return _u(lambda v: jax.scipy.special.polygamma(int(n), v), "polygamma", x)


def i0e(x, name=None):
    return _u(lambda v: jax.scipy.special.i0e(v), "i0e", x)


def i1e(x, name=None):
    return _u(lambda v: jax.scipy.special.i1e(v), "i1e", x)


def sinc(x, name=None):
    return _u(lambda v: jnp.sinc(v), "sinc", x)


def signbit(x, name=None):
    return _u(lambda v: jnp.signbit(v), "signbit", x)


def sgn(x, name=None):
    """Complex-aware sign (reference math.py sgn)."""
    def fn(v):
        if jnp.issubdtype(v.dtype, jnp.complexfloating):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, 0, v / jnp.where(mag == 0, 1, mag))
        return jnp.sign(v)

    return _u(fn, "sgn", x)


def frexp(x, name=None):
    def fn(v):
        m, e = jnp.frexp(v)
        return m, e.astype(jnp.int32)

    return run_op("frexp", fn, [_t(x)], n_outputs=2)


def logcumsumexp(x, axis=None, name=None):
    def fn(v):
        a = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else axis
        mx = jnp.max(a)
        return mx + jnp.log(jnp.cumsum(jnp.exp(a - mx), axis=ax))

    return _u(fn, "logcumsumexp", x)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """reference math.py trapezoid."""
    ins = [_t(y)] + ([_t(x)] if x is not None else [])

    def fn(yv, *rest):
        if rest:
            return jnp.trapezoid(yv, rest[0], axis=axis)
        return jnp.trapezoid(yv, dx=1.0 if dx is None else dx, axis=axis)

    return run_op("trapezoid", fn, ins)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    ins = [_t(y)] + ([_t(x)] if x is not None else [])

    def fn(yv, *rest):
        ys = jnp.moveaxis(yv, axis, -1)
        avg = (ys[..., 1:] + ys[..., :-1]) / 2.0
        if rest:
            xs = jnp.moveaxis(rest[0], axis, -1) if rest[0].ndim == yv.ndim else rest[0]
            d = jnp.diff(xs, axis=-1)
        else:
            d = 1.0 if dx is None else dx
        return jnp.moveaxis(jnp.cumsum(avg * d, axis=-1), -1, axis)

    return run_op("cumulative_trapezoid", fn, ins)


def renorm(x, p, axis, max_norm, name=None):
    """reference math.py renorm — clamp sub-tensor p-norms along `axis`."""
    def fn(v):
        moved = jnp.moveaxis(v, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return jnp.moveaxis(moved * scale.reshape(-1, *([1] * (moved.ndim - 1))), 0, axis)

    return _u(fn, "renorm", x)


# --------------------------------------------------------------------------- #
# predicates / casting
# --------------------------------------------------------------------------- #


def cast(x, dtype):
    """reference manipulation.py cast."""
    return _t(x).astype(dtype)


def is_complex(x):
    return bool(jnp.issubdtype(_t(x)._value.dtype, jnp.complexfloating))


def is_floating_point(x):
    return bool(jnp.issubdtype(_t(x)._value.dtype, jnp.floating))


def is_integer(x):
    return bool(jnp.issubdtype(_t(x)._value.dtype, jnp.integer))


def isneginf(x, name=None):
    return _u(lambda v: jnp.isneginf(v), "isneginf", x)


def isposinf(x, name=None):
    return _u(lambda v: jnp.isposinf(v), "isposinf", x)


def isreal(x, name=None):
    return _u(lambda v: jnp.isreal(v), "isreal", x)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return _u(lambda a, b: jnp.isin(a, b, invert=invert), "isin", x, test_x)


# --------------------------------------------------------------------------- #
# complex
# --------------------------------------------------------------------------- #


def as_complex(x, name=None):
    """[..., 2] real pairs -> complex (reference manipulation.py)."""
    return _u(lambda v: jax.lax.complex(v[..., 0], v[..., 1]), "as_complex", x)


def as_real(x, name=None):
    return _u(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1),
              "as_real", x)


def polar(abs, angle, name=None):  # noqa: A002
    return _u(lambda r, t: jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t)),
              "polar", abs, angle)


# --------------------------------------------------------------------------- #
# shapes / manipulation
# --------------------------------------------------------------------------- #


def shape(x):
    """reference: paddle.shape returns an int tensor."""
    return Tensor(jnp.asarray(_t(x)._value.shape, jnp.int32))


def rank(x):
    return Tensor(jnp.asarray(_t(x)._value.ndim, jnp.int32))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def matrix_transpose(x, name=None):
    return _u(lambda v: jnp.swapaxes(v, -1, -2), "matrix_transpose", x)


def reverse(x, axis, name=None):
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    return _u(lambda v: jnp.flip(v, axis=tuple(ax)), "reverse", x)


def unstack(x, axis=0, num=None, name=None):
    t = _t(x)
    n = num or t.shape[axis]
    return [_u(lambda v, i=i: jnp.take(v, i, axis=axis), "unstack", t)
            for i in range(n)]


def unflatten(x, axis, shape, name=None):  # noqa: A002
    def fn(v):
        new = list(v.shape[:axis]) + list(shape) + list(v.shape[axis + 1:])
        return v.reshape(new)

    return _u(fn, "unflatten", x)


def tensor_split(x, num_or_indices, axis=0, name=None):
    t = _t(x)
    if isinstance(num_or_indices, int):
        pieces = np.array_split(np.arange(t.shape[axis]), num_or_indices)
        bounds = [int(p[0]) for p in pieces[1:]]
    else:
        bounds = list(num_or_indices)

    # through run_op so the splits stay on the autograd tape
    def fn(v):
        return tuple(jnp.split(v, bounds, axis=axis))

    out = run_op("tensor_split", fn, [t], n_outputs=len(bounds) + 1)
    return list(out)


def hsplit(x, num_or_indices, name=None):
    t = _t(x)
    return tensor_split(t, num_or_indices, axis=1 if t.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def take(x, index, mode="raise", name=None):
    """reference math.py take — flat-index gather. mode="raise" bounds-checks
    eagerly (concrete indices); under jit it degrades to wrap (documented —
    XLA gathers cannot raise)."""
    xt, it = _t(x), _t(index)
    if mode == "raise" and not isinstance(it._value, jax.core.Tracer):
        n = int(np.prod(xt.shape)) if xt.ndim else 1
        idx = np.asarray(it._value)
        if idx.size and (idx.min() < -n or idx.max() >= n):
            raise IndexError(
                f"take(): index out of range for tensor of {n} elements")

    def fn(v, i):
        return jnp.take(v.reshape(-1), i.astype(jnp.int32),
                        mode="clip" if mode == "clip" else "wrap")

    return _u(fn, "take", xt, it)


def index_fill(x, index, axis, value, name=None):
    def fn(v, i):
        moved = jnp.moveaxis(v, axis, 0)
        moved = moved.at[i.astype(jnp.int32)].set(value)
        return jnp.moveaxis(moved, 0, axis)

    return _u(fn, "index_fill", x, index)


def masked_scatter(x, mask, value, name=None):
    """reference manipulation.py masked_scatter — fill True slots with
    consecutive `value` entries."""
    def fn(v, m, val):
        flat_m = m.reshape(-1)
        idx = jnp.cumsum(flat_m.astype(jnp.int32)) - 1
        src = val.reshape(-1)[jnp.clip(idx, 0, val.size - 1)]
        return jnp.where(flat_m, src, v.reshape(-1)).reshape(v.shape)

    return _u(fn, "masked_scatter", x, mask, value)


def scatter_nd(index, updates, shape, name=None):
    def fn(i, u):
        out = jnp.zeros(tuple(shape), u.dtype)
        return out.at[tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))].add(u)

    return _u(fn, "scatter_nd", index, updates)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def fn(v):
        n = v.shape[-1] + abs(offset)
        out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        rng = jnp.arange(v.shape[-1])
        r = rng + max(-offset, 0)
        c = rng + max(offset, 0)
        out = out.at[..., r, c].set(v)
        # paddle's dim1/dim2 choose where the ROW and COLUMN dims land —
        # order matters (swapping them transposes an off-diagonal embed)
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        if d1 == d2:
            raise ValueError("diag_embed: dim1 and dim2 must differ")
        pi = iter(i for i in range(nd) if i not in (nd - 2, nd - 1))
        order = [nd - 2 if i == d1 else nd - 1 if i == d2 else next(pi)
                 for i in range(nd)]
        return jnp.transpose(out, order)

    return _u(fn, "diag_embed", x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return _u(lambda v: jnp.diagonal(v, offset=offset, axis1=axis1,
                                     axis2=axis2), "diagonal", x)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def fn(v, u):
        moved = jnp.moveaxis(v, (axis1, axis2), (-2, -1))
        diag_len = min(moved.shape[-2] - max(-offset, 0),
                       moved.shape[-1] - max(offset, 0))
        if u.shape[-1] != diag_len:
            raise ValueError(
                f"diagonal_scatter: values length {u.shape[-1]} != diagonal "
                f"length {diag_len} (offset={offset})")
        rng = jnp.arange(u.shape[-1])
        r = rng + max(-offset, 0)
        c = rng + max(offset, 0)
        moved = moved.at[..., r, c].set(u)
        return jnp.moveaxis(moved, (-2, -1), (axis1, axis2))

    return _u(fn, "diagonal_scatter", x, y)


def select_scatter(x, values, axis, index, name=None):
    def fn(v, u):
        return jax.lax.dynamic_update_index_in_dim(
            v, u.astype(v.dtype), index, axis)

    return _u(fn, "select_scatter", x, values)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def fn(v, u):
        sl = [slice(None)] * v.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            sl[ax] = slice(st, en, sd)
        return v.at[tuple(sl)].set(u.astype(v.dtype))

    return _u(fn, "slice_scatter", x, value)


def shard_index(x, index_num, nshards, shard_id, ignore_value=-1, name=None):
    """reference manipulation.py shard_index (PS-era embedding sharding)."""
    size = (index_num + nshards - 1) // nshards  # ceil, per the reference

    def fn(v):
        shard = v // size
        local = v % size
        return jnp.where(shard == shard_id, local, ignore_value)

    return _u(fn, "shard_index", x)


# --------------------------------------------------------------------------- #
# linalg tail
# --------------------------------------------------------------------------- #


def multi_dot(x, name=None):
    ts = [_t(a) for a in x]
    return run_op("multi_dot",
                  lambda *vs: jnp.linalg.multi_dot(list(vs)), ts)


def cholesky_inverse(x, upper=False, name=None):
    def fn(L):
        A = (L.T @ L) if upper else (L @ L.T)
        return jnp.linalg.inv(A)

    return _u(fn, "cholesky_inverse", x)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    def fn(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.maximum(jnp.sum(d * d, axis=-1), 0.0))
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)

    return _u(fn, "cdist", x, y)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """reference linalg.py lu_unpack: (LU, pivots) -> (P, L, U)."""
    if _t(x).ndim > 2:
        raise NotImplementedError("lu_unpack: batched factorizations are "
                                  "not supported yet")

    def fn(lu, piv):
        m = lu.shape[-2]
        n = lu.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu[..., :, :k], -1) + jnp.eye(m, k, dtype=lu.dtype)
        U = jnp.triu(lu[..., :k, :])
        perm = jnp.arange(m)
        for i in range(piv.shape[-1]):
            j = piv[..., i] - 1
            pi, pj = perm[i], perm[j]
            perm = perm.at[i].set(pj).at[j].set(pi)
        P = jnp.eye(m, dtype=lu.dtype)[perm].T
        return P, L, U

    return run_op("lu_unpack", fn, [_t(x), _t(y)], n_outputs=3)


def vander(x, n=None, increasing=False, name=None):
    def fn(v):
        return jnp.vander(v, N=n, increasing=increasing)

    return _u(fn, "vander", x)


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools

    t = _t(x)
    m = t.shape[0]
    gen = (itertools.combinations_with_replacement if with_replacement
           else itertools.combinations)
    idx = np.asarray(list(gen(range(m), r)), np.int32).reshape(-1, r)

    def fn(v):
        return v[jnp.asarray(idx)]

    return _u(fn, "combinations", t)


def block_diag(inputs, name=None):
    ts = [_t(a) for a in inputs]
    return run_op("block_diag",
                  lambda *vs: jax.scipy.linalg.block_diag(*vs), ts)


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):  # noqa: A002
    t = _t(input)
    lo, hi = (float(min), float(max))
    if lo == 0 and hi == 0:
        v = np.asarray(t._value)
        lo, hi = float(v.min()), float(v.max())
    return Tensor(jnp.linspace(lo, hi, int(bins) + 1))


# --------------------------------------------------------------------------- #
# sampling
# --------------------------------------------------------------------------- #


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling over logits [B, V] (reference math.py
    top_p_sampling, kernel fusion/gpu/top_p_sampling). Returns
    (values, ids)."""
    from ..framework import random as rnd

    key = rnd.next_key() if seed is None else jax.random.PRNGKey(int(seed))

    def fn(logits, p):
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        sort_idx = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        keep = cum - sorted_p <= p.reshape(-1, 1)
        filtered = jnp.where(keep, sorted_p, 0.0)
        filtered = filtered / jnp.sum(filtered, axis=-1, keepdims=True)
        choice = jax.random.categorical(key, jnp.log(filtered + 1e-20), axis=-1)
        ids = jnp.take_along_axis(sort_idx, choice[:, None], axis=-1)
        vals = jnp.take_along_axis(probs, ids, axis=-1)
        return vals, ids.astype(jnp.int32)

    return run_op("top_p_sampling", fn, [_t(x), _t(ps)], n_outputs=2)


# --------------------------------------------------------------------------- #
# generated in-place variants (reference: the `<op>_` API family)
# --------------------------------------------------------------------------- #

_INPLACE_BASES = [
    "abs", "acos", "acosh", "add", "asin", "asinh", "atan", "atanh", "ceil",
    "clip", "cos", "cosh", "cumprod", "cumsum", "divide", "equal", "erfinv",
    "exp", "floor", "floor_divide", "frac", "gcd", "greater_equal",
    "greater_than", "lcm", "lerp", "less_equal", "less_than", "lgamma",
    "log", "log10", "log1p", "log2", "logical_and", "logical_not",
    "logical_or", "logical_xor", "logit", "mod", "multiply", "nan_to_num",
    "neg", "not_equal", "pow", "reciprocal", "remainder", "reshape",
    "round", "rsqrt", "scale", "scatter", "sigmoid", "sin", "sinh", "sqrt",
    "square", "squeeze", "subtract", "t", "tan", "tanh", "tril", "triu",
    "trunc", "unsqueeze", "where",
]


def _make_inplace(base_name, base_fn):
    def inplace(x, *args, **kwargs):
        t = x if isinstance(x, Tensor) else to_tensor(x)
        out = base_fn(t, *args, **kwargs)
        t._inplace_update(out)
        return t

    inplace.__name__ = base_name + "_"
    inplace.__doc__ = (f"In-place variant of `{base_name}` (reference "
                       f"{base_name}_); tape semantics via "
                       f"Tensor._inplace_update snapshots.")
    return inplace


def _register_inplace(namespace: dict):
    """Create `<op>_` for every base present in `namespace`; returns the
    new names (called from tensor/__init__)."""
    created = []
    for base in _INPLACE_BASES:
        fn = namespace.get(base)
        if fn is None or (base + "_") in namespace:
            continue
        inplace = _make_inplace(base, fn)
        namespace[base + "_"] = inplace
        if not hasattr(Tensor, base + "_"):
            register_tensor_method(base + "_", inplace)
        created.append(base + "_")
    return created


# register as Tensor methods (paddle-style), skipping anything that would
# shadow an existing Tensor attribute/property (shape, rank, cast-alias...)
_SKIP_METHODS = {n for n in __all__ if hasattr(Tensor, n)}
for _name in list(__all__):
    if _name not in _SKIP_METHODS:
        register_tensor_method(_name, globals()[_name])
