"""Random sampling ops (reference: python/paddle/tensor/random.py).

Each draw splits the global threefry key (framework/random.py). Under jit
tracing the key is captured as a constant at trace time — deterministic per
trace, matching the reference's seeded-Philox semantics closely enough for
training; dropout layers thread explicit keys instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework import random as rnd
from ..framework.core import Tensor, register_tensor_method, run_op, to_tensor

__all__ = [
    "rand",
    "randn",
    "randint",
    "randint_like",
    "randperm",
    "uniform",
    "uniform_",
    "normal",
    "normal_",
    "standard_normal",
    "gaussian",
    "poisson",
    "bernoulli",
    "multinomial",
    "exponential_",
    "binomial",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def _dt(dtype, default=None):
    if dtype is None:
        return jnp.dtype(default if default is not None else dtype_mod.default_float_dtype())
    return jnp.dtype(dtype_mod.convert_dtype(dtype))


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(rnd.next_key(), _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(rnd.next_key(), _shape(shape), _dt(dtype)))


standard_normal = randn


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = jax.random.PRNGKey(seed) if seed else rnd.next_key()
    return Tensor(mean + std * jax.random.normal(key, _shape(shape), _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            np.shape(m) if not hasattr(m, "shape") else m.shape,
            np.shape(s) if not hasattr(s, "shape") else s.shape,
        )
        return Tensor(m + s * jax.random.normal(rnd.next_key(), shp, jnp.float32))
    return gaussian(shape if shape is not None else [1], mean, std)


def normal_(x, mean=0.0, std=1.0, name=None):
    x.set_value(mean + std * jax.random.normal(rnd.next_key(), tuple(x.shape), jnp.dtype(x.dtype) if dtype_mod.is_floating_point_dtype(x.dtype) else jnp.float32))
    return x


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    key = jax.random.PRNGKey(seed) if seed else rnd.next_key()
    return Tensor(
        jax.random.uniform(key, _shape(shape), _dt(dtype), minval=min, maxval=max)
    )


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    x.set_value(
        jax.random.uniform(rnd.next_key(), tuple(x.shape), jnp.dtype(x.dtype), min, max)
    )
    return x


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = _dt(dtype, np.int32)
    return Tensor(
        jax.random.randint(rnd.next_key(), _shape(shape), int(low), int(high), d)
    )


def randint_like(x, low=0, high=None, dtype=None, name=None):
    xx = x if isinstance(x, Tensor) else to_tensor(x)
    return randint(low, high, xx.shape, dtype or xx.dtype)


def randperm(n, dtype=None, name=None):
    d = _dt(dtype, np.int32)
    return Tensor(jax.random.permutation(rnd.next_key(), int(n)).astype(d))


def poisson(x, name=None):
    xx = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(
        jax.random.poisson(rnd.next_key(), xx._value).astype(xx._value.dtype)
    )


def bernoulli(x, name=None):
    xx = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(
        jax.random.bernoulli(rnd.next_key(), xx._value).astype(xx._value.dtype)
    )


def binomial(count, prob, name=None):
    c = count._value if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob._value if isinstance(prob, Tensor) else jnp.asarray(prob)
    return Tensor(jax.random.binomial(rnd.next_key(), c, p).astype(jnp.int32))


def multinomial(x, num_samples=1, replacement=False, name=None):
    xx = x if isinstance(x, Tensor) else to_tensor(x)
    logits = jnp.log(jnp.clip(xx._value, 1e-30, None))
    if replacement:
        out = jax.random.categorical(
            rnd.next_key(), logits, axis=-1, shape=logits.shape[:-1] + (int(num_samples),)
        )
    else:
        # Gumbel top-k trick for without-replacement sampling
        g = jax.random.gumbel(rnd.next_key(), logits.shape)
        _, out = jax.lax.top_k(logits + g, int(num_samples))
    return Tensor(out.astype(jnp.int32))


def exponential_(x, lam=1.0, name=None):
    x.set_value(
        jax.random.exponential(rnd.next_key(), tuple(x.shape), jnp.dtype(x.dtype)) / lam
    )
    return x


for _name in ("uniform_", "normal_", "exponential_"):
    register_tensor_method(_name, globals()[_name])
register_tensor_method("multinomial", multinomial)
register_tensor_method("bernoulli", bernoulli)
