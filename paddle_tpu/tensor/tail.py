"""Long-tail tensor ops closing the ops.yaml audit gaps (round 4).

References: python/paddle/tensor/creation.py (tril_indices:2480,
triu_indices, complex), tensor/manipulation.py (fill_diagonal_,
fill_diagonal_tensor, reduce_as), tensor/math.py (clip_by_norm),
nn kernels edit_distance / standard_gamma.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, register_tensor_method, run_op, to_tensor

__all__ = [
    "tril_indices",
    "triu_indices",
    "complex",
    "fill_diagonal_",
    "fill_diagonal_tensor",
    "fill_diagonal_tensor_",
    "reduce_as",
    "edit_distance",
    "clip_by_norm",
    "standard_gamma",
    "histogramdd",
    "cauchy_",
    "geometric_",
]


def tril_indices(row, col=None, offset=0, dtype="int64"):
    """reference tensor/creation.py tril_indices (ops.yaml tril_indices)."""
    if col is None:
        col = row
    r, c = np.tril_indices(int(row), int(offset), int(col))
    return to_tensor(np.stack([r, c]).astype(np.int64))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    """reference tensor/creation.py triu_indices."""
    if col is None:
        col = row
    r, c = np.triu_indices(int(row), int(offset), int(col))
    return to_tensor(np.stack([r, c]).astype(np.int64))


def complex(real, imag, name=None):  # noqa: A001
    """reference tensor/creation.py complex (ops.yaml complex)."""
    return run_op("complex", jax.lax.complex, [real, imag])


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    """In-place diagonal fill (reference tensor/manipulation.py
    fill_diagonal_; ops.yaml fill_diagonal)."""
    def fn(a):
        if a.ndim == 2 and wrap and a.shape[0] > a.shape[1]:
            # wrap: the diagonal restarts after each W+1 flat elements
            # (NumPy fill_diagonal wrap semantics; offset must be 0)
            H, W = a.shape
            flat = np.arange(0, H * W, W + 1)
            return a.reshape(-1).at[flat].set(value).reshape(H, W)
        n = min(a.shape[-2], a.shape[-1])
        idx = np.arange(n)
        r = idx - min(offset, 0)
        c = idx + max(offset, 0)
        ok = (r < a.shape[-2]) & (c < a.shape[-1])
        r, c = r[ok], c[ok]
        return a.at[..., r, c].set(value)

    out = run_op("fill_diagonal", fn, [x])
    if isinstance(x, Tensor):
        return x._inplace_update(out)
    return out


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """Write y along the (dim1, dim2) diagonal of x (reference
    tensor/manipulation.py fill_diagonal_tensor; ops.yaml
    fill_diagonal_tensor)."""
    def fn(a, v):
        d1, d2 = dim1 % a.ndim, dim2 % a.ndim
        perm = [d for d in range(a.ndim) if d not in (d1, d2)] + [d1, d2]
        inv = np.argsort(perm)
        t = jnp.transpose(a, perm)
        n = min(t.shape[-2], t.shape[-1])
        idx = np.arange(n)
        r = idx - min(offset, 0)
        c = idx + max(offset, 0)
        ok = (r < t.shape[-2]) & (c < t.shape[-1])
        r, c = r[ok], c[ok]
        t = t.at[..., r, c].set(v[..., : r.shape[0]])
        return jnp.transpose(t, inv)

    return run_op("fill_diagonal_tensor", fn, [x, y])


def fill_diagonal_tensor_(x, y, offset=0, dim1=0, dim2=1, name=None):
    out = fill_diagonal_tensor(x, y, offset, dim1, dim2)
    if isinstance(x, Tensor):
        return x._inplace_update(out)
    return out


def reduce_as(x, target, name=None):
    """Sum x down to target's shape (reference tensor/math.py reduce_as;
    ops.yaml reduce_as)."""
    tgt_shape = tuple(int(s) for s in target.shape)

    def fn(a):
        extra = a.ndim - len(tgt_shape)
        axes = list(range(extra))
        for i, s in enumerate(tgt_shape):
            if a.shape[extra + i] != s:
                axes.append(extra + i)
        out = a.sum(axis=tuple(axes), keepdims=False) if axes else a
        return out.reshape(tgt_shape)

    return run_op("reduce_as", fn, [x])


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance per sequence pair (reference ops.yaml
    edit_distance; kernel edit_distance_kernel.cu). Host-side dynamic
    programming — the op is a metric, not a training path.

    Returns (distance [B,1], sequence_num [1])."""
    a = np.asarray(input._value if isinstance(input, Tensor) else input)
    b = np.asarray(label._value if isinstance(label, Tensor) else label)
    il = (np.asarray(input_length._value).reshape(-1)
          if input_length is not None else None)
    ll = (np.asarray(label_length._value).reshape(-1)
          if label_length is not None else None)
    ig = set(ignored_tokens or [])
    B = a.shape[0]
    out = np.zeros((B, 1), np.float32)
    for i in range(B):
        s1 = a[i][: int(il[i])] if il is not None else a[i]
        s2 = b[i][: int(ll[i])] if ll is not None else b[i]
        s1 = [t for t in s1.tolist() if t not in ig]
        s2 = [t for t in s2.tolist() if t not in ig]
        m, n = len(s1), len(s2)
        dp = np.arange(n + 1, dtype=np.int64)
        for r in range(1, m + 1):
            prev = dp.copy()
            dp[0] = r
            for cc in range(1, n + 1):
                dp[cc] = min(prev[cc] + 1, dp[cc - 1] + 1,
                             prev[cc - 1] + (s1[r - 1] != s2[cc - 1]))
        d = float(dp[n])
        if normalized:
            d = d / max(n, 1)
        out[i, 0] = d
    return to_tensor(out), to_tensor(np.asarray([B], np.int64))


def clip_by_norm(x, max_norm, name=None):
    """Scale x so ||x||_2 <= max_norm (reference ops.yaml clip_by_norm;
    python/paddle/nn/clip.py)."""
    def fn(a):
        norm = jnp.sqrt(jnp.maximum(jnp.sum(a * a), 1e-12))
        scale = jnp.minimum(max_norm / norm, 1.0)
        return a * scale

    return run_op("clip_by_norm", fn, [x])


def standard_gamma(x, name=None):
    """Sample Gamma(alpha=x, 1) elementwise (reference ops.yaml
    standard_gamma; paddle.standard_gamma)."""
    from ..framework import random as rnd

    def fn(a, key):
        return jax.random.gamma(key, a, dtype=a.dtype)

    return run_op("standard_gamma", fn, [x, rnd.rng_tensor()])


for _name in ("fill_diagonal_", "fill_diagonal_tensor",
              "fill_diagonal_tensor_", "reduce_as", "clip_by_norm"):
    if not hasattr(Tensor, _name):
        register_tensor_method(_name, globals()[_name])


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """reference tensor/linalg.py histogramdd — host-side (variable bin
    edges are data-dependent metadata). Returns (hist, edges_list)."""
    sample = np.asarray(x._value if isinstance(x, Tensor) else x)
    w = np.asarray(weights._value if isinstance(weights, Tensor)
                   else weights) if weights is not None else None
    if isinstance(bins, Tensor):
        bins = np.asarray(bins._value)
    if isinstance(bins, (list, tuple)):
        bins = [np.asarray(b._value) if isinstance(b, Tensor) else b
                for b in bins]
    if ranges is not None:
        flat = [float(v) for v in np.asarray(
            ranges._value if isinstance(ranges, Tensor) else ranges
        ).reshape(-1)]
        ranges = [(flat[2 * i], flat[2 * i + 1])
                  for i in range(len(flat) // 2)]  # paddle passes 2*D flat
    hist, edges = np.histogramdd(sample, bins=bins, range=ranges,
                                 density=density, weights=w)
    return (to_tensor(hist.astype(np.float32)),
            [to_tensor(e.astype(np.float32)) for e in edges])


def cauchy_(x, loc=0.0, scale=1.0, name=None):
    """In-place Cauchy fill (reference tensor/random.py cauchy_)."""
    from ..framework import random as rnd

    def fn(a, key):
        return loc + scale * jax.random.cauchy(key, a.shape, a.dtype)

    out = run_op("cauchy", fn, [x, rnd.rng_tensor()])
    return x._inplace_update(out) if isinstance(x, Tensor) else out


def geometric_(x, probs, name=None):
    """In-place Geometric(probs) fill (reference tensor/random.py
    geometric_)."""
    from ..framework import random as rnd

    def fn(a, key):
        u = jax.random.uniform(key, a.shape, jnp.float32, 1e-7, 1.0)
        return (jnp.ceil(jnp.log(u) / jnp.log1p(-probs))).astype(a.dtype)

    out = run_op("geometric", fn, [x, rnd.rng_tensor()])
    return x._inplace_update(out) if isinstance(x, Tensor) else out


for _name in ("cauchy_", "geometric_"):
    if not hasattr(Tensor, _name):
        register_tensor_method(_name, globals()[_name])
