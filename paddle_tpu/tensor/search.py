"""Search / sort ops (reference: python/paddle/tensor/search.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, register_tensor_method, run_op, to_tensor

__all__ = [
    "argmax",
    "argmin",
    "argsort",
    "sort",
    "topk",
    "where",
    "nonzero",
    "searchsorted",
    "index_sample",
    "kthvalue",
    "mode",
    "masked_fill_",
    "bucketize",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _idx_dtype(dtype):
    from ..framework import dtype as dtype_mod

    return jnp.dtype(dtype_mod.convert_dtype(dtype or "int64"))


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = _idx_dtype(dtype)

    def fn(a):
        if axis is None:
            return jnp.argmax(a.reshape(-1)).astype(d)
        return jnp.argmax(a, axis=int(axis), keepdims=keepdim).astype(d)

    return run_op("argmax", fn, [_t(x)])


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = _idx_dtype(dtype)

    def fn(a):
        if axis is None:
            return jnp.argmin(a.reshape(-1)).astype(d)
        return jnp.argmin(a, axis=int(axis), keepdims=keepdim).astype(d)

    return run_op("argmin", fn, [_t(x)])


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(a):
        idx = jnp.argsort(a, axis=axis, stable=True, descending=descending)
        return idx.astype(jnp.int32)

    return run_op("argsort", fn, [_t(x)])


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(a):
        return jnp.sort(a, axis=axis, stable=True, descending=descending)

    return run_op("sort", fn, [_t(x)])


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):  # noqa: A002
    if isinstance(k, Tensor):
        k = int(k.item())
    kk = int(k)

    import jax as _jax

    def fn(a):
        ax = axis % a.ndim
        am = jnp.moveaxis(a, ax, -1)
        src = am if largest else -am
        vals, idx = _jax.lax.top_k(src, kk)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int32), -1, ax)

    return run_op("topk", fn, [_t(x)])


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return run_op(
        "where",
        lambda c, a, b: jnp.where(c, a, b),
        [_t(condition), _t(x), _t(y)],
    )


def nonzero(x, as_tuple=False):
    arr = np.asarray(_t(x)._value)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.astype(np.int32))[:, None]) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int32)))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"

    def fn(s, v):
        out = jnp.searchsorted(s, v, side=side)
        return out.astype(jnp.int32)

    return run_op("searchsorted", fn, [_t(sorted_sequence), _t(values)])


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def index_sample(x, index):
    def fn(a, i):
        return jnp.take_along_axis(a, i.astype(jnp.int32), axis=1)

    return run_op("index_sample", fn, [_t(x), _t(index)])


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    kk = int(k)

    def fn(a):
        ax = axis % a.ndim
        vals = jnp.sort(a, axis=ax)
        idx = jnp.argsort(a, axis=ax).astype(jnp.int32)
        v = jnp.take(vals, kk - 1, axis=ax)
        i = jnp.take(idx, kk - 1, axis=ax)
        if keepdim:
            v, i = jnp.expand_dims(v, ax), jnp.expand_dims(i, ax)
        return v, i

    return run_op("kthvalue", fn, [_t(x)])


def mode(x, axis=-1, keepdim=False, name=None):
    arr = np.asarray(_t(x)._value)
    ax = axis % arr.ndim
    moved = np.moveaxis(arr, ax, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], dtype=arr.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int32)
    for r in range(flat.shape[0]):
        uniq, counts = np.unique(flat[r], return_counts=True)
        best = uniq[np.argmax(counts)]
        vals[r] = best
        idxs[r] = np.where(flat[r] == best)[0][-1]
    out_shape = moved.shape[:-1]
    v = vals.reshape(out_shape)
    i = idxs.reshape(out_shape)
    if keepdim:
        v, i = np.expand_dims(v, ax), np.expand_dims(i, ax)
    return Tensor(jnp.asarray(v)), Tensor(jnp.asarray(i))


def masked_fill_(x, mask, value, name=None):
    from .manipulation import masked_fill

    out = masked_fill(x, mask, value)
    x._inplace_update(out)
    return x


for _name in __all__:
    register_tensor_method(_name, globals()[_name])
