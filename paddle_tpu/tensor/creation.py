"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.core import Tensor, run_op, to_tensor

__all__ = [
    "to_tensor",
    "zeros",
    "zeros_like",
    "ones",
    "ones_like",
    "full",
    "full_like",
    "empty",
    "empty_like",
    "arange",
    "linspace",
    "logspace",
    "eye",
    "diag",
    "diagflat",
    "meshgrid",
    "tril",
    "triu",
    "assign",
    "clone",
    "create_parameter",
]


def _resolve_dtype(dtype, default=None):
    if dtype is None:
        return default if default is not None else dtype_mod.default_float_dtype()
    return dtype_mod.convert_dtype(dtype)


def _shape_tuple(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape_tuple(shape), jnp.dtype(_resolve_dtype(dtype))))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape_tuple(shape), jnp.dtype(_resolve_dtype(dtype))))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = np.bool_
        elif isinstance(fill_value, int):
            dtype = np.int32
        else:
            dtype = dtype_mod.default_float_dtype()
    return Tensor(
        jnp.full(_shape_tuple(shape), fill_value, jnp.dtype(dtype_mod.convert_dtype(dtype)))
    )


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    d = None if dtype is None else jnp.dtype(dtype_mod.convert_dtype(dtype))
    return Tensor(jnp.zeros_like(x._value, dtype=d))


def ones_like(x, dtype=None, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    d = None if dtype is None else jnp.dtype(dtype_mod.convert_dtype(dtype))
    return Tensor(jnp.ones_like(x._value, dtype=d))


def full_like(x, fill_value, dtype=None, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    d = None if dtype is None else jnp.dtype(dtype_mod.convert_dtype(dtype))
    return Tensor(jnp.full_like(x._value, fill_value, dtype=d))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange with Tensor bounds is not supported; pass scalars")
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dtype = np.int32
        else:
            dtype = dtype_mod.default_float_dtype()
    else:
        dtype = dtype_mod.convert_dtype(dtype)
    return Tensor(jnp.arange(start, end, step, dtype=jnp.dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    d = jnp.dtype(_resolve_dtype(dtype))
    return Tensor(jnp.linspace(start, stop, int(num), dtype=d))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    d = jnp.dtype(_resolve_dtype(dtype))
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=d))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    d = jnp.dtype(_resolve_dtype(dtype))
    return Tensor(jnp.eye(num_rows, num_columns, dtype=d))


def diag(x, offset=0, padding_value=0, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x

    def fn(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.eye(out.shape[0], out.shape[1], k=offset, dtype=bool)
                out = jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
            return out
        return jnp.diagonal(a, offset=offset)

    return run_op("diag", fn, [x])


def diagflat(x, offset=0, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return run_op("diagflat", lambda a: jnp.diagflat(a, k=offset), [x])


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    ts = [to_tensor(a) if not isinstance(a, Tensor) else a for a in args]
    outs = run_op(
        "meshgrid", lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")), ts
    )
    return list(outs)


def tril(x, diagonal=0, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return run_op("tril", lambda a: jnp.tril(a, k=diagonal), [x])


def triu(x, diagonal=0, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return run_op("triu", lambda a: jnp.triu(a, k=diagonal), [x])


def assign(x, output=None):
    src = to_tensor(x) if not isinstance(x, Tensor) else x
    out = run_op("assign", lambda a: a + jnp.zeros((), a.dtype), [src])
    if output is not None:
        output._inplace_update(out)
        return output
    return out


def clone(x, name=None):
    return assign(x)


def create_parameter(shape, dtype=None, name=None, attr=None, is_bias=False, default_initializer=None):
    from ..framework.core import Parameter
    from ..framework import random as rnd
    import jax

    d = jnp.dtype(_resolve_dtype(dtype))
    shape = _shape_tuple(shape)
    if default_initializer is not None:
        t = zeros(shape, d)
        p = Parameter(t._value, name=name)
        default_initializer(p)
        return p
    if is_bias:
        return Parameter(jnp.zeros(shape, d), name=name)
    # Xavier-uniform default, like the reference's default param init
    fan_in = shape[0] if shape else 1
    fan_out = shape[-1] if shape else 1
    limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
    val = jax.random.uniform(rnd.next_key(), shape, jnp.float32, -limit, limit).astype(d)
    return Parameter(val, name=name)
