"""Shape / layout manipulation ops (reference: python/paddle/tensor/manipulation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.core import Tensor, register_tensor_method, run_op, to_tensor

__all__ = [
    "reshape",
    "flatten",
    "transpose",
    "t",
    "moveaxis",
    "swapaxes",
    "squeeze",
    "unsqueeze",
    "concat",
    "stack",
    "hstack",
    "vstack",
    "dstack",
    "split",
    "chunk",
    "unbind",
    "tile",
    "expand",
    "expand_as",
    "broadcast_to",
    "broadcast_tensors",
    "flip",
    "rot90",
    "roll",
    "gather",
    "gather_nd",
    "scatter",
    "scatter_nd_add",
    "index_select",
    "index_add",
    "index_put",
    "take_along_axis",
    "put_along_axis",
    "masked_select",
    "masked_fill",
    "slice",
    "strided_slice",
    "pad",
    "repeat_interleave",
    "unique",
    "unique_consecutive",
    "flatten_",
    "as_strided",
    "view",
    "view_as",
    "unfold",
    "tensordot",
    "atleast_1d",
    "atleast_2d",
    "atleast_3d",
    "tolist",
    "crop",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _static_shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()

    def coerce(s):
        try:
            return int(s)
        except Exception:
            return s  # symbolic dim (jax.export shape polymorphism)

    return tuple(coerce(s) for s in shape)


def reshape(x, shape, name=None):
    shp = _static_shape(shape)
    return run_op("reshape", lambda a: a.reshape(shp), [_t(x)])


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    xx = _t(x)
    nd = xx.ndim
    if nd == 0:
        return reshape(xx, [1])
    sa = start_axis % nd
    so = stop_axis % nd

    def fn(a):
        shp = a.shape[:sa] + (-1,) + a.shape[so + 1:]
        return a.reshape(shp)

    return run_op("flatten", fn, [xx])


flatten_ = flatten


def transpose(x, perm=None, name=None):
    if perm is not None:
        perm = tuple(int(p) for p in perm)
    return run_op("transpose", lambda a: jnp.transpose(a, perm), [_t(x)])


def t(x, name=None):
    xx = _t(x)
    if xx.ndim < 2:
        return xx
    return run_op("t", lambda a: jnp.swapaxes(a, -1, -2), [xx])


def moveaxis(x, source, destination, name=None):
    return run_op("moveaxis", lambda a: jnp.moveaxis(a, source, destination), [_t(x)])


def swapaxes(x, axis1, axis2, name=None):
    return run_op("swapaxes", lambda a: jnp.swapaxes(a, axis1, axis2), [_t(x)])


def squeeze(x, axis=None, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    elif axis is not None:
        axis = int(axis)

    def fn(a):
        if axis is None:
            return jnp.squeeze(a)
        ax = axis if isinstance(axis, tuple) else (axis,)
        ax = tuple(a_ % a.ndim for a_ in ax)
        ax = tuple(a_ for a_ in ax if a.shape[a_] == 1)
        return jnp.squeeze(a, axis=ax) if ax else a

    return run_op("squeeze", fn, [_t(x)])


def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        ax = tuple(int(a) for a in axis)
    else:
        ax = (int(axis),)

    def fn(a):
        out = a
        for v in ax:
            out = jnp.expand_dims(out, v)
        return out

    return run_op("unsqueeze", fn, [_t(x)])


def concat(x, axis=0, name=None):
    ts = [_t(v) for v in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    ax = int(axis)
    return run_op("concat", lambda *vs: jnp.concatenate(vs, axis=ax), ts)


def stack(x, axis=0, name=None):
    ts = [_t(v) for v in x]
    ax = int(axis)
    return run_op("stack", lambda *vs: jnp.stack(vs, axis=ax), ts)


def hstack(x, name=None):
    ts = [_t(v) for v in x]
    return run_op("hstack", lambda *vs: jnp.hstack(vs), ts)


def vstack(x, name=None):
    ts = [_t(v) for v in x]
    return run_op("vstack", lambda *vs: jnp.vstack(vs), ts)


def dstack(x, name=None):
    ts = [_t(v) for v in x]
    return run_op("dstack", lambda *vs: jnp.dstack(vs), ts)


def split(x, num_or_sections, axis=0, name=None):
    xx = _t(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    ax = int(axis) % max(xx.ndim, 1)
    dim = xx.shape[ax]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dimension {ax} of size {dim} is not divisible by "
                f"num={num_or_sections}; pass explicit section sizes instead"
            )
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = [int(s) if not isinstance(s, Tensor) else int(s.item()) for s in num_or_sections]
        n_unknown = sum(1 for s in sections if s < 0)
        if n_unknown:
            known = sum(s for s in sections if s >= 0)
            sections = [s if s >= 0 else dim - known for s in sections]
    offsets = np.cumsum([0] + sections)

    def fn(a):
        return tuple(
            jax.lax.slice_in_dim(a, int(offsets[i]), int(offsets[i + 1]), axis=ax)
            for i in range(len(sections))
        )

    return list(run_op("split", fn, [xx]))


def chunk(x, chunks, axis=0, name=None):
    xx = _t(x)
    n = int(chunks)
    ax = int(axis) % max(xx.ndim, 1)
    dim = xx.shape[ax]
    if dim % n == 0:
        return split(xx, n, ax)
    # uneven: ceil-sized chunks with a smaller last chunk
    size = -(-dim // n)
    sections = []
    left = dim
    while left > 0:
        sections.append(min(size, left))
        left -= size
    return split(xx, sections, ax)


def unbind(x, axis=0, name=None):
    xx = _t(x)
    ax = int(axis) % xx.ndim
    n = xx.shape[ax]

    def fn(a):
        return tuple(jnp.squeeze(v, ax) for v in jnp.split(a, n, axis=ax))

    return list(run_op("unbind", fn, [xx]))


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.tolist()
    reps = tuple(int(r) for r in repeat_times)
    return run_op("tile", lambda a: jnp.tile(a, reps), [_t(x)])


def expand(x, shape, name=None):
    shp = _static_shape(shape)
    xx = _t(x)

    def fn(a):
        target = tuple(
            a.shape[i - (len(shp) - a.ndim)] if s == -1 else s
            for i, s in enumerate(shp)
        )
        return jnp.broadcast_to(a, target)

    return run_op("expand", fn, [xx])


def expand_as(x, y, name=None):
    yy = _t(y)
    return expand(x, yy.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    ts = [_t(v) for v in inputs]
    outs = run_op(
        "broadcast_tensors", lambda *vs: tuple(jnp.broadcast_arrays(*vs)), ts
    )
    return list(outs)


def flip(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    ax = tuple(int(a) for a in axis)
    return run_op("flip", lambda a: jnp.flip(a, axis=ax), [_t(x)])


def rot90(x, k=1, axes=(0, 1), name=None):
    return run_op("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), [_t(x)])


def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, Tensor):
        shifts = shifts.tolist()
    return run_op("roll", lambda a: jnp.roll(a, shifts, axis=axis), [_t(x)])


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    ax = int(axis)
    return run_op(
        "gather",
        lambda a, i: jnp.take(a, i.astype(jnp.int32).reshape(-1), axis=ax),
        [_t(x), _t(index)],
    )


def gather_nd(x, index, name=None):
    def fn(a, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        out = a[tuple(jnp.moveaxis(idx, -1, 0))]
        return out

    return run_op("gather_nd", fn, [_t(x), _t(index)])


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(a, i, u):
        i = i.astype(jnp.int32).reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        z = a.at[i].set(jnp.zeros_like(u))
        return z.at[i].add(u)

    return run_op("scatter", fn, [_t(x), _t(index), _t(updates)])


def scatter_nd_add(x, index, updates, name=None):
    def fn(a, i, u):
        i = i.astype(jnp.int32)
        return a.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)

    return run_op("scatter_nd_add", fn, [_t(x), _t(index), _t(updates)])


def index_select(x, index, axis=0, name=None):
    ax = int(axis)
    return run_op(
        "index_select",
        lambda a, i: jnp.take(a, i.astype(jnp.int32).reshape(-1), axis=ax),
        [_t(x), _t(index)],
    )


def index_add(x, index, axis, value, name=None):
    ax = int(axis)

    def fn(a, i, v):
        i = i.astype(jnp.int32).reshape(-1)
        vm = jnp.moveaxis(v, ax % a.ndim, 0)
        am = jnp.moveaxis(a, ax % a.ndim, 0)
        am = am.at[i].add(vm.astype(a.dtype))
        return jnp.moveaxis(am, 0, ax % a.ndim)

    return run_op("index_add", fn, [_t(x), _t(index), _t(value)])


def index_put(x, indices, value, accumulate=False, name=None):
    idx_ts = [_t(i) for i in indices]
    vv = _t(value)

    def fn(a, v, *idx):
        idx = tuple(i.astype(jnp.int32) if i.dtype != jnp.bool_ else i for i in idx)
        if accumulate:
            return a.at[idx].add(v)
        return a.at[idx].set(v.astype(a.dtype))

    return run_op("index_put", fn, [_t(x), vv] + idx_ts)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    ax = int(axis)
    return run_op(
        "take_along_axis",
        lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=ax),
        [_t(arr), _t(indices)],
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):  # noqa: A002
    ax = int(axis)
    mode = reduce

    def fn(a, i, v):
        i = i.astype(jnp.int32)
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        if mode == "assign":
            return jnp_put_along_axis_set(a, i, v, ax)
        if mode == "add":
            return jnp_put_along_axis_add(a, i, v, ax)
        if mode in ("mul", "multiply"):
            ones = jnp_put_along_axis_set(jnp.ones_like(a), i, v, ax)
            return a * ones
        raise ValueError(f"unsupported reduce mode {mode}")

    return run_op("put_along_axis", fn, [_t(arr), _t(indices), _t(values)])


def _along_axis_indices(i, axis):
    idx = list(jnp.meshgrid(*[jnp.arange(s) for s in i.shape], indexing="ij"))
    idx[axis % i.ndim] = i
    return tuple(idx)


def jnp_put_along_axis_set(a, i, v, axis):
    return a.at[_along_axis_indices(i, axis)].set(v)


def jnp_put_along_axis_add(a, i, v, axis):
    return a.at[_along_axis_indices(i, axis)].add(v)


def masked_select(x, mask, name=None):
    # dynamic output shape: eager-only (same restriction the reference's
    # to_static places on masked_select without explicit shape hints)
    xx, mm = _t(x), _t(mask)
    vals = np.asarray(xx._value)[np.asarray(mm._value)]
    out = Tensor(jnp.asarray(vals))
    return out


def masked_fill(x, mask, value, name=None):
    if isinstance(value, Tensor):
        return run_op(
            "masked_fill",
            lambda a, m, v: jnp.where(m, v.astype(a.dtype), a),
            [_t(x), _t(mask), value],
        )
    return run_op(
        "masked_fill",
        lambda a, m: jnp.where(m, jnp.asarray(value, a.dtype), a),
        [_t(x), _t(mask)],
    )


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    axes = [int(a) for a in axes]
    starts = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in starts]
    ends = [int(e.item()) if isinstance(e, Tensor) else int(e) for e in ends]
    xx = _t(x)

    def fn(a):
        out = a
        for ax, st, en in zip(axes, starts, ends):
            dim = a.shape[ax]
            st2 = max(st + dim, 0) if st < 0 else min(st, dim)
            en2 = max(en + dim, 0) if en < 0 else min(en, dim)
            out = jax.lax.slice_in_dim(out, st2, en2, axis=ax)
        return out

    return run_op("slice", fn, [xx])


def strided_slice(x, axes, starts, ends, strides, name=None):
    xx = _t(x)
    axes = [int(a) for a in axes]
    starts = [int(s) for s in starts]
    ends = [int(e) for e in ends]
    strides_ = [int(s) for s in strides]

    def fn(a):
        index = [np.s_[:]] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides_):
            index[ax] = np.s_[st:en:sd]
        return a[tuple(index)]

    return run_op("strided_slice", fn, [xx])


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    xx = _t(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]

    def fn(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle semantics: pad applies to the trailing spatial dims,
            # ordered from the last dim inward when data_format is NCHW/NCL/NCDHW
            n_spatial = len(pad) // 2
            widths = [(0, 0)] * nd
            if data_format.startswith("NC"):
                dims = range(nd - n_spatial, nd)
            else:
                dims = range(1, 1 + n_spatial)
            for k, d in enumerate(dims):
                widths[d] = (pad[2 * k], pad[2 * k + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, widths, mode="constant", constant_values=value)
        return jnp.pad(a, widths, mode=jmode)

    return run_op("pad", fn, [xx])


def repeat_interleave(x, repeats, axis=None, name=None):
    xx = _t(x)
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats._value)
        total = int(reps.sum())

        def fn(a, r):
            return jnp.repeat(a, r, axis=axis, total_repeat_length=total)

        return run_op("repeat_interleave", fn, [xx, _t(repeats)])
    return run_op(
        "repeat_interleave", lambda a: jnp.repeat(a, int(repeats), axis=axis), [xx]
    )


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    # dynamic shape -> host computation (eager-only), like reference unique on CPU
    arr = np.asarray(_t(x)._value)
    res = np.unique(
        arr, return_index=True, return_inverse=True, return_counts=True, axis=axis
    )
    vals, idx, inv, cnt = res
    outs = [Tensor(jnp.asarray(vals))]
    if return_index:
        outs.append(Tensor(jnp.asarray(idx.astype(np.int32))))
    if return_inverse:
        outs.append(Tensor(jnp.asarray(inv.astype(np.int32))))
    if return_counts:
        outs.append(Tensor(jnp.asarray(cnt.astype(np.int32))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    arr = np.asarray(_t(x)._value)
    if axis is None:
        arr = arr.reshape(-1)
        keep = np.ones(arr.shape[0], dtype=bool)
        keep[1:] = arr[1:] != arr[:-1]
        vals = arr[keep]
    else:
        raise NotImplementedError("unique_consecutive with axis is not supported yet")
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv.astype(np.int32))))
    if return_counts:
        idx = np.flatnonzero(keep)
        cnt = np.diff(np.append(idx, arr.shape[0]))
        outs.append(Tensor(jnp.asarray(cnt.astype(np.int32))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def as_strided(x, shape, stride, offset=0, name=None):
    arr = np.lib.stride_tricks.as_strided(
        np.asarray(_t(x)._value).reshape(-1)[offset:],
        shape=_static_shape(shape),
        strides=tuple(int(s) * np.dtype(_t(x).dtype).itemsize for s in stride),
    )
    return Tensor(jnp.asarray(arr))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return _t(x).astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, _t(other).shape)


def unfold(x, axis, size, step, name=None):
    xx = _t(x)
    ax = int(axis) % xx.ndim
    dim = xx.shape[ax]
    n_windows = (dim - size) // step + 1

    def fn(a):
        idx = jnp.arange(n_windows)[:, None] * step + jnp.arange(size)[None, :]
        out = jnp.take(a, idx.reshape(-1), axis=ax)
        new_shape = a.shape[:ax] + (n_windows, size) + a.shape[ax + 1:]
        out = out.reshape(new_shape)
        return jnp.moveaxis(out, ax + 1, -1) if ax + 1 != out.ndim - 1 else out

    return run_op("unfold", fn, [xx])


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, Tensor):
        axes = axes.tolist()
    return run_op("tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes), [_t(x), _t(y)])


def atleast_1d(*inputs, name=None):
    outs = [reshape(_t(v), [1]) if _t(v).ndim == 0 else _t(v) for v in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = []
    for v in inputs:
        vv = _t(v)
        while vv.ndim < 2:
            vv = unsqueeze(vv, 0)
        outs.append(vv)
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = []
    for v in inputs:
        vv = _t(v)
        while vv.ndim < 3:
            vv = unsqueeze(vv, -1) if vv.ndim >= 2 else unsqueeze(vv, 0)
        outs.append(vv)
    return outs[0] if len(outs) == 1 else outs


def tolist(x):
    # registered as the Tensor method below, so it must not dispatch back
    # through `.tolist()` (infinite recursion — found by the graftlint
    # runtime suite); .numpy() keeps the host-sync observer in the loop
    return _t(x).numpy().tolist()


def crop(x, shape=None, offsets=None, name=None):
    xx = _t(x)
    shp = _static_shape(shape) if shape is not None else xx.shape
    offs = [0] * xx.ndim if offsets is None else [int(o) for o in offsets]
    axes = list(range(xx.ndim))
    starts = offs
    ends = [o + (s if s != -1 else xx.shape[i] - o) for i, (o, s) in enumerate(zip(offs, shp))]
    return slice(xx, axes, starts, ends)


_SKIP = {"slice", "t", "view", "view_as", "tolist"}
for _name in __all__:
    if _name not in _SKIP:
        register_tensor_method(_name, globals()[_name])
register_tensor_method("tolist", tolist)
register_tensor_method("t", t)
register_tensor_method("view", view)
register_tensor_method("view_as", view_as)
