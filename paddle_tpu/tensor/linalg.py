"""Linear algebra ops (reference: python/paddle/tensor/linalg.py).

matmul and friends lower to jnp/lax dot_general — XLA tiles these onto the MXU;
`preferred_element_type` keeps bf16 inputs accumulating in f32 like the
reference's cublas GEMM with FP32 compute type.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, register_tensor_method, run_op, to_tensor

__all__ = [
    "matmul",
    "mm",
    "bmm",
    "dot",
    "mv",
    "norm",
    "dist",
    "cross",
    "cholesky",
    "cholesky_solve",
    "inverse",
    "pinv",
    "det",
    "slogdet",
    "matrix_rank",
    "matrix_power",
    "qr",
    "svd",
    "eig",
    "eigh",
    "eigvals",
    "eigvalsh",
    "solve",
    "triangular_solve",
    "lstsq",
    "lu",
    "histogram",
    "bincount",
    "cov",
    "corrcoef",
    "einsum",
    "svdvals",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        out_dtype = jnp.promote_types(a.dtype, b.dtype)
        acc = jnp.float32 if out_dtype in (jnp.bfloat16, jnp.float16) else None
        out = jnp.matmul(a, b, preferred_element_type=acc)
        return out.astype(out_dtype)

    return run_op("matmul", fn, [_t(x), _t(y)])


def mm(input, mat2, name=None):  # noqa: A002
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return run_op("dot", lambda a, b: jnp.sum(a * b, axis=-1), [_t(x), _t(y)])


def mv(x, vec, name=None):
    return matmul(x, vec)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    if p is None:
        p = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2

    def fn(a):
        if p == "fro":
            ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
            return jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdim))
        if p == np.inf or p == "inf":
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == -np.inf:
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)

    return run_op("norm", fn, [_t(x)])


def dist(x, y, p=2, name=None):
    return norm(_t(x) - _t(y), p=p)


def cross(x, y, axis=9, name=None):
    def fn(a, b):
        ax = axis
        if ax == 9:
            ax = next((i for i, s in enumerate(a.shape) if s == 3), -1)
        return jnp.cross(a, b, axis=ax)

    return run_op("cross", fn, [_t(x), _t(y)])


def cholesky(x, upper=False, name=None):
    def fn(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return run_op("cholesky", fn, [_t(x)])


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, L):
        Lm = jnp.swapaxes(L, -1, -2) if upper else L
        z = jax.scipy.linalg.solve_triangular(Lm, b, lower=True)
        return jax.scipy.linalg.solve_triangular(jnp.swapaxes(Lm, -1, -2), z, lower=False)

    return run_op("cholesky_solve", fn, [_t(x), _t(y)])


def inverse(x, name=None):
    return run_op("inverse", jnp.linalg.inv, [_t(x)])


inv = inverse


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return run_op("pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), [_t(x)])


def det(x, name=None):
    return run_op("det", jnp.linalg.det, [_t(x)])


def slogdet(x, name=None):
    outs = run_op("slogdet", lambda a: tuple(jnp.linalg.slogdet(a)), [_t(x)])
    return run_op("stack_slogdet", lambda s, l: jnp.stack([s, l]), list(outs))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return run_op(
        "matrix_rank",
        lambda a: jnp.linalg.matrix_rank(a, tol=tol).astype(jnp.int32),
        [_t(x)],
    )


def matrix_power(x, n, name=None):
    return run_op("matrix_power", lambda a: jnp.linalg.matrix_power(a, int(n)), [_t(x)])


def qr(x, mode="reduced", name=None):
    outs = run_op("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), [_t(x)]) \
        if mode != "r" else (run_op("qr_r", lambda a: jnp.linalg.qr(a, mode="r"), [_t(x)]),)
    return outs if len(outs) > 1 else outs[0]


def svd(x, full_matrices=False, name=None):
    outs = run_op(
        "svd", lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), [_t(x)]
    )
    return outs


def eig(x, name=None):
    vals, vecs = np.linalg.eig(np.asarray(_t(x)._value))
    return Tensor(jnp.asarray(vals)), Tensor(jnp.asarray(vecs))


def eigh(x, UPLO="L", name=None):
    outs = run_op("eigh", lambda a: tuple(jnp.linalg.eigh(a, symmetrize_input=True)), [_t(x)])
    return outs[0], outs[1]


def eigvals(x, name=None):
    vals = np.linalg.eigvals(np.asarray(_t(x)._value))
    return Tensor(jnp.asarray(vals))


def eigvalsh(x, UPLO="L", name=None):
    return run_op("eigvalsh", lambda a: jnp.linalg.eigvalsh(a), [_t(x)])


def solve(x, y, name=None):
    def fn(a, b):
        if b.ndim == a.ndim - 1:
            return jnp.linalg.solve(a, b[..., None])[..., 0]
        return jnp.linalg.solve(a, b)

    return run_op("solve", fn, [_t(x), _t(y)])


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular,
        )

    return run_op("triangular_solve", fn, [_t(x), _t(y)])


def lstsq(x, y, rcond=None, driver=None, name=None):
    def fn(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank.astype(jnp.int32), sv

    return run_op("lstsq", fn, [_t(x), _t(y)])


def lu(x, pivot=True, get_infos=False, name=None):
    def fn(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, (piv + 1).astype(jnp.int32)

    lu_t, piv_t = run_op("lu", fn, [_t(x)])
    if get_infos:
        return lu_t, piv_t, Tensor(jnp.zeros((), jnp.int32))
    return lu_t, piv_t


def histogram(input, bins=100, min=0, max=0, name=None):  # noqa: A002
    arr = np.asarray(_t(input)._value)
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    hist, _ = np.histogram(arr, bins=bins, range=(lo, hi))
    return Tensor(jnp.asarray(hist.astype(np.int32)))


def bincount(x, weights=None, minlength=0, name=None):
    xx = _t(x)
    n = int(np.asarray(xx._value).max()) + 1 if xx.size else 0
    length = max(n, minlength)
    if weights is None:
        return run_op(
            "bincount",
            lambda a: jnp.bincount(a.astype(jnp.int32), length=length),
            [xx],
        )
    return run_op(
        "bincount",
        lambda a, w: jnp.bincount(a.astype(jnp.int32), weights=w, length=length),
        [xx, _t(weights)],
    )


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return run_op(
        "cov",
        lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0),
        [_t(x)],
    )


def corrcoef(x, rowvar=True, name=None):
    return run_op("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), [_t(x)])


def einsum(equation, *operands):
    ts = [_t(o) for o in operands]
    return run_op("einsum", lambda *vs: jnp.einsum(equation, *vs), ts)


# ----------------------------------------------------------------------- #
# linalg tail (reference: python/paddle/tensor/linalg.py cond :? ,
# matrix_exp, vector_norm/matrix_norm, householder_product :?, ormqr,
# svd_lowrank/pca_lowrank — randomized low-rank per Halko et al. 2011)
# ----------------------------------------------------------------------- #


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    def fn(v):
        v = v.astype(jnp.float32)
        if p == float("inf"):
            out = jnp.max(jnp.abs(v), axis=axis, keepdims=keepdim)
        elif p == float("-inf"):
            out = jnp.min(jnp.abs(v), axis=axis, keepdims=keepdim)
        elif p == 0:
            out = jnp.sum(v != 0, axis=axis, keepdims=keepdim).astype(
                jnp.float32)
        else:
            out = jnp.sum(jnp.abs(v) ** p, axis=axis,
                          keepdims=keepdim) ** (1.0 / p)
        return out

    return run_op("vector_norm", fn, [_t(x)])


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    def fn(v):
        v32 = v.astype(jnp.float32)
        # normalize to the last-two-dims layout so every p below (including
        # the SVD-based ones) reduces the requested axes
        a0 = axis[0] % v32.ndim
        a1 = axis[1] % v32.ndim
        v32 = jnp.moveaxis(v32, (a0, a1), (-2, -1))
        if p == "fro":
            out = jnp.sqrt(jnp.sum(v32 * v32, axis=(-2, -1)))
        elif p == "nuc":
            s = jnp.linalg.svd(v32, compute_uv=False)
            out = s.sum(-1)
        elif p in (1, 1.0):
            out = jnp.max(jnp.sum(jnp.abs(v32), axis=-2), axis=-1)
        elif p in (np.inf, float("inf")):
            out = jnp.max(jnp.sum(jnp.abs(v32), axis=-1), axis=-1)
        elif p in (2, 2.0):
            s = jnp.linalg.svd(v32, compute_uv=False)
            out = s.max(-1)
        else:
            raise ValueError(f"unsupported matrix norm order {p!r}")
        if keepdim:
            out = jnp.expand_dims(jnp.expand_dims(out, a1 if a1 < a0 else a0),
                                  a0 if a1 < a0 else a1)
        return out

    return run_op("matrix_norm", fn, [_t(x)])


def cond(x, p=None, name=None):
    """reference: linalg.cond — ||A|| * ||A^-1|| (2-norm default via
    singular values)."""
    def fn(v):
        v32 = v.astype(jnp.float32)
        if p is None or p in (2, 2.0):
            s = jnp.linalg.svd(v32, compute_uv=False)
            return s.max(-1) / s.min(-1)
        if p == "fro":
            inv = jnp.linalg.inv(v32)
            return (jnp.sqrt((v32 * v32).sum((-2, -1)))
                    * jnp.sqrt((inv * inv).sum((-2, -1))))
        if p in (np.inf, float("inf"), 1, 1.0):
            # 1-norm = max column sum (reduce rows, axis -2);
            # inf-norm = max row sum (reduce columns, axis -1)
            ax = -2 if p in (1, 1.0) else -1
            inv = jnp.linalg.inv(v32)
            return (jnp.abs(v32).sum(ax).max(-1)
                    * jnp.abs(inv).sum(ax).max(-1))
        raise ValueError(f"unsupported cond order {p!r}")

    return run_op("cond", fn, [_t(x)])


def matrix_exp(x, name=None):
    from jax.scipy.linalg import expm

    return run_op("matrix_exp", lambda v: expm(v.astype(jnp.float32)),
                  [_t(x)])


def vecdot(x, y, axis=-1, name=None):
    return run_op("vecdot",
                  lambda a, b: jnp.sum(a * b, axis=axis), [_t(x), _t(y)])


def _householder_q_full(a, t):
    """Full m x m Q = H_0 H_1 ... from reflectors in a's lower triangle
    (LAPACK orgqr accumulation)."""
    m = a.shape[-2]
    k = t.shape[-1]  # number of reflectors = tau length (may be < n)
    ident = jnp.eye(m, dtype=a.dtype)
    q = jnp.broadcast_to(ident, a.shape[:-2] + (m, m))

    def body(i, q):
        v = jnp.where(jnp.arange(m) > i, a[..., :, i], 0.0)
        v = v.at[..., i].set(1.0)
        vv = v[..., :, None] * v[..., None, :]
        h = ident - t[..., i][..., None, None] * vv
        return q @ h

    return jax.lax.fori_loop(0, k, body, q)


def householder_product(x, tau, name=None):
    """reference: linalg.householder_product (LAPACK orgqr) — the first n
    columns of the accumulated Q."""
    def fn(a, t):
        return _householder_q_full(a, t)[..., :, :a.shape[-1]]

    return run_op("householder_product", fn, [_t(x), _t(tau)])


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """reference: linalg.ormqr — multiply `other` by the FULL Q of a QR
    factorization (LAPACK ormqr semantics: other is [m, k] for left)."""
    def fn(a, t, ov):
        q = _householder_q_full(a, t)
        qm = jnp.swapaxes(q, -2, -1) if transpose else q
        return qm @ ov if left else ov @ qm

    return run_op("ormqr", fn, [_t(x), _t(tau), _t(other)])


def _lowrank(v, q, key, niter=2):
    """Randomized range finder (Halko et al. 2011) — the reference's
    svd_lowrank/pca_lowrank backbone; all dense matmuls (MXU-native)."""
    m, n = v.shape[-2], v.shape[-1]
    omega = jax.random.normal(key, v.shape[:-2] + (n, q), v.dtype)
    y = v @ omega
    for _ in range(niter):
        y = v @ (jnp.swapaxes(v, -2, -1) @ y)
    qmat, _ = jnp.linalg.qr(y)
    b = jnp.swapaxes(qmat, -2, -1) @ v
    u, s, vt = jnp.linalg.svd(b, full_matrices=False)
    return qmat @ u, s, jnp.swapaxes(vt, -2, -1)


def svdvals(x, name=None):
    """Singular values only (reference tensor/linalg.py svdvals; ops.yaml
    svdvals)."""
    return run_op("svdvals",
                  lambda a: jnp.linalg.svd(a, compute_uv=False), [x])


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    from ..framework import random as _rnd

    key = _rnd.next_key()

    def fn(v, *rest):
        vv = v.astype(jnp.float32)
        if rest:
            vv = vv - rest[0].astype(jnp.float32)
        return _lowrank(vv, min(q, min(vv.shape[-2:])), key, niter)

    ins = [_t(x)] + ([_t(M)] if M is not None else [])
    return run_op("svd_lowrank", fn, ins, n_outputs=3)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    from ..framework import random as _rnd

    key = _rnd.next_key()

    def fn(v):
        vv = v.astype(jnp.float32)
        if center:
            vv = vv - vv.mean(-2, keepdims=True)
        k = q if q is not None else min(6, *vv.shape[-2:])
        return _lowrank(vv, min(k, min(vv.shape[-2:])), key, niter)

    return run_op("pca_lowrank", fn, [_t(x)], n_outputs=3)


__all__ += ["vector_norm", "matrix_norm", "cond", "matrix_exp", "vecdot",
            "householder_product", "ormqr", "svd_lowrank", "pca_lowrank"]

# aliases living elsewhere in the tensor namespace (reference exports them
# from linalg too)
from .extras import lu_unpack, matrix_transpose, multi_dot  # noqa: E402,F401

__all__ += ["lu_unpack", "matrix_transpose", "multi_dot"]

for _name in __all__:
    if _name not in ("einsum", "lu_unpack", "matrix_transpose", "multi_dot"):
        register_tensor_method(_name, globals()[_name])
