"""Comparison / logical ops (reference: python/paddle/tensor/logic.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, register_tensor_method, run_op, to_tensor

__all__ = [
    "equal",
    "not_equal",
    "greater_than",
    "greater_equal",
    "less_than",
    "less_equal",
    "equal_all",
    "allclose",
    "isclose",
    "logical_and",
    "logical_or",
    "logical_not",
    "logical_xor",
    "bitwise_and",
    "bitwise_or",
    "bitwise_not",
    "bitwise_xor",
    "bitwise_left_shift",
    "bitwise_right_shift",
    "is_empty",
    "is_tensor",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _make(name, jfn, n=2):
    if n == 2:
        def op(x, y, name=None):
            return run_op(op.__name__, jfn, [_t(x), _t(y)])
    else:
        def op(x, name=None):
            return run_op(op.__name__, jfn, [_t(x)])
    op.__name__ = name
    return op


equal = _make("equal", jnp.equal)
not_equal = _make("not_equal", jnp.not_equal)
greater_than = _make("greater_than", jnp.greater)
greater_equal = _make("greater_equal", jnp.greater_equal)
less_than = _make("less_than", jnp.less)
less_equal = _make("less_equal", jnp.less_equal)
logical_and = _make("logical_and", jnp.logical_and)
logical_or = _make("logical_or", jnp.logical_or)
logical_xor = _make("logical_xor", jnp.logical_xor)
logical_not = _make("logical_not", jnp.logical_not, n=1)
bitwise_and = _make("bitwise_and", lambda a, b: a & b)
bitwise_or = _make("bitwise_or", lambda a, b: a | b)
bitwise_xor = _make("bitwise_xor", lambda a, b: a ^ b)
bitwise_not = _make("bitwise_not", lambda a: ~a, n=1)
bitwise_left_shift = _make("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _make("bitwise_right_shift", jnp.right_shift)


def equal_all(x, y, name=None):
    return run_op("equal_all", lambda a, b: jnp.array_equal(a, b), [_t(x), _t(y)])


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return run_op(
        "allclose",
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        [_t(x), _t(y)],
    )


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return run_op(
        "isclose",
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        [_t(x), _t(y)],
    )


def is_empty(x, name=None):
    return Tensor(jnp.asarray(_t(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


for _name in __all__:
    if _name != "is_tensor":
        register_tensor_method(_name, globals()[_name])
