"""Profiler subsystem (reference: python/paddle/profiler/profiler.py:358
Profiler, :89 ProfilerState, :129 make_scheduler, :227 export_chrome_tracing;
chrome-trace writer paddle/fluid/platform/profiler/chrometracing_logger.cc;
RecordEvent python/paddle/profiler/utils.py).

TPU-native split: host-side events (eager op dispatch spans via the run_op
hook, user RecordEvent annotations, dataloader/step timing) are collected by
this package and exported as chrome-trace JSON + summary tables — the analog
of the reference's CPU RecordEvent stream. Device-side timelines come from
XLA's own profiler: pass `device_trace_dir` (or use targets containing
ProfilerTarget.TPU with on_trace_ready=export_chrome_tracing(dir)) and the
Profiler brackets each RECORD window with jax.profiler.start_trace/
stop_trace, producing an XPlane/perfetto trace viewable in XProf — replacing
the reference's CUPTI tracer (paddle/fluid/platform/profiler/cuda_tracer.cc).
RecordEvent doubles as a jax.profiler.TraceAnnotation so host annotations
appear on the device timeline too.
"""

from .profiler import (
    Profiler,
    ProfilerState,
    ProfilerTarget,
    RecordEvent,
    SummaryView,
    export_chrome_tracing,
    export_protobuf,
    load_profiler_result,
    make_scheduler,
)
from .timer import benchmark

__all__ = [
    "Profiler",
    "ProfilerState",
    "ProfilerTarget",
    "RecordEvent",
    "SummaryView",
    "benchmark",
    "export_chrome_tracing",
    "export_protobuf",
    "load_profiler_result",
    "make_scheduler",
]
