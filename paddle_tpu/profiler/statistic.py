"""Summary statistics over the host event stream and the device trace
(reference: python/paddle/profiler/profiler_statistic.py — per-op
aggregation and the formatted summary tables: Overview Summary, Operator
Summary, Kernel Summary).

TPU mapping: host-side op dispatch spans come from the run_op event hook
(the reference's RecordEvent stream); device-side kernel times come from
the XLA/TPU chrome trace that jax.profiler captures into
`device_trace_dir` — the analog of the reference's CUPTI kernel records.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from collections import defaultdict

_UNIT = {"s": 1e-9, "ms": 1e-6, "us": 1e-3, "ns": 1.0}


def aggregate(events):
    """name -> dict(calls, total_ns, avg_ns, min_ns, max_ns, cat)."""
    agg = {}
    for e in events:
        d = agg.get(e.name)
        dur = e.end_ns - e.start_ns
        if d is None:
            agg[e.name] = d = dict(calls=0, total=0, mn=None, mx=0, cat=e.cat)
        d["calls"] += 1
        d["total"] += dur
        d["mn"] = dur if d["mn"] is None else min(d["mn"], dur)
        d["mx"] = max(d["mx"], dur)
    return agg


def _table(title, rows, width, scale, time_unit, grand):
    """rows: [(name, dict)] sorted; returns formatted lines."""
    out = []
    out.append(f"\n{'-' * (width + 58)}")
    out.append(f"{title}   (time unit: {time_unit})")
    out.append(f"{'-' * (width + 58)}")
    out.append(f"{'Name'.ljust(width)}  {'Calls':>7}  {'Total':>10}  "
               f"{'Avg':>10}  {'Min':>10}  {'Max':>10}  {'Ratio':>6}")
    for name, d in rows:
        t, c = d["total"], d["calls"]
        out.append(
            f"{name.ljust(width)}  {c:>7}  {t * scale:>10.3f}  "
            f"{t / c * scale:>10.3f}  {d['mn'] * scale:>10.3f}  "
            f"{d['mx'] * scale:>10.3f}  {t / grand:>6.1%}")
    return out


def build_overview(events, time_unit="ms"):
    """Overview Summary: time per event category (reference
    profiler_statistic.py overview table)."""
    scale = _UNIT.get(time_unit, 1e-6)
    by_cat = defaultdict(lambda: dict(calls=0, total=0, mn=None, mx=0))
    for e in events:
        d = by_cat[e.cat]
        dur = e.end_ns - e.start_ns
        d["calls"] += 1
        d["total"] += dur
        d["mn"] = dur if d["mn"] is None else min(d["mn"], dur)
        d["mx"] = max(d["mx"], dur)
    if not by_cat:
        return []
    grand = sum(d["total"] for d in by_cat.values()) or 1
    rows = sorted(by_cat.items(), key=lambda kv: -kv[1]["total"])
    width = max([len(c) for c in by_cat] + [20])
    return _table("Overview Summary", rows, width, scale, time_unit, grand)


def find_device_trace(trace_dir):
    """Latest XLA chrome trace under a jax.profiler trace dir (it writes
    plugins/profile/<ts>/<host>.trace.json.gz)."""
    pats = [os.path.join(trace_dir, "**", "*.trace.json.gz"),
            os.path.join(trace_dir, "**", "*.trace.json"),
            os.path.join(trace_dir, "*.json.gz"),
            os.path.join(trace_dir, "*.json")]
    cands = []
    for p in pats:
        cands.extend(glob.glob(p, recursive=True))
    if not cands:
        return None
    return max(cands, key=os.path.getmtime)


def parse_device_trace(path, max_ops=None):
    """Aggregate device-track complete events from a chrome trace.

    Returns name -> dict(calls, total_ns, mn, mx, cat="kernel"). Device
    tracks are processes whose metadata name mentions a device ("/device:",
    "TPU", "GPU"); within them, XLA op events carry `dur` in us.
    """
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", data if isinstance(data, list) else [])
    dev_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname = str(e.get("args", {}).get("name", ""))
            if ("/device:" in pname or "TPU" in pname or "GPU" in pname
                    or pname.startswith("Device")):
                dev_pids.add(e.get("pid"))
    agg = {}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "?")
        dur_ns = float(e.get("dur", 0)) * 1e3  # chrome trace dur is us
        d = agg.get(name)
        if d is None:
            agg[name] = d = dict(calls=0, total=0.0, mn=None, mx=0.0,
                                 cat="kernel")
        d["calls"] += 1
        d["total"] += dur_ns
        d["mn"] = dur_ns if d["mn"] is None else min(d["mn"], dur_ns)
        d["mx"] = max(d["mx"], dur_ns)
    if max_ops is not None and len(agg) > max_ops:
        top = sorted(agg.items(), key=lambda kv: -kv[1]["total"])[:max_ops]
        agg = dict(top)
    return agg


def build_device_summary(trace_dir, time_unit="ms", max_ops=30):
    """Kernel Summary from the captured device trace (reference
    profiler_statistic.py kernel table over CUPTI records)."""
    scale = _UNIT.get(time_unit, 1e-6)
    path = find_device_trace(trace_dir) if trace_dir else None
    if path is None:
        return []
    try:
        agg = parse_device_trace(path, max_ops=max_ops)
    except Exception:
        return []
    if not agg:
        return []
    grand = sum(d["total"] for d in agg.values()) or 1
    rows = sorted(agg.items(), key=lambda kv: -kv[1]["total"])
    width = min(max([len(n) for n in agg] + [20]), 60)
    rows = [(n[:width], d) for n, d in rows]
    return _table(f"Kernel Summary (device, top {len(rows)})", rows, width,
                  scale, time_unit, grand)


def build_summary(events, time_unit="ms", device_trace_dir=None):
    """Formatted tables: Overview + per-category host ops + device kernels,
    sorted by total time (reference profiler_statistic.py _build_table)."""
    scale = _UNIT.get(time_unit, 1e-6)
    agg = aggregate(events)
    dev = build_device_summary(device_trace_dir, time_unit)
    if not agg and not dev:
        return "no profiler events recorded"
    out = []
    out.extend(build_overview(events, time_unit))
    by_cat = defaultdict(list)
    for name, d in agg.items():
        by_cat[d["cat"]].append((name, d))
    grand = sum(d["total"] for d in agg.values()) or 1
    width = max([len(n) for n in agg] + [20]) if agg else 20
    for cat in sorted(by_cat):
        rows = sorted(by_cat[cat], key=lambda kv: -kv[1]["total"])
        out.extend(_table(f"Category: {cat}", rows, width, scale, time_unit,
                          grand))
    out.extend(dev)
    return "\n".join(out)
