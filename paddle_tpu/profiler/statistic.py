"""Summary statistics over the host event stream (reference:
python/paddle/profiler/profiler_statistic.py — per-op aggregation and the
formatted summary tables)."""

from __future__ import annotations

from collections import defaultdict

_UNIT = {"s": 1e-9, "ms": 1e-6, "us": 1e-3, "ns": 1.0}


def aggregate(events):
    """name -> dict(calls, total_ns, avg_ns, min_ns, max_ns, cat)."""
    agg = {}
    for e in events:
        d = agg.get(e.name)
        dur = e.end_ns - e.start_ns
        if d is None:
            agg[e.name] = d = dict(calls=0, total=0, mn=None, mx=0, cat=e.cat)
        d["calls"] += 1
        d["total"] += dur
        d["mn"] = dur if d["mn"] is None else min(d["mn"], dur)
        d["mx"] = max(d["mx"], dur)
    return agg


def build_summary(events, time_unit="ms"):
    """Formatted per-category tables sorted by total time (reference
    profiler_statistic.py _build_table)."""
    scale = _UNIT.get(time_unit, 1e-6)
    agg = aggregate(events)
    if not agg:
        return "no profiler events recorded"
    by_cat = defaultdict(list)
    for name, d in agg.items():
        by_cat[d["cat"]].append((name, d))
    grand = sum(d["total"] for d in agg.values()) or 1

    out = []
    width = max([len(n) for n in agg] + [20])
    for cat in sorted(by_cat):
        rows = sorted(by_cat[cat], key=lambda kv: -kv[1]["total"])
        out.append(f"\n{'-' * (width + 58)}")
        out.append(f"Category: {cat}   (time unit: {time_unit})")
        out.append(f"{'-' * (width + 58)}")
        out.append(f"{'Name'.ljust(width)}  {'Calls':>7}  {'Total':>10}  "
                   f"{'Avg':>10}  {'Min':>10}  {'Max':>10}  {'Ratio':>6}")
        for name, d in rows:
            t, c = d["total"], d["calls"]
            out.append(
                f"{name.ljust(width)}  {c:>7}  {t * scale:>10.3f}  "
                f"{t / c * scale:>10.3f}  {d['mn'] * scale:>10.3f}  "
                f"{d['mx'] * scale:>10.3f}  {t / grand:>6.1%}")
    return "\n".join(out)
