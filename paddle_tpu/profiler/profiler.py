"""Profiler core (reference: python/paddle/profiler/profiler.py — state
machine :89-225, Profiler :358-900, chrome-trace export :227; host event
recording python/paddle/profiler/utils.py RecordEvent)."""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional

__all__ = [
    "Profiler",
    "ProfilerState",
    "ProfilerTarget",
    "RecordEvent",
    "SummaryView",
    "export_chrome_tracing",
    "export_protobuf",
    "load_profiler_result",
    "make_scheduler",
]


class ProfilerState(Enum):
    """reference profiler.py:89 — CLOSED/READY/RECORD/RECORD_AND_RETURN."""

    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    """reference profiler.py:110 (CPU/GPU/XPU/CUSTOM_DEVICE) — the device
    target here is the TPU via XLA's profiler."""

    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class SummaryView(Enum):
    """reference profiler.py:55."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """State scheduler: skip_first CLOSED steps, then cycles of
    closed→ready→record (last record step RECORD_AND_RETURN), `repeat` times
    (0 = forever). reference profiler.py:129."""
    assert (closed >= 0 and ready >= 0 and record > 0 and repeat >= 0
            and skip_first >= 0), "Invalid profiler scheduler arguments"

    def schedule(step: int) -> ProfilerState:
        assert step >= 0
        if step < skip_first:
            return ProfilerState.CLOSED
        step = step - skip_first
        period = closed + ready + record
        if repeat > 0 and step // period >= repeat:
            return ProfilerState.CLOSED
        mod = step % period
        if mod < closed:
            return ProfilerState.CLOSED
        if mod < closed + ready:
            return ProfilerState.READY
        if mod < period - 1:
            return ProfilerState.RECORD
        return ProfilerState.RECORD_AND_RETURN

    return schedule


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None
                          ) -> Callable[["Profiler"], None]:
    """on_trace_ready factory writing chrome-trace JSON per profiling window
    (reference profiler.py:227)."""
    os.makedirs(dir_name, exist_ok=True)

    def handle(prof: "Profiler") -> None:
        name = worker_name or f"{socket.gethostname()}_pid{os.getpid()}"
        path = os.path.join(
            dir_name, f"{name}_time_{int(time.time() * 1000)}.paddle_trace.json")
        prof.export(path, format="json")

    return handle


def export_protobuf(dir_name: str, worker_name: Optional[str] = None
                    ) -> Callable[["Profiler"], None]:
    """reference profiler.py:280 — here an alias of the JSON exporter (the
    chrome-trace JSON is the interchange format; XPlane protobufs come from
    the device_trace_dir jax.profiler output)."""
    return export_chrome_tracing(dir_name, worker_name)


def load_profiler_result(filename: str) -> dict:
    """Load an exported chrome-trace JSON (reference load_profiler_result)."""
    with open(filename) as f:
        return json.load(f)


class _HostEvent:
    __slots__ = ("name", "start_ns", "end_ns", "tid", "cat")

    def __init__(self, name, start_ns, end_ns, tid, cat):
        self.name = name
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.tid = tid
        self.cat = cat


_active_profiler: Optional["Profiler"] = None


class RecordEvent:
    """Host annotation context manager (reference python/paddle/profiler/
    utils.py RecordEvent). Recorded into the active Profiler's host stream
    and, when a device trace is running, mirrored as a
    jax.profiler.TraceAnnotation so it shows up on the XLA timeline."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None
        self._ann = None

    def begin(self):
        self._t0 = time.perf_counter_ns()
        try:
            import jax

            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        prof = _active_profiler
        if prof is not None and self._t0 is not None and prof._recording:
            prof._add_event(self.name, self._t0, time.perf_counter_ns(),
                            cat="user_defined")
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """reference profiler.py:358.

    Usage:
        with Profiler(scheduler=(2, 5),
                      on_trace_ready=export_chrome_tracing('./log')) as p:
            for batch in loader:
                train_step(batch)
                p.step()
        print(p.summary())
    """

    def __init__(self, *, targets: Optional[Iterable[ProfilerTarget]] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 record_op_events: bool = True, timer_only: bool = False,
                 device_trace_dir: Optional[str] = None,
                 emit_nvtx: bool = False):
        self.targets = list(targets) if targets is not None else [
            ProfilerTarget.CPU, ProfilerTarget.TPU]
        if scheduler is None:
            self.scheduler = _default_scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self.scheduler = make_scheduler(
                closed=max(start - 1, 0), ready=min(start, 1),
                record=end - start, repeat=1)
        else:
            self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.record_op_events = record_op_events
        self.timer_only = timer_only
        self.device_trace_dir = device_trace_dir
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._events: list[_HostEvent] = []
        self._recording = False
        self._device_tracing = False
        self._step_times: list[float] = []
        self._last_step_t = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ #

    def _add_event(self, name, t0, t1, cat):
        with self._lock:
            self._events.append(_HostEvent(
                name, t0, t1, threading.get_ident(), cat))

    def _op_hook(self, name, t0, t1):
        self._add_event(name, t0, t1, cat="operator")

    def _begin_record(self):
        if self._recording:
            return
        self._recording = True
        self._events = []  # each record window exports only its own events
        if self.timer_only:
            return
        if self.record_op_events:
            from ..framework.core import set_op_event_hook

            set_op_event_hook(self._op_hook)
        if (self.device_trace_dir is not None
                and ProfilerTarget.TPU in self.targets):
            try:
                import jax

                jax.profiler.start_trace(self.device_trace_dir)
                self._device_tracing = True
            except Exception:
                self._device_tracing = False

    def _end_record(self):
        if not self._recording:
            return
        self._recording = False
        if self.record_op_events and not self.timer_only:
            from ..framework.core import set_op_event_hook

            set_op_event_hook(None)
        if self._device_tracing:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_tracing = False

    def _transition(self, new_state):
        old = self.current_state
        self.current_state = new_state
        recording = new_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN)
        was = old in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if recording and not was:
            self._begin_record()
        if old == ProfilerState.RECORD_AND_RETURN:
            # window complete: flush to the handler, then resume/close
            self._end_record()
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
            if recording:
                self._begin_record()
        elif was and not recording:
            self._end_record()

    # ------------------------------------------------------------------ #

    def start(self) -> None:
        global _active_profiler
        _active_profiler = self
        self.step_num = 0
        self._last_step_t = time.perf_counter()
        self._transition(self.scheduler(0))

    def stop(self) -> None:
        """Flush any in-flight record window (the reference invokes the
        trace handler on stop whenever the profiler is recording)."""
        global _active_profiler
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._end_record()
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
            self.current_state = ProfilerState.CLOSED
        else:
            self._end_record()
        if _active_profiler is self:
            _active_profiler = None

    def step(self, num_samples: Optional[int] = None) -> None:
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self.step_num += 1
        self._transition(self.scheduler(self.step_num))

    def step_info(self, unit: Optional[str] = None) -> str:
        """Throughput line for the recent steps (reference :735)."""
        if not self._step_times:
            return "no steps recorded"
        import numpy as np

        ts = np.asarray(self._step_times[-20:])
        ips = 1.0 / ts.mean() if ts.mean() > 0 else float("inf")
        return (f"batch_cost: {ts.mean():.5f} s, ips: {ips:.3f} "
                f"{unit or 'steps'}/s")

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------------ #

    def export(self, path: str = "", format: str = "json") -> None:
        """Write the host event stream as chrome-trace JSON (reference :853;
        chrometracing_logger.cc format)."""
        events = []
        pid = os.getpid()
        for e in self._events:
            events.append({
                "name": e.name, "ph": "X", "cat": e.cat,
                "ts": e.start_ns / 1000.0,
                "dur": (e.end_ns - e.start_ns) / 1000.0,
                "pid": pid, "tid": e.tid,
            })
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {"producer": "paddle_tpu.profiler",
                         "host": socket.gethostname()},
        }
        with open(path, "w") as f:
            json.dump(doc, f)

    def events(self):
        return list(self._events)

    def summary(self, sorted_by=None, op_detail: bool = True,
                thread_sep: bool = False, time_unit: str = "ms",
                views=None) -> str:
        """Overview + per-op host tables + device Kernel Summary parsed from
        the captured XLA trace (reference :883 backed by
        profiler_statistic.py's overview/operator/kernel tables)."""
        from .statistic import build_summary

        return build_summary(self._events, time_unit=time_unit,
                             device_trace_dir=self.device_trace_dir)
