"""Throughput benchmark timer (reference: python/paddle/profiler/timer.py —
Benchmark with reader_cost / batch_cost / ips, hooked by hapi and the
DataLoader)."""

from __future__ import annotations

import time

__all__ = ["benchmark", "Benchmark"]


class _Window:
    def __init__(self, cap=50):
        self.cap = cap
        self.vals = []

    def add(self, v):
        self.vals.append(v)
        if len(self.vals) > self.cap:
            self.vals.pop(0)

    @property
    def avg(self):
        return sum(self.vals) / len(self.vals) if self.vals else 0.0


class Benchmark:
    """Collects reader/batch costs; `ips` = samples (or steps) per second.
    reference timer.py Benchmark; enabled via benchmark().begin()."""

    def __init__(self):
        self.reader = _Window()
        self.batch = _Window()
        self._batch_start = None
        self._reader_done = None
        self.num_samples = None
        self._enabled = False

    # hooks -------------------------------------------------------------- #

    def begin(self):
        self._enabled = True
        self._batch_start = time.perf_counter()

    def before_reader(self):
        pass

    def after_reader(self):
        if not self._enabled or self._batch_start is None:
            return
        dt = time.perf_counter() - self._batch_start
        self.reader.add(dt)
        self._metric().observe(dt, phase="reader")

    def after_step(self, num_samples=None):
        if not self._enabled or self._batch_start is None:
            return
        now = time.perf_counter()
        dt = now - self._batch_start
        self.batch.add(dt)
        self._metric().observe(dt, phase="batch")
        self.num_samples = num_samples
        self._batch_start = now

    def _metric(self):
        """Mirror every window sample into the observability registry so the
        timer's step_info and telemetry exports read the same data (handle
        cached per registry instance — see metrics.HandleCache)."""
        cache = getattr(self, "_metric_cache", None)
        if cache is None:
            from ..observability.metrics import HandleCache

            cache = self._metric_cache = HandleCache(
                lambda reg: reg.histogram(
                    "benchmark_cost_seconds",
                    "timer.Benchmark reader/batch costs", ("phase",)))
        return cache.get()

    def end(self):
        self._enabled = False

    # reporting ---------------------------------------------------------- #

    @property
    def ips(self):
        b = self.batch.avg
        if b <= 0:
            return 0.0
        return (self.num_samples or 1) / b

    def step_info(self, unit="samples"):
        return (f"reader_cost: {self.reader.avg:.5f} s, "
                f"batch_cost: {self.batch.avg:.5f} s, "
                f"ips: {self.ips:.3f} {unit}/s")


_bench = Benchmark()


def benchmark() -> Benchmark:
    """Global Benchmark singleton (reference timer.py benchmark())."""
    return _bench
