"""reference: python/paddle/sysconfig.py — include/lib dirs for building
extensions against the framework (here: the native runtime library)."""

from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    return os.path.join(os.path.dirname(_ROOT), "native")


def get_lib():
    return os.path.join(os.path.dirname(_ROOT), "native")
