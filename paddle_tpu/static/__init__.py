"""paddle.static Program/Executor surface (reference:
python/paddle/base/framework.py Program :5890 / program_guard :7480,
python/paddle/base/executor.py Executor :1256, python/paddle/static/
input.py data, python/paddle/static/nn/common.py fc).

TPU formulation: a Program is a *recorded op trace*. Under program_guard the
eager dispatcher's recorder hook (framework.core.set_op_recorder) appends
every run_op (name, fn, inputs, outputs) to the program while the ops also
execute eagerly on placeholder zeros — construction doubles as shape
inference (the reference's infer-shape pass). Executor.run replays the
recorded ops as ONE pure jax function of the feeds (placeholders bound by
name, parameters read live so optimizer updates are visible) and jits it —
the new-executor + PIR lowering collapse into a jax.jit. Re-running with new
feed shapes retraces; repeated shapes hit the jit cache.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework import core as _core
from ..framework.core import Tensor
from ..framework.dtype import convert_dtype

__all__ = [
    "Program",
    "enable_static",
    "disable_static",
    "in_static_mode",
    "program_guard",
    "default_main_program",
    "default_startup_program",
    "data",
    "InputSpec",
    "Executor",
    "CompiledProgram",
    "Variable",
    "global_scope",
    "scope_guard",
    "name_scope",
    "cpu_places",
    "cuda_places",
    "nn",
    "gradients",
    "Print",
    "Assert",
]

Variable = Tensor  # the one-type design: static Variables ARE Tensors


class Program:
    """reference framework.py:5890 — here a recorded op trace."""

    def __init__(self):
        self._ops = []            # (name, fn, input_entries, output_ids)
        self._placeholders = {}   # feed name -> Tensor (placeholder)
        self._holders = []        # layers created by static.nn.* (param owners)
        self.random_seed = 0

    # ------------------------------------------------------------------ #

    def _record(self, name, fn, inputs, result):
        entries = []
        for i in inputs:
            if isinstance(i, Tensor):
                entries.append(("t", id(i), i))
            else:
                entries.append(("c", np.asarray(i), None))
        outs = result if isinstance(result, (list, tuple)) else [result]
        out_ids = [id(o) for o in outs if isinstance(o, Tensor)]
        # keep the output objects alive so ids stay unique for the program
        self._ops.append((name, fn, entries, out_ids,
                          [o for o in outs if isinstance(o, Tensor)]))

    def global_block(self):
        return self

    @property
    def blocks(self):
        return [self]

    def clone(self, for_test=False):
        p = Program()
        p._ops = list(self._ops)
        p._placeholders = dict(self._placeholders)
        p._holders = list(self._holders)
        return p

    def all_parameters(self):
        params = []
        for h in self._holders:
            params.extend(p for _, p in h.named_parameters())
        return params

    # ------------------------------------------------------------------ #

    def _build_replay(self, fetch_ids):
        """A pure function feeds_dict -> fetches replaying the trace."""
        placeholders = {id(t): name for name, t in self._placeholders.items()}
        ops = self._ops

        def replay(feeds, live_params):
            env = {}
            for pid, fname in placeholders.items():
                env[pid] = feeds[fname]
            env.update(live_params)

            from ..framework.core import tracing_guard

            with tracing_guard(True):
                for name, fn, entries, out_ids, _outs in ops:
                    args = []
                    for kind, a, obj in entries:
                        if kind == "c":
                            args.append(a)
                        else:
                            v = env.get(a)
                            if v is None:
                                # external tensor captured at trace time
                                v = obj._value
                            args.append(v)
                    res = fn(*args)
                    res_list = res if isinstance(res, tuple) else [res]
                    for oid, val in zip(out_ids, res_list):
                        env[oid] = val
            return [env[fid] for fid in fetch_ids]

        return replay

    def _live_param_map(self):
        out = {}
        for h in self._holders:
            for _, p in h.named_parameters():
                out[id(p)] = p._value
        return out


_default_main = Program()
_default_startup = Program()
_current = [_default_main]


def default_main_program():
    """reference framework.py default_main_program."""
    return _default_main


def default_startup_program():
    """reference framework.py default_startup_program — parameter init runs
    eagerly at layer construction here, so the startup program is an empty
    trace kept for API parity."""
    return _default_startup


_static_mode = [False]


def enable_static():
    """reference paddle.enable_static — bare static building (no
    program_guard) records into the default main program."""
    _static_mode[0] = True
    _core.set_op_recorder(_current[-1]._record)


def disable_static():
    _static_mode[0] = False
    if len(_current) == 1:
        _core.set_op_recorder(None)


def in_static_mode():
    return _static_mode[0] or len(_current) > 1


class program_guard:
    """reference framework.py:7480 — routes op recording into `main`."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        _current.append(self.main)
        _core.set_op_recorder(self.main._record)
        return self

    def __exit__(self, *exc):
        _current.pop()
        if len(_current) > 1 or _static_mode[0]:
            _core.set_op_recorder(_current[-1]._record)
        else:
            _core.set_op_recorder(None)
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder (reference python/paddle/static/input.py data).
    None/-1 dims capture as 1; replay binds the real fed shape."""
    shp = tuple(1 if (s is None or int(s) < 0) else int(s) for s in shape)
    t = Tensor(jnp.zeros(shp, convert_dtype(dtype)))
    t.name = name
    prog = _current[-1]
    prog._placeholders[name] = t
    return t


from ..jit import InputSpec  # noqa: E402  (one spec type, shared with jit)
from . import control_flow  # noqa: E402
from .control_flow import Assert, Print, gradients  # noqa: E402


class Executor:
    """reference executor.py:1256 — run() jits the recorded trace."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True):
        program = program or _default_main
        if program is _default_startup or not program._ops:
            return []  # startup: params already initialized eagerly
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_ids = [id(f) for f in fetch_list]

        missing = [n for n in program._placeholders if n not in feed]
        if missing:
            # silent zeros would be plausible-looking garbage; the reference
            # raises on an unfed placeholder (executor.py feed check)
            raise ValueError(
                f"Executor.run: missing feed for placeholder(s) {missing}; "
                f"got feed keys {sorted(feed)}")
        feeds = {}
        for name in program._placeholders:
            v = feed[name]
            feeds[name] = jnp.asarray(
                v._value if isinstance(v, Tensor) else np.asarray(v))

        key = (id(program), tuple(fetch_ids))
        entry = self._cache.get(key)
        if entry is None:
            replay = program._build_replay(fetch_ids)
            entry = jax.jit(replay)
            self._cache[key] = entry
        outs = entry(feeds, program._live_param_map())
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def close(self):
        self._cache.clear()


class CompiledProgram:
    """reference compiler.py CompiledProgram — jit is the compiler; kept as
    a transparent wrapper for API parity."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def __getattr__(self, item):
        return getattr(self.__dict__["_program"], item)


# --------------------------------------------------------------------------- #
# scope / places (API parity)
# --------------------------------------------------------------------------- #


class _Scope:
    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, Tensor(jnp.zeros(())))

    def find_var(self, name):
        return self._vars.get(name)


_global_scope = _Scope()


def global_scope():
    return _global_scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self.scope

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def cpu_places(device_count=None):
    from .. import CPUPlace

    return [CPUPlace()] * (device_count or 1)


def cuda_places(device_ids=None):
    from .. import TPUPlace

    return [TPUPlace()]


# --------------------------------------------------------------------------- #
# static.nn (reference python/paddle/static/nn/common.py)
# --------------------------------------------------------------------------- #


class _StaticNN:
    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None,
           **kwargs):
        """reference static/nn/common.py fc — creates the layer's parameters
        in the current program and applies it."""
        import paddle_tpu.nn as pnn

        in_features = int(np.prod(x.shape[num_flatten_dims:]))
        layer = pnn.Linear(in_features, size)
        prog = _current[-1]
        prog._holders.append(layer)
        h = x
        if len(x.shape) > num_flatten_dims + 1:
            h = h.reshape(tuple(x.shape[:num_flatten_dims]) + (-1,))
        out = layer(h)
        if activation:
            import paddle_tpu.nn.functional as F

            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def batch_norm(x, **kwargs):
        import paddle_tpu.nn as pnn

        layer = pnn.BatchNorm(int(x.shape[1]))
        _current[-1]._holders.append(layer)
        return layer(x)

    cond = staticmethod(control_flow.cond)
    while_loop = staticmethod(control_flow.while_loop)
    py_func = staticmethod(control_flow.py_func)


nn = _StaticNN()
