"""Static-graph control flow: cond / while_loop (+ static gradients).

Reference surface: python/paddle/static/nn/control_flow.py (cond:723,
while_loop:1313) and python/paddle/base/backward.py gradients.

TPU formulation — no ConditionalBlock / While ops or sub-block descs
(reference: paddle/fluid/operators/controlflow/conditional_block_op.cc,
while_op.cc). A branch/body is TRACED ONCE by running its Python callable
under a nested op recorder; the captured sub-trace replays inside a single
``lax.cond`` / ``lax.while_loop`` recorded as ONE op of the enclosing
program (and of the eager tape). XLA compiles real device-side control
flow — both branches live in one program, the loop carry stays on-chip —
which is what the reference's executor-level sub-block scheduling becomes
on TPU.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework import core as _core
from ..framework.core import Tensor, run_op

__all__ = ["cond", "while_loop", "gradients", "Print", "Assert", "py_func"]


def _flatten(x):
    return jax.tree_util.tree_flatten(
        x, is_leaf=lambda v: isinstance(v, Tensor))


class _SubTrace:
    """One nested recording: ops + output + external tensor inputs."""

    def __init__(self, fn, bound_ids=()):
        self.ops = []
        prev = _core._op_recorder
        _core.set_op_recorder(self._record)
        try:
            self.out = fn()
        finally:
            _core.set_op_recorder(prev)
        produced = set()
        for _n, _f, entries, out_ids, _o in self.ops:
            produced.update(out_ids)
        self.externals = []
        seen = set(bound_ids) | produced
        for _n, _f, entries, _oi, _o in self.ops:
            for kind, a, obj in entries:
                if kind == "t" and a not in seen:
                    self.externals.append(obj)
                    seen.add(a)

    def _record(self, name, fn, inputs, result):
        entries = []
        for i in inputs:
            if isinstance(i, Tensor):
                entries.append(("t", id(i), i))
            else:
                entries.append(("c", np.asarray(i), None))
        outs = result if isinstance(result, (list, tuple)) else [result]
        out_ids = [id(o) for o in outs if isinstance(o, Tensor)]
        self.ops.append((name, fn, entries, out_ids,
                         [o for o in outs if isinstance(o, Tensor)]))

    def replay_into(self, env):
        """Pure replay of the sub-trace over an id->value env (mutates)."""
        for _name, fn, entries, out_ids, _outs in self.ops:
            vals = []
            for kind, a, obj in entries:
                if kind == "c":
                    vals.append(a)
                else:
                    v = env.get(a)
                    vals.append(obj._value if v is None else v)
            res = fn(*vals)
            rl = res if isinstance(res, tuple) else [res]
            for oid, v in zip(out_ids, rl):
                env[oid] = v
        return env

    def leaf_value(self, env, t):
        if isinstance(t, Tensor):
            v = env.get(id(t))
            return t._value if v is None else v
        return t


def _check_same_structure(t_leaves, f_leaves, t_tree, f_tree):
    if t_tree != f_tree:
        raise ValueError(
            "true_fn and false_fn must return the same nest structure, "
            f"got {t_tree} vs {f_tree}")
    for a, b in zip(t_leaves, f_leaves):
        at = isinstance(a, Tensor)
        bt = isinstance(b, Tensor)
        if at != bt:
            raise ValueError("branch outputs mix Tensors and constants")
        if at and (tuple(a.shape) != tuple(b.shape)
                   or str(a.dtype) != str(b.dtype)):
            raise ValueError(
                f"branch output shape/dtype mismatch: "
                f"{a.shape}/{a.dtype} vs {b.shape}/{b.dtype}")


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Run true_fn() or false_fn() by device-side predicate (reference
    control_flow.py:723). Both callables take no arguments and must return
    matching nests of Tensors; both are traced at build time and compiled
    into one ``lax.cond``.

    Example (reference docstring, control_flow.py:723)::

        a = paddle.full([1], 1.0)
        b = paddle.full([1], 2.0)
        out = paddle.static.nn.cond(a < b, lambda: a + b, lambda: a * b)
    """
    if true_fn is None:
        raise ValueError("cond requires a true_fn")
    tt = _SubTrace(true_fn)
    ft = _SubTrace(false_fn) if false_fn is not None else None
    if ft is None:
        if tt.out is not None:
            raise ValueError(
                "cond: false_fn is None so true_fn must return None")
        ft = _SubTrace(lambda: None)

    t_leaves, t_tree = _flatten(tt.out)
    f_leaves, f_tree = _flatten(ft.out)
    _check_same_structure(t_leaves, f_leaves, t_tree, f_tree)
    for a, b in zip(t_leaves, f_leaves):
        if not isinstance(a, Tensor) and a is not b and a != b:
            raise ValueError(
                f"non-Tensor branch outputs must be equal, got {a} vs {b}")
    tensor_slots = [i for i, a in enumerate(t_leaves)
                    if isinstance(a, Tensor)]

    ext, seen = [], set()
    for t in tt.externals + ft.externals:
        if id(t) not in seen:
            ext.append(t)
            seen.add(id(t))
    ext_ids = [id(t) for t in ext]

    def fn(pv, *ext_vals):
        p = jnp.reshape(pv, ()).astype(bool)

        def true_branch(ops_ext):
            env = dict(zip(ext_ids, ops_ext))
            tt.replay_into(env)
            return tuple(tt.leaf_value(env, t_leaves[i])
                         for i in tensor_slots)

        def false_branch(ops_ext):
            env = dict(zip(ext_ids, ops_ext))
            ft.replay_into(env)
            return tuple(ft.leaf_value(env, f_leaves[i])
                         for i in tensor_slots)

        return jax.lax.cond(p, true_branch, false_branch, tuple(ext_vals))

    if not tensor_slots:
        return tt.out  # both branches returned None / equal constants
    outs = run_op("static_cond", fn, [pred] + ext)
    outs = list(outs) if isinstance(outs, tuple) else [outs]
    merged = list(t_leaves)
    for slot, o in zip(tensor_slots, outs):
        merged[slot] = o
    return jax.tree_util.tree_unflatten(t_tree, merged)


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """reference control_flow.py:1313. ``cond(*loop_vars) -> scalar bool
    Tensor``; ``body(*loop_vars) -> new loop_vars`` with identical
    shapes/dtypes. Traced once, compiled into one ``lax.while_loop``.

    Example (reference docstring, control_flow.py:1313)::

        i = paddle.full(shape=[1], fill_value=0, dtype='int64')
        ten = paddle.full(shape=[1], fill_value=10, dtype='int64')
        out = paddle.static.nn.while_loop(
            lambda i: i < ten, lambda i: [i + 1], [i])
    """
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise ValueError("loop_vars must be a non-empty list/tuple")
    lv_leaves, lv_tree = _flatten(list(loop_vars))
    if not all(isinstance(t, Tensor) for t in lv_leaves):
        raise ValueError("loop_vars leaves must be Tensors")
    lv_ids = [id(t) for t in lv_leaves]

    ct = _SubTrace(lambda: cond(*loop_vars), bound_ids=lv_ids)
    if not isinstance(ct.out, Tensor) or int(np.prod(ct.out.shape or [1])) != 1:
        raise ValueError("cond must return a scalar (shape [] or [1]) Tensor")
    bt = _SubTrace(lambda: body(*loop_vars), bound_ids=lv_ids)
    b_out = bt.out if isinstance(bt.out, (list, tuple)) else [bt.out]
    b_leaves, b_tree = _flatten(list(b_out))
    if len(b_leaves) != len(lv_leaves):
        raise ValueError(
            f"body must return as many vars as loop_vars "
            f"({len(b_leaves)} vs {len(lv_leaves)})")
    for a, b in zip(lv_leaves, b_leaves):
        if isinstance(b, Tensor) and (tuple(a.shape) != tuple(b.shape)
                                      or str(a.dtype) != str(b.dtype)):
            raise ValueError(
                f"body output {b.shape}/{b.dtype} does not match loop var "
                f"{a.shape}/{a.dtype}")

    ext, seen = [], set(lv_ids)
    for t in ct.externals + bt.externals:
        if id(t) not in seen:
            ext.append(t)
            seen.add(id(t))
    ext_ids = [id(t) for t in ext]
    n = len(lv_leaves)

    def fn(*vals):
        lvs, exts = vals[:n], vals[n:]

        def cond_f(carry):
            env = dict(zip(lv_ids, carry))
            env.update(zip(ext_ids, exts))
            ct.replay_into(env)
            return jnp.reshape(ct.leaf_value(env, ct.out), ()).astype(bool)

        def body_f(carry):
            env = dict(zip(lv_ids, carry))
            env.update(zip(ext_ids, exts))
            bt.replay_into(env)
            return tuple(bt.leaf_value(env, b) for b in b_leaves)

        return jax.lax.while_loop(cond_f, body_f, tuple(lvs))

    outs = run_op("static_while", fn, list(lv_leaves) + ext)
    outs = list(outs) if isinstance(outs, tuple) else [outs]
    result = jax.tree_util.tree_unflatten(lv_tree, outs)
    return result if isinstance(loop_vars, list) else tuple(result)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference python/paddle/base/backward.py gradients — appends the
    backward computation to the current program and returns grad Variables.

    Here the differentiable-grad path (autograd._grad_create_graph) runs
    ONE grad_replay op; with a program recorder active that op is recorded
    like any other, so Executor.run can fetch the returned grads with feeds
    bound as usual."""
    from ..autograd import grad as _grad

    single = isinstance(inputs, Tensor)
    outs = _grad(targets, inputs, grad_outputs=target_gradients,
                 create_graph=True, allow_unused=True)
    return [outs] if single else list(outs)


def Print(input, first_n=-1, message=None, summarize=20,  # noqa: A002
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both", name=None):
    """Staged print: emits at RUN time, inside compiled programs too
    (reference control_flow.py:2215 Print op). jax.debug.print is the TPU
    lowering — the payload streams back from the device per execution,
    which is exactly the reference Print op's runtime-side-effect
    semantics (a trace-time Python print would fire once)."""
    counter = [0]  # counts RUNTIME executions (host callback), so first_n
    # limits prints per run, not per trace
    # under a program recorder the op ALSO executes eagerly once at build
    # time on placeholder zeros — that execution must not print
    skip_build = [_core._op_recorder is not None]

    def emit(v):
        if first_n < 0 or counter[0] < first_n:
            counter[0] += 1
            prefix = (message + " ") if message else ""
            print(f"{prefix}{np.asarray(v)}", flush=True)

    def fn(v):
        if skip_build[0]:
            skip_build[0] = False
            return v
        jax.debug.callback(emit, v)
        return v

    return run_op("static_print", fn, [input])


def Assert(cond, data=None, summarize=20, name=None):
    """Staged assert: checks at RUN time inside compiled programs
    (reference control_flow.py:59). jax.debug.callback raises
    AssertionError host-side when the predicate is False."""
    datas = list(data) if data is not None else []
    # the build-time eager execution sees placeholder zeros; only REPLAYS
    # (Executor.run / jit) may fire the check
    skip_build = [_core._op_recorder is not None]

    def fn(c, *vals):
        def check(cv, *dv):
            if not bool(np.asarray(cv).reshape(-1).all()):
                payload = "; ".join(
                    np.array2string(np.asarray(d).reshape(-1)[:summarize])
                    for d in dv)
                raise AssertionError(
                    f"static.Assert failed{': ' + payload if payload else ''}")

        if skip_build[0]:
            skip_build[0] = False
            return c
        jax.debug.callback(check, c, *vals)
        return c

    return run_op("static_assert", fn, [cond] + datas)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-side Python op inside a program (reference static/nn/common.py
    py_func). jax.pure_callback is the TPU mechanism: the callable runs on
    host at execution time with materialized arrays; `out` supplies the
    result aval(s).

    backward_func follows the reference contract: it receives
    (inputs..., outputs..., out_grads...) MINUS any tensors listed in
    skip_vars_in_backward_input, and returns grads for the inputs."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(int(s) for s in o.shape),
                                   np.dtype(str(o.numpy().dtype)))
              for o in outs]
    single = not isinstance(out, (list, tuple))
    # the build-time eager pass under a recorder must not run user code on
    # placeholder zeros (side effects / validation errors)
    skip_build = [_core._op_recorder is not None]

    def host(*vals):
        res = func(*[np.asarray(v) for v in vals])
        rl = res if isinstance(res, (list, tuple)) else [res]
        return [np.asarray(r, dtype=s.dtype).reshape(s.shape)
                for r, s in zip(rl, shapes)]

    def call_host(*vals):
        if skip_build[0]:
            skip_build[0] = False
            res = tuple(jnp.zeros(s.shape, s.dtype) for s in shapes)
        else:
            res = jax.pure_callback(host, shapes, *vals)
        return res[0] if single else tuple(res)

    if backward_func is None:
        return run_op("py_func", call_host, list(xs))

    skip_ids = {id(t) for t in (skip_vars_in_backward_input or [])}
    # positions into the (inputs..., outputs...) list handed to backward
    keep_in = [i for i, t in enumerate(xs) if id(t) not in skip_ids]
    keep_out = [j for j, t in enumerate(outs) if id(t) not in skip_ids]
    bwd_shapes = [jax.ShapeDtypeStruct(tuple(int(s) for s in t.shape),
                                       np.dtype(str(t.numpy().dtype)))
                  for t in xs]

    @jax.custom_vjp
    def core(*vals):
        return call_host(*vals)

    def core_fwd(*vals):
        res = call_host(*vals)
        outs_flat = (res,) if single else tuple(res)
        return res, (vals, outs_flat)

    def core_bwd(saved, ct):
        vals, outs_flat = saved
        cts = (ct,) if single else tuple(ct)
        args = ([vals[i] for i in keep_in]
                + [outs_flat[j] for j in keep_out] + list(cts))

        def bhost(*a):
            res = backward_func(*[np.asarray(v) for v in a])
            rl = res if isinstance(res, (list, tuple)) else [res]
            return [np.asarray(r, dtype=s.dtype).reshape(s.shape)
                    for r, s in zip(rl, bwd_shapes)]

        gs = jax.pure_callback(bhost, bwd_shapes, *args)
        return tuple(gs)

    core.defvjp(core_fwd, core_bwd)
    return run_op("py_func", lambda *v: core(*v), list(xs))
