"""Device utilities + memory observability.

Reference analogs: python/paddle/device/__init__.py (get/set_device,
synchronize) and the allocator stat surface
paddle/phi/core/memory/stats.cc + python/paddle/device/cuda/
max_memory_allocated/memory_allocated/... .

TPU formulation: PJRT owns allocation, so the stats come from
Device.memory_stats() (bytes_in_use / peak_bytes_in_use on TPU). Backends
whose PJRT client doesn't publish stats (CPU tests) fall back to summing
jax.live_arrays() per device, with the peak tracked across queries and op
dispatches in this process.
"""

from __future__ import annotations

import numpy as np

import jax

__all__ = [
    "device_count",
    "get_device",
    "set_device",
    "synchronize",
    "memory_allocated",
    "max_memory_allocated",
    "memory_reserved",
    "max_memory_reserved",
    "reset_max_memory_allocated",
    "memory_stats",
]

_peaks: dict[int, int] = {}


def _dev(device=None):
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    return device


def device_count() -> int:
    return jax.device_count()


def get_device() -> str:
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def set_device(device: str):
    # single-process placement is owned by jax; accepted for API parity
    return device


def synchronize(device=None):
    """Block until all dispatched work on the device finishes (reference
    paddle.device.synchronize / cudaDeviceSynchronize)."""
    for a in jax.live_arrays():
        try:
            a.block_until_ready()
        except Exception:
            pass


def _live_bytes(dev) -> int:
    total = 0
    for a in jax.live_arrays():
        try:
            for s in a.addressable_shards:
                if s.device == dev:
                    total += int(np.dtype(a.dtype).itemsize
                                 * int(np.prod(s.data.shape)))
        except Exception:
            continue
    return total


def memory_stats(device=None) -> dict:
    """Raw PJRT allocator stats dict; synthesized from live arrays when the
    backend publishes none (reference stats.cc DeviceMemoryStat*)."""
    d = _dev(device)
    stats = None
    try:
        stats = d.memory_stats()
    except Exception:
        stats = None
    if stats is None:
        in_use = _live_bytes(d)
        peak = max(_peaks.get(d.id, 0), in_use)
        _peaks[d.id] = peak
        stats = {"bytes_in_use": in_use, "peak_bytes_in_use": peak,
                 "synthesized": True}
    return stats


def memory_allocated(device=None) -> int:
    """reference python/paddle/device/cuda/__init__.py memory_allocated."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """reference max_memory_allocated (stats.cc peak tracking).

    PJRT's peak counter cannot be rewound, so after
    reset_max_memory_allocated() this reports the real peak only once it
    exceeds the recorded baseline; until then it reports current usage."""
    d = _dev(device)
    s = memory_stats(device)
    peak = int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))
    base = _reset_baseline.get(d.id)
    if base is not None and peak <= base:
        return int(s.get("bytes_in_use", 0))
    return peak


def memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("peak_bytes_reserved", s.get("peak_bytes_in_use", 0)))


_reset_baseline: dict[int, int] = {}


def reset_max_memory_allocated(device=None):
    d = _dev(device)
    _peaks[d.id] = _live_bytes(d)
    try:
        s = d.memory_stats()
    except Exception:
        s = None
    if s:
        _reset_baseline[d.id] = int(s.get("peak_bytes_in_use", 0))
